"""Fault-injecting hostile-peer harness: scripted bad network behavior.

The liveness layer grew out of hand-rolled raw-socket attackers
(tests/test_liveness.py) and the compact-relay hostile cases out of
in-process fake peers (tests/test_compact.py) — each test rebuilding its
own adversary.  This module is the reusable adversary: a ``HostilePeer``
listens (or dials) like a real node, completes the HELLO exchange, serves
a scripted chain — and injects exactly one family of delivery faults, per
a declarative ``FaultPlan``:

- **stall**: swallow chosen request types silently while answering PINGs,
  staying comfortably under the liveness layer's bar (the sync-stall
  attack supervision exists to beat);
- **trickle**: deliver reply bytes at N bytes/s (the honest-slow peer —
  the false-demotion control case);
- **truncate**: send half of one reply frame, then wedge (mid-frame
  stall: byte progress happened, the frame never completes);
- **drop**: close the socket the instant a chosen request arrives;
- **stale/empty replies**: syntactically perfect BLOCKS frames that never
  advance the requester (the chatty-useless attack).

Faults can be deferred (``serve_before_fault``) so a peer serves the
first N rounds honestly and stalls *mid*-IBD.  The harness counts every
request it sees (``requests``) so tests assert what the victim actually
asked, not just what state it reached.

Test infrastructure, not product: nothing in the node imports this.  It
lives in the package (rather than tests/) so external integration rigs
and future soak drivers can script delivery faults against real nodes
without vendoring test helpers.
"""

from __future__ import annotations

import asyncio
import collections
import dataclasses
import secrets
import struct

from p1_tpu.core.block import Block, merkle_root
from p1_tpu.core.header import BlockHeader
from p1_tpu.core.tx import Transaction
from p1_tpu.node import protocol
from p1_tpu.node.protocol import Hello, MsgType
from p1_tpu.node.transport import SOCKET_TRANSPORT

__all__ = ["FaultPlan", "FloodPlan", "GreedyPeer", "HostilePeer", "make_blocks"]

#: Request types whose replies the fault machinery can intercept — the
#: multi-round fetches request supervision covers, exactly.
_FAULTABLE = frozenset(
    {
        MsgType.GETBLOCKS,
        MsgType.GETHEADERS,
        MsgType.GETBLOCKTXN,
        MsgType.GETMEMPOOL,
        MsgType.GETSNAPSHOT,
    }
)


def make_blocks(
    n: int,
    difficulty: int = 12,
    miner_id: str = "hostile",
    txs_at: dict[int, tuple] | None = None,
) -> list[Block]:
    """Genesis plus ``n`` mined blocks at ``difficulty`` (fixed-rule
    chain, cpu backend — a few ms per block at difficulty 12).  Each
    block carries its height's coinbase plus any extra transactions from
    ``txs_at[height]`` (the caller funds and signs those; validation on
    the victim is the full consensus check, so they must be real)."""
    from p1_tpu.core.genesis import make_genesis
    from p1_tpu.hashx import get_backend
    from p1_tpu.miner import Miner

    miner = Miner(backend=get_backend("cpu"))
    blocks = [make_genesis(difficulty)]
    for height in range(1, n + 1):
        parent = blocks[-1]
        txs = (
            Transaction.coinbase(miner_id, height),
            *(txs_at or {}).get(height, ()),
        )
        draft = BlockHeader(
            version=1,
            prev_hash=parent.block_hash(),
            merkle_root=merkle_root([tx.txid() for tx in txs]),
            timestamp=parent.header.timestamp + 1,
            difficulty=difficulty,
            nonce=0,
        )
        sealed = miner.search_nonce(draft)
        assert sealed is not None
        blocks.append(Block(sealed, txs))
    return blocks


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """One scripted delivery pathology.  Default = a fully honest peer."""

    #: Request types to swallow silently (the stall: liveness-visible,
    #: progress-invisible).
    swallow: frozenset = frozenset()
    #: Close the socket the moment this request type arrives.
    drop_at: MsgType | None = None
    #: Answer this request type with HALF its reply frame, then wedge the
    #: session (no further sends — the stream is desynced by design).
    truncate_at: MsgType | None = None
    #: Deliver reply bytes at this rate (None = full speed).  An honest
    #: slow link, not an attack — the false-demotion control.
    trickle_bps: float | None = None
    #: Sleep this long before every reply (coarse honest-slow knob).
    reply_delay_s: float = 0.0
    #: Blocks (or headers) per sync reply — small values force many
    #: rounds, exercising the per-round progress deadline.
    batch_limit: int = 500
    #: Serve this many faultable requests honestly BEFORE the configured
    #: fault engages — stalls *mid*-IBD instead of at the first ask.
    serve_before_fault: int = 0
    #: Answer sync requests with zero-entry (yet well-formed) replies.
    empty_replies: bool = False
    #: Ignore the locator and re-serve the chain from genesis forever:
    #: non-empty replies that stop advancing the requester after one
    #: round (the stale-branch / chatty-useless attack).
    stale_replies: bool = False
    #: Keep answering keepalive probes (True = stay under the liveness
    #: bar while any of the faults above starve the actual sync).
    answer_pings: bool = True
    #: Advertised HELLO tip height (None = the served chain's real tip).
    #: 0 makes the victim skip the handshake-time sync ask — the
    #: "connected but never triggered" second peer a failover discovers.
    hello_height: int | None = None
    #: MEMPOOL reply shape: the ``more`` flag on served pages.
    mempool_more: bool = False
    #: Answer GETMEMPOOL with EMPTY pages that claim ``more=True``
    #: forever — the round-23 initial-sync starvation: each page is
    #: well-formed and "progress" by frame count, but the tail never
    #: arrives and the pool never advances.  The page supervisor must
    #: read it as a stall (zero NEW txs per page), not as progress.
    mempool_empty_tail: bool = False
    #: Snapshot-serving pathologies (chain/snapshot.py, GETSNAPSHOT).
    #: ``snapshot_lie`` corrupts the SERVED STATE: "balance" inflates
    #: one account by 1000 with the manifest root computed over the lie
    #: (internally consistent — only background revalidation can catch
    #: it), "root" flips a state-root byte (caught at assembly, before
    #: any trust is extended).  ``snapshot_chunks`` truncates the serve:
    #: only the first N chunk requests are answered, then silence (the
    #: crash/stall-mid-transfer profile; compose with ``swallow`` for a
    #: server that never answers at all).
    snapshot_lie: str | None = None
    snapshot_chunks: int | None = None


@dataclasses.dataclass(frozen=True)
class FloodPlan:
    """One scripted resource-exhaustion profile for a ``GreedyPeer``.

    Everything a GreedyPeer sends is PROTOCOL-VALID — well-formed frames,
    real PoW where blocks are involved, decodable transactions.  That is
    the point: the misbehavior score cannot see these floods (nothing is
    malformed), so only the governor's admission budgets, slot caps, and
    write-queue enforcement stand between a handful of greedy peers and
    node memory.  The complement of ``FaultPlan``: faults starve, floods
    drown."""

    #: Push the served chain's blocks over and over (full BLOCK frames —
    #: valid work, instant duplicates after round one): index/dedup
    #: pressure plus raw blocks-class traffic.
    blocks: bool = False
    #: Spray valid-PoW blocks whose parent the victim cannot know (the
    #: connecting block is withheld): orphan-pool pressure.
    orphans: bool = False
    #: Loop these raw TX payload frames (caller signs them; admission
    #: may refuse them for affordability, but each one still costs the
    #: victim a decode + signature check unless dropped at the door).
    tx_frames: tuple = ()
    #: Hammer GETBLOCKS/GETHEADERS with genesis locators — each reply is
    #: a full sync batch the victim must assemble and serve.
    queries: bool = False
    #: The write-queue squat: keep asking for sync batches and NEVER
    #: read the socket — the victim's transport buffer grows until its
    #: write-queue cap drops us (or its memory does not survive).
    squat: bool = False
    #: Hammer the wallet push plane: re-register a rotating SUBSCRIBE
    #: watch set every frame (each replaces the session's subscription
    #: — admission + registry work with zero lasting footprint), capped
    #: by one unverifiable resume cursor the victim answers by dropping
    #: the session, so the reconnect loop then pressures accept too.
    subscribe: bool = False
    #: Frames per burst between event-loop yields.
    burst: int = 32
    #: Sleep between bursts (0 = as fast as the loop allows).
    pause_s: float = 0.0


class GreedyPeer:
    """A protocol-valid flooder: dials the victim, completes a real
    HELLO, then runs its ``FloodPlan`` until stopped — reconnecting
    (counted) whenever the victim drops or bans it, exactly like a real
    attacker would.

    Usage::

        peer = GreedyPeer(make_blocks(12, difficulty=8),
                          plan=FloodPlan(queries=True))
        await peer.start("127.0.0.1", victim.port)
        ...
        await peer.stop()
        assert peer.sent > 0

    ``sent`` counts frames written, ``disconnects`` how often the victim
    (or its ban layer) cut us off, ``refused`` connects that never got a
    HELLO back (an accept-time ban working)."""

    def __init__(
        self,
        blocks: list[Block],
        plan: FloodPlan = FloodPlan(),
        source: str | None = None,
        transport=None,
        rng=None,
    ):
        assert blocks, "need at least a genesis block"
        self.blocks = list(blocks)
        self.plan = plan
        #: Local address to dial FROM (a loopback alias like 127.0.0.66),
        #: so the victim's per-host scoring lands on the attacker, not on
        #: every other localhost peer — same trick as the byzantine suite.
        self.source = source
        #: The transport seam (node/transport.py): real sockets by
        #: default; a netsim facade runs the same flood over in-memory
        #: links (``rng`` then pins the nonce for reproducible traces).
        self.transport = transport if transport is not None else SOCKET_TRANSPORT
        self.nonce = (
            rng.getrandbits(64) if rng is not None else secrets.randbits(64)
        ) | 1
        self.sent = 0
        self.disconnects = 0
        self.refused = 0
        self._task: asyncio.Task | None = None
        self._stopping = False

    async def start(self, host: str, port: int) -> None:
        self._task = asyncio.create_task(self._run(host, port))

    async def stop(self) -> None:
        self._stopping = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    def _frames(self) -> list[bytes]:
        plan = self.plan
        out: list[bytes] = []
        # Stamp pushes from OUR transport clock (virtual under the
        # simulator): the stamp is inside the frame bytes, so a host
        # clock read here would make every simulated flood's trace
        # nondeterministic.
        now = self.transport.clock.wall()
        if plan.blocks:
            out += [
                protocol.encode_block(b, sent_ts=now) for b in self.blocks[1:]
            ]
        if plan.orphans:
            # Withhold the connecting block: everything from [2:] parks
            # in the victim's orphan pool (valid PoW, unknown parent).
            out += [
                protocol.encode_block(b, sent_ts=now) for b in self.blocks[2:]
            ]
        out += list(plan.tx_frames)
        if plan.queries:
            genesis_locator = [self.blocks[0].block_hash()]
            out += [
                protocol.encode_getblocks(genesis_locator),
                protocol.encode_getheaders(genesis_locator),
            ]
        if plan.squat:
            out += [protocol.encode_getblocks([self.blocks[0].block_hash()])]
        if plan.subscribe:
            out += [
                protocol.encode_subscribe([b"flood-item-%d" % i])
                for i in range(4)
            ]
            out += [
                protocol.encode_subscribe([b"flood-item-x"], (1, b"\x55" * 32))
            ]
        assert out, "empty FloodPlan"
        return out

    async def _run(self, host: str, port: int) -> None:
        frames = self._frames()
        hello = protocol.encode_hello(
            Hello(
                self.blocks[0].block_hash(),
                len(self.blocks) - 1,
                0,
                self.nonce,
            )
        )
        while not self._stopping:
            try:
                reader, writer = await self.transport.connect(
                    host,
                    port,
                    local_addr=(self.source, 0) if self.source else None,
                )
            except OSError:
                await asyncio.sleep(0.1)
                continue
            drain_task = None
            try:
                await protocol.write_frame(writer, hello)
                try:
                    await asyncio.wait_for(protocol.read_frame(reader), 5.0)
                except (asyncio.TimeoutError, asyncio.IncompleteReadError):
                    self.refused += 1
                    continue
                if not self.plan.squat:
                    # Keep the socket's inbound side drained so OUR read
                    # buffer never backpressures the victim's replies
                    # into its own send timeout — a squatter does the
                    # opposite on purpose.
                    async def _drain():
                        while True:
                            if not await reader.read(1 << 16):
                                return

                    drain_task = asyncio.create_task(_drain())
                i = 0
                while not self._stopping:
                    for _ in range(self.plan.burst):
                        writer.write(
                            struct.pack(">I", len(frames[i % len(frames)]))
                            + frames[i % len(frames)]
                        )
                        self.sent += 1
                        i += 1
                    await writer.drain()
                    if self.plan.pause_s:
                        await asyncio.sleep(self.plan.pause_s)
                    else:
                        await asyncio.sleep(0)
            except (ConnectionError, OSError, asyncio.TimeoutError):
                self.disconnects += 1
                await asyncio.sleep(0.05)
            finally:
                if drain_task is not None:
                    drain_task.cancel()
                writer.close()


class _Session:
    def __init__(self, reader, writer):
        self.reader = reader
        self.writer = writer
        #: True after a deliberate mid-frame truncation: any further
        #: frame would desync the stream, so sends are suppressed.
        self.wedged = False


class HostilePeer:
    """A scriptable peer serving ``blocks`` under a ``FaultPlan``.

    Usage::

        peer = HostilePeer(make_blocks(30), plan=FaultPlan(
            swallow=frozenset({MsgType.GETBLOCKS})))
        await peer.start()            # victim dials 127.0.0.1:peer.port
        ...
        assert peer.requests[MsgType.GETBLOCKS] >= 1
        await peer.stop()

    ``requests`` counts every decoded frame by type; ``sessions`` counts
    connections accepted or dialed.  ``push`` sends a raw frame to every
    live session (e.g. an unsolicited CBLOCK); ``dial`` connects OUT to
    a victim, covering the inbound-attacker profiles of the liveness
    tests with the same machinery.
    """

    def __init__(
        self,
        blocks: list[Block],
        plan: FaultPlan = FaultPlan(),
        mempool_txs: tuple = (),
        transport=None,
        host: str = "127.0.0.1",
        rng=None,
    ):
        assert blocks, "need at least a genesis block"
        self.blocks = list(blocks)
        self.plan = plan
        self.mempool_txs = tuple(mempool_txs)
        self._pos = {b.block_hash(): i for i, b in enumerate(self.blocks)}
        #: Transport seam: real sockets by default; a netsim facade runs
        #: the identical FaultPlan over simulated links (``host`` is then
        #: the simulated listen address, ``rng`` pins the nonce so two
        #: same-seed runs trace identically).
        self.transport = transport if transport is not None else SOCKET_TRANSPORT
        self.host = host
        self.nonce = (
            rng.getrandbits(64) if rng is not None else secrets.randbits(64)
        ) | 1
        self.port: int | None = None
        self.requests: collections.Counter = collections.Counter()
        self.sessions = 0
        self._server = None
        # Ordered (dicts, not sets): teardown order is part of the
        # deterministic-trace contract under the simulator.
        self._tasks: dict[asyncio.Task, None] = {}
        self._live: dict[_Session, None] = {}
        self._fault_hits = 0
        self._snapshot_records = None  # lazy (manifest, chunks) cache

    def snapshot_records(self) -> tuple[bytes, list[bytes]]:
        """(manifest payload, chunk payloads) of the served chain's tip
        state — with the plan's ``snapshot_lie`` applied.  A "balance"
        lie is INTERNALLY CONSISTENT (the root commits to the lie), so
        every wire-level check passes and only background revalidation
        against the real history can expose it — exactly the attack the
        ASSUMED state exists to contain."""
        if self._snapshot_records is not None:
            return self._snapshot_records
        from p1_tpu.chain import snapshot as chain_snapshot
        from p1_tpu.chain.ledger import Ledger

        ledger = Ledger()
        for block in self.blocks:
            ledger.apply_block(block)
        balances = ledger.snapshot()
        nonces = ledger.nonces_snapshot()
        if self.plan.snapshot_lie == "balance":
            victim = sorted(balances)[0] if balances else "phantom"
            balances[victim] = balances.get(victim, 0) + 1000
        manifest_payload, chunks = chain_snapshot.build_records(
            len(self.blocks) - 1, self.blocks[-1], balances, nonces
        )
        if self.plan.snapshot_lie == "root":
            # Flip one state-root byte INSIDE the manifest payload: the
            # joiner's assembly check must refuse before adopting.
            manifest = chain_snapshot.parse_manifest(manifest_payload)
            bad = bytes([manifest_payload[37] ^ 0x01]) + manifest_payload[38:]
            manifest_payload = manifest_payload[:37] + bad
        self._snapshot_records = (manifest_payload, chunks)
        return self._snapshot_records

    # -- lifecycle -------------------------------------------------------

    async def start(self) -> int:
        self._server = await self.transport.listen(self._on_conn, self.host, 0)
        self.port = self._server.port
        return self.port

    async def stop(self) -> None:
        for task in list(self._tasks):
            task.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks.clear()
        for sess in list(self._live):
            sess.writer.close()
        self._live.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def dial(self, host: str, port: int) -> None:
        """Connect OUT to a victim (the inbound-attacker profile) and run
        the same scripted session over that socket."""
        reader, writer = await self.transport.connect(host, port)
        task = asyncio.create_task(self._session(reader, writer))
        self._tasks[task] = None
        task.add_done_callback(lambda t: self._tasks.pop(t, None))

    async def _on_conn(self, reader, writer) -> None:
        await self._session(reader, writer)

    # -- the scripted session --------------------------------------------

    def _hello(self) -> bytes:
        height = (
            self.plan.hello_height
            if self.plan.hello_height is not None
            else len(self.blocks) - 1
        )
        return protocol.encode_hello(
            Hello(
                self.blocks[0].block_hash(), height, self.port or 0, self.nonce
            )
        )

    async def _session(self, reader, writer) -> None:
        self.sessions += 1
        sess = _Session(reader, writer)
        self._live[sess] = None
        try:
            await self._send(sess, self._hello())
            while True:
                mtype, body = protocol.decode(
                    await protocol.read_frame(reader)
                )
                self.requests[mtype] += 1
                await self._handle(sess, mtype, body)
        except (
            asyncio.IncompleteReadError,
            ConnectionError,
            OSError,
            ValueError,
        ):
            pass  # victim hung up (or stop() closed us) — session over
        finally:
            self._live.pop(sess, None)
            writer.close()

    async def _handle(self, sess: _Session, mtype: MsgType, body) -> None:
        plan = self.plan
        if mtype is MsgType.PING:
            if plan.answer_pings:
                await self._send(sess, protocol.encode_pong(body))
            return
        if mtype is MsgType.GETADDR:
            await self._send(sess, protocol.encode_addr([]))
            return
        if mtype not in _FAULTABLE:
            return  # pushes (BLOCK/TX/...) and late HELLOs: just counted
        fault = self._fault_for(mtype)
        if fault == "swallow":
            return
        if fault == "drop":
            sess.writer.close()
            return
        payload = self._answer(mtype, body)
        if payload is not None:
            await self._send(sess, payload, fault=fault)

    def _fault_for(self, mtype: MsgType) -> str | None:
        plan = self.plan
        if mtype in plan.swallow:
            hit = "swallow"
        elif plan.drop_at is mtype:
            hit = "drop"
        elif plan.truncate_at is mtype:
            hit = "truncate"
        else:
            return None
        self._fault_hits += 1
        if self._fault_hits <= plan.serve_before_fault:
            return None  # still in the honest prefix: stall mid-IBD later
        return hit

    def _after(self, locator: list[bytes]) -> list[Block]:
        start = 0
        if self.plan.stale_replies:
            start = 1  # ignore the locator: re-serve from genesis forever
        else:
            for h in locator:
                i = self._pos.get(h)
                if i is not None:
                    start = i + 1
                    break
        return self.blocks[start : start + self.plan.batch_limit]

    def _answer(self, mtype: MsgType, body) -> bytes | None:
        plan = self.plan
        if mtype is MsgType.GETBLOCKS:
            blocks = [] if plan.empty_replies else self._after(body)
            return protocol.encode_blocks(blocks)
        if mtype is MsgType.GETHEADERS:
            blocks = [] if plan.empty_replies else self._after(body)
            return protocol.encode_headers([b.header for b in blocks])
        if mtype is MsgType.GETMEMPOOL:
            if plan.mempool_empty_tail:
                return protocol.encode_mempool([], more=True)
            raws = [tx.serialize() for tx in self.mempool_txs]
            return protocol.encode_mempool(raws, more=plan.mempool_more)
        if mtype is MsgType.GETBLOCKTXN:
            bhash, indices = body
            i = self._pos.get(bhash)
            block = self.blocks[i] if i is not None else None
            if block is None or indices[-1] >= len(block.txs):
                return None
            return protocol.encode_blocktxn(
                bhash, [block.txs[j].serialize() for j in indices]
            )
        if mtype is MsgType.GETSNAPSHOT:
            start, count = body
            manifest_payload, chunks = self.snapshot_records()
            if count == 0:
                return protocol.encode_snapshot_manifest(manifest_payload)
            limit = (
                self.plan.snapshot_chunks
                if self.plan.snapshot_chunks is not None
                else len(chunks)
            )
            if start >= limit:
                return None  # truncated serve: stall mid-transfer
            return protocol.encode_snapshot_chunks(
                start, chunks[start : min(start + count, limit)]
            )
        return None

    # -- delivery --------------------------------------------------------

    async def push(self, payload: bytes) -> int:
        """Send one raw frame to every live session (unsolicited pushes:
        CBLOCK, BLOCK, TX...).  Returns the number of sessions reached."""
        n = 0
        for sess in list(self._live):
            try:
                await self._send(sess, payload)
                n += 1
            except (ConnectionError, OSError):
                pass
        return n

    async def _send(
        self, sess: _Session, payload: bytes, fault: str | None = None
    ) -> None:
        if sess.wedged:
            return
        plan = self.plan
        if plan.reply_delay_s:
            await asyncio.sleep(plan.reply_delay_s)
        frame = struct.pack(">I", len(payload)) + payload
        if fault == "truncate":
            sess.wedged = True
            sess.writer.write(frame[: max(1, len(frame) // 2)])
            await sess.writer.drain()
            return
        if plan.trickle_bps:
            # ~20 writes/s at the configured byte rate.
            chunk = max(1, int(plan.trickle_bps * 0.05))
            for off in range(0, len(frame), chunk):
                sess.writer.write(frame[off : off + chunk])
                await sess.writer.drain()
                await asyncio.sleep(0.05)
            return
        sess.writer.write(frame)
        await sess.writer.drain()
