"""Fleet provisioning: snapshot-cold-started replicas (`p1 serve
--bootstrap`) and the upstream pull loop that keeps them current.

The north star needs read capacity to be ELASTIC: PR 18's push plane
proved one replica carries 100k live wallet sessions, but adding a
second replica still meant a full IBD (or out-of-band store copies).
This module closes that gap with the two Bitcoin-lineage designs the
repo already trusts end to end:

- **assumeUTXO-analog snapshots (PR 9/17)**: ``bootstrap_store`` pulls
  a state snapshot over the supervised GETSNAPSHOT path, verifying the
  manifest and every chunk digest AS THEY ARRIVE (client.get_snapshot),
  and pins the snapshot's anchor block to a PoW-verified header
  skeleton fetched first — a snapshot server lying about height, root,
  or content is DEMOTED exactly as in PR 9 and the next peer is tried.
- **BIP157-analog commitment chains (PR 18)**: the filter headers for
  the adopted window [0..base] are fetched from the peer and, when a
  second bootstrap peer is available, cross-checked and adjudicated via
  the hash-pinned block at the first divergence (client._adjudicate) —
  the same machinery a watching wallet uses, applied at provision time.

What lands on disk next to the store:

- ``<store>.snapshot`` — the CRC-framed snapshot file (chain/snapshot).
- ``<store>.bootbase`` — this module's sidecar: the base height, the
  PoW-verified headers 1..base, and the adopted filter headers 0..base,
  digest-trailed and written atomically (tmp + rename).  ReplicaView
  (node/queryplane.py) reads it at attach and seeds heights 1..base as
  ADOPTED entries: headers served, bodies/filters refused honestly —
  the same contract as a pruned archive.
- the chain store itself — bodies for (base..tip] fetched by locator
  rounds, each pinned to the verified skeleton by hash and checked
  against its merkle commitment before the append.

Crash model: every stage is resumable.  The sidecars are atomic
(rename) so a crash leaves either nothing or a whole file; a torn or
absent ``.bootbase`` restarts the snapshot stages cleanly, an intact
one skips straight to the body fill, and the body fill itself resumes
from whatever the store already holds (the locator does the dedup).

``UpstreamSync`` is the serving-time half: a supervised locator-pull
loop against the upstream peers that appends new PoW-checked blocks to
the replica's own store (this process is the store's writer — the
ReplicaView refresh loop picks them up and the push plane notifies).
Appends run off-loop (``asyncio.to_thread``): a replica mid-push must
not stall its sessions on an fsync.
"""

from __future__ import annotations

import asyncio
import hashlib
import os
import struct
from pathlib import Path

from p1_tpu.core.genesis import make_genesis
from p1_tpu.core.header import HEADER_SIZE, meets_target
from p1_tpu.node import protocol
from p1_tpu.node.protocol import MsgType

__all__ = [
    "BootstrapError",
    "UpstreamSync",
    "bootstrap_store",
    "read_bootbase",
    "write_bootbase",
]

#: Bootbase sidecar format tag (bump on layout change).
BOOTBASE_MAGIC = b"P1TPUBB1"

#: Network failure shapes that mean "rotate peers", never "peer lies".
NET_ERRORS = (
    ConnectionError,
    OSError,
    asyncio.IncompleteReadError,
    asyncio.TimeoutError,
    TimeoutError,
)


class BootstrapError(ValueError):
    """Cold-start provisioning failed for every offered peer — the
    caller gets the full story (who was tried, who was demoted, why)
    in one message instead of the last peer's symptom."""


# -- the .bootbase sidecar -------------------------------------------------


def _bootbase_path(store_path) -> Path:
    p = Path(store_path)
    return p.with_name(p.name + ".bootbase")


def write_bootbase(store_path, headers: list[bytes], fheaders: list[bytes]) -> Path:
    """Atomically write the adopted-prefix sidecar: ``headers`` are the
    80-byte serialized headers for heights 1..base (genesis excluded —
    it is local knowledge), ``fheaders`` the 32-byte filter headers for
    heights 0..base.  Layout: magic, u32 base, headers, filter headers,
    and a sha256 digest over everything before it — a torn write can
    never parse."""
    base = len(headers)
    if len(fheaders) != base + 1:
        raise ValueError("bootbase needs filter headers for 0..base")
    payload = BOOTBASE_MAGIC + struct.pack(">I", base)
    payload += b"".join(headers) + b"".join(fheaders)
    payload += hashlib.sha256(payload).digest()
    path = _bootbase_path(store_path)
    tmp = path.with_name(path.name + ".tmp")
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def read_bootbase(store_path):
    """Parse the sidecar next to ``store_path``; returns ``(base,
    headers, fheaders)`` or None when absent/torn/corrupt (a bad
    sidecar restarts the bootstrap stages — it never half-loads)."""
    path = _bootbase_path(store_path)
    try:
        raw = path.read_bytes()
    except (FileNotFoundError, IsADirectoryError):
        return None
    if len(raw) < len(BOOTBASE_MAGIC) + 4 + 32:
        return None
    if raw[: len(BOOTBASE_MAGIC)] != BOOTBASE_MAGIC:
        return None
    if hashlib.sha256(raw[:-32]).digest() != raw[-32:]:
        return None
    (base,) = struct.unpack_from(">I", raw, len(BOOTBASE_MAGIC))
    off = len(BOOTBASE_MAGIC) + 4
    want = off + base * HEADER_SIZE + (base + 1) * 32 + 32
    if len(raw) != want:
        return None
    headers = [
        raw[off + i * HEADER_SIZE : off + (i + 1) * HEADER_SIZE]
        for i in range(base)
    ]
    off += base * HEADER_SIZE
    fheaders = [raw[off + i * 32 : off + (i + 1) * 32] for i in range(base + 1)]
    return base, headers, fheaders


# -- cold start ------------------------------------------------------------


async def _blocks_round(reader, writer, locator):
    from p1_tpu.node.client import _read_msg

    await protocol.write_frame(writer, protocol.encode_getblocks(locator))
    while True:
        mtype, body = await _read_msg(reader, writer)
        if mtype is MsgType.BLOCKS:
            return body


async def bootstrap_store(
    store_path,
    peers,
    difficulty: int,
    *,
    retarget=None,
    stall_timeout_s: float = 15.0,
    snapshot_timeout_s: float = 120.0,
    progress=None,
) -> dict:
    """Cold-start a replica store at ``store_path`` from ``peers`` (a
    list of ``(host, port)``); returns a report dict with the measured
    stages (the PERF.md cold-start figure reads them).  Stages:

    1. PoW-verified header skeleton (supervised ``get_headers`` across
       all peers, then ``replay_fast`` + the genesis pin).
    2. Snapshot: manifest + chunk-verified payloads from the first peer
       that serves one, its anchor pinned to the skeleton — a server
       whose snapshot fails ANY check is demoted and the next is tried.
       No snapshot anywhere degrades to a full body fill from genesis
       (an IBD — slower, never wrong).
    3. Adopted filter headers [0..base], genesis anchor recomputed
       locally, cross-checked against a second peer when one is live
       (disagreement adjudicated via the hash-pinned block; the proven
       liar is demoted).  Then the ``.bootbase`` sidecar lands
       atomically.
    4. Body fill (base..skeleton tip] by locator rounds into the local
       ChainStore — each block hash-pinned to the skeleton and
       merkle-checked; resumes from whatever a previous (crashed) run
       already appended.

    A valid ``.bootbase`` from a previous run whose base hash still
    sits on the skeleton skips stages 2–3 (the crash-resume path)."""
    import time as _time

    from p1_tpu.chain import snapshot as chain_snapshot
    from p1_tpu.chain.chain import locator_hashes
    from p1_tpu.chain.filters import (
        GENESIS_FILTER_HEADER,
        block_filter,
        filter_hash,
        next_filter_header,
    )
    from p1_tpu.chain.replay import replay_fast
    from p1_tpu.chain.store import ChainStore
    from p1_tpu.node.client import (
        CommitmentViolation,
        _adjudicate,
        _session,
        get_filter_headers,
        get_headers,
        get_snapshot,
    )

    def _say(msg: str) -> None:
        if progress is not None:
            progress(msg)

    targets = [tuple(p) for p in peers]
    if not targets:
        raise BootstrapError("bootstrap needs at least one peer")
    demoted: list[tuple[tuple, str]] = []
    t0 = _time.perf_counter()
    report: dict = {"store": str(store_path), "peers": len(targets)}

    # -- 1. verified header skeleton --------------------------------------
    genesis = make_genesis(difficulty, retarget)
    _say("fetching header skeleton")
    headers = await get_headers(
        targets[0][0],
        targets[0][1],
        difficulty,
        timeout=max(60.0, stall_timeout_s * 8),
        retarget=retarget,
        stall_timeout_s=stall_timeout_s,
        fallback_peers=targets[1:],
    )
    if headers[0].block_hash() != genesis.block_hash():
        raise BootstrapError("header skeleton does not start at our genesis")
    rep = replay_fast(headers, retarget=retarget)
    if not rep.valid:
        raise BootstrapError(
            f"header skeleton fails verification at index {rep.first_invalid}"
        )
    hashes = [h.block_hash() for h in headers]
    tip = len(hashes) - 1
    report["skeleton_tip"] = tip
    report["headers_s"] = round(_time.perf_counter() - t0, 3)

    def _alive():
        down = {t for t, _ in demoted}
        return [t for t in targets if t not in down]

    def _demote(peer, why: str) -> None:
        demoted.append((tuple(peer), why))
        _say(f"demoted {peer[0]}:{peer[1]}: {why}")

    # -- 2+3. snapshot + adopted filter headers (or resume) ----------------
    base = 0
    fheaders: list[bytes] = []
    resumed = False
    bb = read_bootbase(store_path)
    if bb is not None:
        rbase, rheaders, rfheaders = bb
        from p1_tpu.core.header import BlockHeader

        if rbase <= tip and (
            not rheaders
            or BlockHeader.deserialize(rheaders[-1]).block_hash()
            == hashes[rbase]
        ):
            base, fheaders, resumed = rbase, rfheaders, True
            _say(f"resuming from existing bootbase (base {base})")
        # A sidecar off the verified skeleton (the snapshot peer's
        # branch lost, or garbage): restart the snapshot stages.
    if not resumed:
        t_snap = _time.perf_counter()
        snap_path = Path(store_path).with_name(Path(store_path).name + ".snapshot")
        manifest = None
        for peer in list(_alive()):
            try:
                got = await get_snapshot(
                    *peer,
                    difficulty,
                    timeout=snapshot_timeout_s,
                    retarget=retarget,
                    out_path=snap_path,
                )
            except NET_ERRORS:
                continue  # unreachable: not evidence, try the next
            except ValueError as e:
                _demote(peer, f"snapshot failed verification: {e}")
                continue
            if got is None:
                continue  # serves no snapshot: honest, just unhelpful
            m_height, m_bhash = got.height, got.block_hash
            if m_height < 1 or m_height > tip or hashes[m_height] != m_bhash:
                _demote(peer, "snapshot anchor is not on the verified chain")
                continue
            manifest, snap_peer = got, peer
            break
        if manifest is not None:
            base = manifest.height
            _say(f"snapshot verified at height {base}")
            # Adopted filter headers [0..base] from the snapshot peer.
            try:
                fheaders = await get_filter_headers(
                    *snap_peer, 0, base + 1, difficulty, retarget=retarget
                )
            except NET_ERRORS as e:
                raise BootstrapError(
                    f"snapshot peer vanished serving filter headers: {e!r}"
                ) from e
            if len(fheaders) != base + 1:
                raise BootstrapError(
                    "snapshot peer refuses filter headers for its own window"
                )
            want0 = next_filter_header(
                filter_hash(block_filter(genesis)), GENESIS_FILTER_HEADER
            )
            if fheaders[0] != want0:
                _demote(snap_peer, "commits a wrong genesis filter header")
                raise BootstrapError(
                    f"{snap_peer[0]}:{snap_peer[1]} commits a wrong genesis"
                    " filter header"
                )
            # Cross-check the adopted tip against a second live peer —
            # the wallet-grade agreement test, applied at provision.
            for other in _alive():
                if other == snap_peer:
                    continue
                try:
                    theirs = await get_filter_headers(
                        *other, base, 1, difficulty, retarget=retarget
                    )
                except NET_ERRORS + (ValueError,):
                    continue
                if not theirs or theirs[0] == fheaders[base]:
                    break  # corroborated (or honestly short)
                try:
                    verdict = await _adjudicate(
                        fheaders, other, hashes, base,
                        difficulty, retarget, None,
                    )
                except NET_ERRORS + (ValueError,):
                    continue
                if verdict in ("other", "both"):
                    _demote(other, "filter-header chain disproven")
                if verdict in ("self", "both"):
                    _demote(snap_peer, "filter-header chain disproven")
                    raise CommitmentViolation(
                        f"{snap_peer[0]}:{snap_peer[1]} serves forged filter"
                        f" headers (proven vs {other[0]}:{other[1]})"
                    )
                break
            write_bootbase(
                store_path,
                [h.serialize() for h in headers[1 : base + 1]],
                fheaders,
            )
        else:
            _say("no peer serves a snapshot — falling back to a full fill")
        report["snapshot_s"] = round(_time.perf_counter() - t_snap, 3)
    report["base"] = base
    report["resumed"] = resumed

    # -- 4. body fill (base..tip] ------------------------------------------
    t_fill = _time.perf_counter()
    pos = {bh: i for i, bh in enumerate(hashes)}
    store = ChainStore(store_path, fsync=False)
    store.acquire()
    fetched = 0
    try:
        # Resume point: whatever the store (plus the adopted base)
        # already covers — a fresh ReplicaView indexes both.
        from p1_tpu.node.queryplane import ReplicaView

        view = ReplicaView(store_path, difficulty, retarget)
        try:
            while view.tip_height < tip and _alive():
                peer = _alive()[0]
                try:
                    async with _session(
                        *peer,
                        difficulty,
                        retarget,
                        handshake_timeout=stall_timeout_s,
                    ) as (reader, writer, _):
                        stalled = False
                        while view.tip_height < tip:
                            locator = locator_hashes(list(view._main))
                            blocks = await asyncio.wait_for(
                                _blocks_round(reader, writer, locator),
                                stall_timeout_s,
                            )
                            new = 0
                            for block in blocks:
                                bhash = block.block_hash()
                                h = pos.get(bhash)
                                if h is None or h > tip:
                                    break  # off/past the skeleton: done
                                if view.hash_at(h) == bhash:
                                    continue  # already held
                                if block.header.prev_hash != hashes[h - 1]:
                                    raise ValueError(
                                        "block does not link to the skeleton"
                                    )
                                if not block.merkle_ok():
                                    raise ValueError(
                                        "block fails its merkle commitment"
                                    )
                                await asyncio.to_thread(
                                    store.append, block, h
                                )
                                new += 1
                                fetched += 1
                            if new:
                                await asyncio.to_thread(store.sync)
                                view.refresh()
                            else:
                                stalled = True
                                break
                        if stalled and view.tip_height < tip:
                            _demote(peer, "stopped serving bodies")
                except NET_ERRORS:
                    _demote(peer, "dead/stalled session during body fill")
                except ValueError as e:
                    _demote(peer, f"served bad blocks: {e}")
            if view.tip_height < tip:
                raise BootstrapError(
                    f"body fill stalled at height {view.tip_height}/{tip}; "
                    f"demoted: {[(f'{h}:{p}', why) for (h, p), why in demoted]}"
                )
        finally:
            view.close()
    finally:
        store.close()
    report["blocks_fetched"] = fetched
    report["fill_s"] = round(_time.perf_counter() - t_fill, 3)
    report["tip"] = tip
    report["demoted"] = [
        {"peer": f"{h}:{p}", "why": why} for (h, p), why in demoted
    ]
    report["cold_start_s"] = round(_time.perf_counter() - t0, 3)
    _say(
        f"cold start complete: base {base}, tip {tip}, "
        f"{fetched} bodies in {report['cold_start_s']}s"
    )
    return report


# -- serving-time upstream pull --------------------------------------------


class UpstreamSync:
    """Keeps a bootstrapped replica current: a supervised locator-pull
    loop against the upstream peers, appending new blocks to the
    replica's OWN store (this process is the writer; the ReplicaView
    refresh loop indexes the appends and the push plane notifies).

    Verification before every append: the block must link to a header
    the view already holds, carry the chain's proof of work (fixed
    difficulty pinned when ``retarget`` is None — the same self-attest
    scope as ``client.watch``), and pass its merkle commitment.  A peer
    violating any of those is demoted permanently; a dead or stalled
    one just rotates.  Appends and fsyncs run in a worker thread so a
    pull burst never stalls the serving loop mid-push."""

    def __init__(
        self,
        store,
        view,
        peers,
        difficulty: int,
        *,
        retarget=None,
        poll_interval_s: float = 1.0,
        stall_timeout_s: float = 15.0,
    ):
        self.store = store
        self.view = view
        self.targets = [tuple(p) for p in peers]
        self.difficulty = difficulty
        self.retarget = retarget
        self.poll_interval_s = poll_interval_s
        self.stall_timeout_s = stall_timeout_s
        self.demoted: set[tuple] = set()
        self.pulled = 0
        self.rounds = 0
        self.stalls = 0
        self._ti = 0
        self._task: asyncio.Task | None = None

    def _append_batch(self, blocks: list) -> None:
        for block, h in blocks:
            self.store.append(block, h)
        self.store.sync()

    async def poll_once(self) -> int:
        """One pull round against the current upstream; returns blocks
        appended.  Rotates to the next peer on failure."""
        from p1_tpu.chain.chain import locator_hashes
        from p1_tpu.node.client import _session

        live = [t for t in self.targets if t not in self.demoted]
        if not live:
            raise ConnectionError("all upstream peers demoted")
        peer = live[self._ti % len(live)]
        self.rounds += 1
        try:
            async with _session(
                *peer,
                self.difficulty,
                self.retarget,
                handshake_timeout=self.stall_timeout_s,
            ) as (reader, writer, _):
                total = 0
                while True:
                    self.view.refresh()
                    blocks = await asyncio.wait_for(
                        _blocks_round(
                            reader, writer, locator_hashes(list(self.view._main))
                        ),
                        self.stall_timeout_s,
                    )
                    batch: list = []
                    for block in blocks:
                        bhash = block.block_hash()
                        if bhash in self.view._entries:
                            continue
                        parent = self.view._entries.get(block.header.prev_hash)
                        if parent is None:
                            continue  # orphan: wait for its parent
                        if not meets_target(bhash, block.header.difficulty) or (
                            self.retarget is None
                            and block.header.difficulty != self.difficulty
                        ):
                            raise ValueError("block without the chain's PoW")
                        if not block.merkle_ok():
                            raise ValueError("block fails merkle commitment")
                        batch.append((block, parent.height + 1))
                    if not batch:
                        return total
                    await asyncio.to_thread(self._append_batch, batch)
                    self.view.refresh()
                    total += len(batch)
                    self.pulled += len(batch)
        except NET_ERRORS:
            self.stalls += 1
            self._ti += 1
            return 0
        except ValueError:
            self.demoted.add(peer)
            self._ti += 1
            return 0

    async def run(self) -> None:
        """The serve-time loop (`p1 serve --bootstrap` spawns this as a
        task): poll, sleep, repeat until cancelled."""
        while True:
            await self.poll_once()
            await asyncio.sleep(self.poll_interval_s)

    def start(self) -> None:
        self._task = asyncio.create_task(self.run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    def snapshot(self) -> dict:
        return {
            "upstreams": len(self.targets),
            "demoted": len(self.demoted),
            "pulled": self.pulled,
            "rounds": self.rounds,
            "stalls": self.stalls,
        }
