"""Command line: run nodes, miners, replays, and benchmarks.

SURVEY.md §7 step 7 — every benchmark config reproducible from one command
(BASELINE.json:6-12):

  config 1/2: p1 mine   --difficulty 16 --blocks 10 --backend jax
  config 3:   p1 replay --n 10000 --difficulty 12
  config 4:   p1 net    --nodes 4 --difficulty 20 --duration 10
  one node:   p1 node   --port 9444 --peers host:port --mine
  headline:   p1 bench

(``p1`` = ``python -m p1_tpu``.)  Structured logs go to stderr; metric
output is JSON on stdout, one object per line, so the driver and shell
pipelines can consume it.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import statistics
import sys
import time


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--difficulty", type=int, default=16)
    p.add_argument(
        "--backend",
        default="cpu",
        help="hash backend registry name (cpu, numpy, jax, sharded, ...)",
    )
    p.add_argument("--batch", type=int, default=None, help="device batch override")
    p.add_argument("--chunk", type=int, default=None, help="miner abort granularity")


def _add_retarget(p: argparse.ArgumentParser) -> None:
    """Chain-identity flags for opt-in difficulty retargeting.  They ride
    every command that selects a chain (node/net and the wallet tools):
    the rule is committed into genesis, so a client that omits them cannot
    even handshake with a retargeting node."""
    p.add_argument(
        "--retarget-window",
        type=int,
        default=0,
        help="adjust difficulty every N blocks (0 = fixed difficulty; "
        "all chain participants must agree — the rule is part of the "
        "chain's genesis identity)",
    )
    p.add_argument(
        "--target-spacing",
        type=int,
        default=0,
        help="target seconds per block for retargeting (set together "
        "with --retarget-window)",
    )


def _fee_arg(value: str):
    """--fee: an integer or the literal 'auto' — validated by argparse so
    a typo is a usage error, not a runtime failure after other work."""
    if value == "auto":
        return value
    try:
        return int(value)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"fee must be an integer or 'auto', got {value!r}"
        )


def _retarget_rule(args):
    """The ``RetargetRule`` selected by the flags, or None (fixed) — flag
    validation lives in ``RetargetRule.from_params``; here only the
    ValueError→SystemExit mapping."""
    from p1_tpu.core.retarget import RetargetRule

    try:
        return RetargetRule.from_params(
            getattr(args, "retarget_window", 0),
            getattr(args, "target_spacing", 0),
        )
    except ValueError as e:
        raise SystemExit(str(e))


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="p1_tpu", description="TPU-native proof-of-work blockchain node"
    )
    parser.add_argument("-v", "--verbose", action="store_true")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("mine", help="mine N blocks from genesis (configs 1/2)")
    _add_common(p)
    p.add_argument("--blocks", type=int, default=10)
    p.add_argument(
        "--profile",
        default=None,
        metavar="DIR",
        help="capture a jax.profiler device trace of the mining loop into "
        "DIR (view with tensorboard or xprof)",
    )

    p = sub.add_parser(
        "sweep", help="difficulty sweep: time-to-block scaling (config 2)"
    )
    _add_common(p)
    p.add_argument(
        "--difficulties",
        default="16:25",
        help="half-open range LO:HI (e.g. 16:25) or comma list (16,20,24)",
    )
    p.add_argument("--blocks", type=int, default=5, help="blocks per difficulty")

    p = sub.add_parser("replay", help="generate+verify a header chain (config 3)")
    _add_common(p)
    p.add_argument("--n", type=int, default=10_000)
    p.add_argument(
        "--method",
        choices=["host", "native", "device", "both", "all"],
        default="both",
        help="verification engine(s): host=hashlib oracle, native=C++ "
        "SHA-NI, device=one-dispatch lax.scan; both=host+device, all=every "
        "engine",
    )
    p.add_argument("--out", default=None, help="write generated headers here")
    p.add_argument("--verify", default=None, help="verify this header file instead")
    _add_retarget(p)

    p = sub.add_parser("node", help="run one p2p node")
    _add_common(p)
    p.add_argument(
        "--platform",
        default=None,
        help="pin the JAX platform (e.g. cpu) before backend init — the "
        "axon sitecustomize overrides the JAX_PLATFORMS env var, so an "
        "explicit pin is the only reliable way to force CPU",
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9444)
    p.add_argument("--peers", nargs="*", default=[], help="host:port ...")
    p.add_argument("--no-mine", action="store_true")
    p.add_argument(
        "--miner-id",
        default=None,
        help="coinbase recipient id (default: random per process)",
    )
    p.add_argument("--store", default=None, help="chain persistence path")
    p.add_argument(
        "--revalidate-store",
        action="store_true",
        help="re-run full stateless validation (PoW, merkle, Ed25519) "
        "over the stored chain at boot instead of the trusted fast "
        "resume (the store is this node's own validated, flocked log)",
    )
    p.add_argument(
        "--verify-workers",
        type=int,
        default=0,
        help="worker threads for batched Ed25519 verification on the "
        "untrusted validation paths (--revalidate-store, deep sync); "
        "0 = auto (P1_VERIFY_WORKERS env, else cpu count).  With the "
        "cryptography wheel, threads verify in parallel (OpenSSL "
        "releases the GIL); never changes validation outcomes",
    )
    p.add_argument(
        "--pipeline-workers",
        type=int,
        default=0,
        help="staged block pipeline: off-loop worker lanes for the "
        "validate and store stages (node/pipeline.py).  0 = inline "
        "historical node (every stage on the event loop); N >= 1 moves "
        "batched signature pre-verification and the whole fsync chain "
        "onto worker threads and, when --verify-workers is 0, sizes "
        "the verify pool to N.  Never changes validation outcomes or "
        "wire behavior, only where the CPU/IO cost is paid",
    )
    p.add_argument(
        "--sig-backend",
        default="auto",
        choices=["auto", "cryptography", "native", "fallback", "device"],
        help="Ed25519 verification backend: auto resolves the ladder "
        "(cryptography wheel > native C++ engine > pure-Python "
        "fallback); native/cryptography pin a rung (degrading with a "
        "warning if unavailable); fallback forces pure Python; device "
        "routes batches through the JAX mesh multi-scalar "
        "multiplication.  Never changes validation outcomes, only the "
        "cost model",
    )
    p.add_argument(
        "--store-degraded-exit",
        action="store_true",
        help="exit (code 4) on the first store write failure instead of "
        "the default degraded serve-only mode (which keeps answering "
        "headers/blocks/proof queries while retrying the disk with "
        "backoff) — for operators who prefer a supervisor restart",
    )
    p.add_argument("--duration", type=float, default=None, help="exit after N s")
    p.add_argument(
        "--deadline",
        default=None,
        help="unix time to stop mining at (overrides --duration; lets a "
        "multi-process net quiesce simultaneously), or 'stdin' to print a "
        "ready line and read the deadline from stdin once the parent has "
        "seen every node come up (interpreter startup on a loaded host "
        "can cost many seconds, so parent-computed wall times are unsafe)",
    )
    p.add_argument("--status-interval", type=float, default=10.0)
    p.add_argument(
        "--no-compact-gossip",
        action="store_true",
        help="push full BLOCK frames instead of compact blocks (local "
        "preference; compact and full nodes interoperate)",
    )
    p.add_argument(
        "--mempool-ttl",
        type=float,
        default=3600.0,
        help="drop pool transactions older than this many seconds "
        "(hygiene for unmineable spends; 0 = never)",
    )
    p.add_argument(
        "--target-peers",
        type=int,
        default=0,
        help="peer-discovery out-degree: dial addresses learned via "
        "GETADDR/ADDR gossip until this many connections hold (0 = only "
        "the configured --peers; one seed peer bootstraps the rest)",
    )
    p.add_argument(
        "--handshake-timeout",
        type=float,
        default=10.0,
        help="seconds a new connection gets to complete HELLO before "
        "being reaped (liveness layer)",
    )
    p.add_argument(
        "--ping-interval",
        type=float,
        default=60.0,
        help="probe a peer with PING after this many seconds of silence; "
        "any received frame counts as liveness",
    )
    p.add_argument(
        "--pong-timeout",
        type=float,
        default=20.0,
        help="seconds of continued silence after a PING probe before the "
        "peer is evicted and its slot reused",
    )
    p.add_argument(
        "--sync-stall-timeout",
        type=float,
        default=10.0,
        help="progress deadline on an in-flight chain/mempool sync: a "
        "peer that advances nothing (blocks accepted, pages consumed — "
        "not mere liveness) within this window is demoted and the "
        "request re-issued to another peer (0 disables supervision)",
    )
    p.add_argument(
        "--sync-attempts",
        type=int,
        default=8,
        help="failover budget per catch-up episode: consecutive "
        "no-progress re-issues before the node stops chasing and waits "
        "for a fresh sync trigger (progress resets the budget)",
    )
    p.add_argument(
        "--mem-watermark-mb",
        type=float,
        default=0.0,
        help="overload high watermark in MB on the node's accounted "
        "memory gauge (resident chain bodies + pending pool + peer "
        "write buffers): above it the node SHEDs low-priority gossip "
        "and mempool pages and pauses mining while consensus-critical "
        "headers/blocks/proof service keeps running; back to NORMAL "
        "below 80%% of the mark (0 = no shedding)",
    )
    p.add_argument(
        "--body-cache",
        type=int,
        default=0,
        help="memory-bounded operation: keep only the last N main-chain "
        "block BODIES resident (headers/metadata always stay), evicting "
        "older bodies once durably in the store and refetching on "
        "demand — bounds RSS at O(N) instead of O(chain); 0 = fully "
        "resident (requires --store)",
    )
    p.add_argument(
        "--store-segment-mb",
        type=float,
        default=0.0,
        help="segmented store layout (chain/segstore.py): shard the "
        "append-only log into bounded segment files of this many MB "
        "(per-segment fsck/compaction/pruning; a single-file store "
        "upgrades losslessly on the first writer acquire); 0 keeps the "
        "store's existing layout",
    )
    p.add_argument(
        "--prune",
        type=int,
        default=0,
        metavar="KEEP_BLOCKS",
        help="pruned mode: discard block-body segments below the latest "
        "snapshot checkpoint, keeping at least KEEP_BLOCKS recent "
        "bodies — headers/filters/snapshots keep serving, block-sync "
        "requests into the pruned range are refused without "
        "disconnecting (0 = archive node; implies a segmented store)",
    )
    p.add_argument(
        "--snapshot-interval",
        type=int,
        default=0,
        metavar="BLOCKS",
        help="state checkpoint / served-snapshot cadence in blocks — "
        "also the granularity of `p1 maintain rebase` targets (must "
        "agree across nodes for served snapshot heights to line up; "
        "0 = the chain default)",
    )
    p.add_argument(
        "--no-admission-control",
        action="store_true",
        help="disable the per-peer blocks/txs/queries admission budgets "
        "(on by default; the budgets sit far above honest rates and "
        "only clip protocol-valid floods)",
    )
    p.add_argument(
        "--no-telemetry",
        action="store_true",
        help="disable the telemetry plane's latency recording "
        "(node/telemetry.py stage spans + histograms; counters and "
        "`p1 status` stay live — recording is observer-only, so this "
        "is an overhead knob, never a behavior change)",
    )
    _add_retarget(p)

    p = sub.add_parser(
        "status",
        help="query a running node's status JSON (height, peers, sync/"
        "storage/overload state) over the wire",
    )
    p.add_argument("--difficulty", type=int, default=16, help="chain selector")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9444)
    p.add_argument(
        "--watch",
        type=float,
        default=None,
        metavar="N",
        help="re-poll every N seconds until Ctrl-C (clean exit 0) — a "
        "live operator view without shell loops",
    )
    _add_retarget(p)

    p = sub.add_parser(
        "metrics",
        help="query a running node's (or replica's) telemetry registry "
        "over the wire (node/telemetry.py): per-stage block-pipeline "
        "latency histograms, query latency, counters",
    )
    p.add_argument("--difficulty", type=int, default=16, help="chain selector")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9444)
    fmt = p.add_mutually_exclusive_group()
    fmt.add_argument(
        "--json",
        action="store_true",
        dest="as_json",
        help="raw registry snapshot JSON instead of the human table",
    )
    fmt.add_argument(
        "--prom",
        action="store_true",
        help="Prometheus text exposition (scrape-ready)",
    )
    _add_retarget(p)

    p = sub.add_parser(
        "maintain",
        help="drive a running node's zero-downtime maintenance plane "
        "(v13): live re-base, online prune/compact, or the maintenance/"
        "version-bits status report — all without restarting the node",
    )
    p.add_argument(
        "op",
        choices=("status", "rebase", "prune", "compact"),
        help="status = report the maintenance plane (counters + "
        "version-bits deployments); rebase = advance the in-RAM base "
        "to a checkpoint, spilling history to the sidecar planes; "
        "prune = discard body segments below the floor; compact = "
        "rewrite dirty segments without dead side-branch records",
    )
    p.add_argument("--difficulty", type=int, default=16, help="chain selector")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9444)
    p.add_argument(
        "--keep",
        type=int,
        default=None,
        metavar="N",
        help="blocks to keep behind the tip (rebase/prune; default: "
        "the node's checkpoint interval)",
    )
    _add_retarget(p)

    p = sub.add_parser("tx", help="submit a signed transaction to a running node")
    p.add_argument("--difficulty", type=int, default=16, help="chain selector")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9444)
    p.add_argument(
        "--key",
        required=True,
        help="sender key file from `p1 keygen` (the sender id is the "
        "key's account fingerprint — spends are signed, not asserted)",
    )
    p.add_argument("--recipient", required=True)
    p.add_argument("--amount", type=int, required=True)
    p.add_argument(
        "--fee",
        type=_fee_arg,
        default=1,
        help="fee units, or 'auto' to price at the node's recent "
        "confirmed-fee median (floor 1)",
    )
    p.add_argument(
        "--max-fee",
        type=int,
        default=100,
        help="refuse an --fee auto quote above this many units — the "
        "quote is peer-supplied, and a hostile or broken node must not "
        "be able to price a wallet's spend unbounded (explicit --fee N "
        "is never capped: the user stated the number)",
    )
    p.add_argument(
        "--seq",
        type=int,
        default=None,
        help="account nonce to spend (consensus requires the sender's "
        "exact next nonce; default: query the node via GETACCOUNT and "
        "use its next usable seq)",
    )
    _add_retarget(p)

    p = sub.add_parser(
        "account",
        help="query an account's balance/nonce from a running node",
    )
    p.add_argument("--difficulty", type=int, default=16, help="chain selector")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9444)
    p.add_argument(
        "--account", default=None, help="account id (or use --key)"
    )
    p.add_argument(
        "--key", default=None, help="key file; queries its fingerprint account"
    )
    _add_retarget(p)

    p = sub.add_parser(
        "fees", help="query confirmed-fee percentiles from a running node"
    )
    p.add_argument("--difficulty", type=int, default=16, help="chain selector")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9444)
    p.add_argument(
        "--window", type=int, default=0, help="blocks to sample (0 = node default)"
    )
    _add_retarget(p)

    p = sub.add_parser(
        "proof",
        help="fetch + SPV-verify a transaction inclusion proof from a node",
    )
    p.add_argument("--difficulty", type=int, default=16, help="chain selector")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9444)
    p.add_argument(
        "--txid", required=True, help="hex txid (printed by `p1 tx`)"
    )
    p.add_argument(
        "--headers",
        default=None,
        metavar="FILE",
        help="anchor the proof against a locally verified header chain "
        "(from `p1 headers --out FILE`) instead of trusting the peer's "
        "tip claim — full light-client confirmation",
    )
    _add_retarget(p)

    p = sub.add_parser(
        "headers",
        help="light client: fetch + locally verify a node's header chain",
    )
    p.add_argument("--difficulty", type=int, default=16, help="chain selector")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9444)
    p.add_argument(
        "--out", default=None, help="write the verified headers here "
        "(80 bytes each; feeds `p1 replay --verify` and `p1 proof --headers`)"
    )
    p.add_argument(
        "--stall-timeout",
        type=float,
        default=15.0,
        help="per-round progress deadline: a GETHEADERS round that grows "
        "nothing within this window abandons the session and retries "
        "(against --fallback peers, round-robin, when given)",
    )
    p.add_argument(
        "--fallback",
        nargs="*",
        default=[],
        help="host:port alternates to fail over to when the primary "
        "stalls mid-sync (accumulated headers are kept)",
    )
    _add_retarget(p)

    p = sub.add_parser(
        "watch",
        help="live wallet push plane (v14): subscribe for block events, "
        "verify each against the filter-header commitment chain, and "
        "print one JSON line per verified event; a peer caught lying is "
        "demoted and the watch fails over to --fallback replicas at the "
        "verified cursor; exit 4 when every peer is proven dishonest",
    )
    p.add_argument("account", help="account id to watch (utf-8 watch item)")
    p.add_argument(
        "--item",
        action="append",
        default=[],
        help="extra watch item: another account id, or a txid as 64 hex "
        "chars (repeatable)",
    )
    p.add_argument("--difficulty", type=int, default=16, help="chain selector")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9444)
    p.add_argument(
        "--fallback",
        action="append",
        nargs="*",
        default=[],
        metavar="HOST:PORT",
        help="replica to fail over to when the active target dies or is "
        "caught lying (also a cross-check source); repeatable, and each "
        "use also accepts a space-separated list",
    )
    p.add_argument(
        "--fallback-file",
        default=None,
        metavar="PATH",
        help="file of host:port replicas, one per line (# comments and "
        "blank lines ignored) — the fleet roster an orchestrator "
        "rewrites as replicas join and leave",
    )
    p.add_argument(
        "--deadline",
        type=float,
        default=None,
        help="exit 0 after this many seconds (tests/harnesses); "
        "default: watch until interrupted",
    )
    p.add_argument(
        "--max-events",
        type=int,
        default=0,
        help="exit 0 after this many verified events (0 = no cap)",
    )
    p.add_argument(
        "--cross-check-every",
        type=int,
        default=32,
        help="verify the committed tip against a fallback replica every "
        "N events (0 = self-consistency checks only)",
    )
    p.add_argument(
        "--max-session-failures",
        type=int,
        default=None,
        help="give up (exit 1) after N consecutive dead sessions "
        "(default: retry forever)",
    )
    _add_retarget(p)

    p = sub.add_parser(
        "keygen", help="create an Ed25519 spending key (account = fingerprint)"
    )
    p.add_argument("--out", required=True, help="key file to write (0600)")
    p.add_argument(
        "--seed-text",
        default=None,
        help="derive deterministically from this label (TESTS ONLY: the "
        "seed is sha256(label), so the account is publicly spendable)",
    )
    p.add_argument(
        "--force",
        action="store_true",
        help="overwrite an existing key file (DESTROYS the old seed — "
        "coins held by its account become unspendable)",
    )

    p = sub.add_parser(
        "balances", help="account balances from a persisted chain"
    )
    p.add_argument(
        "--difficulty",
        type=int,
        default=None,
        help="chain selector (default: inferred from the store's records)",
    )
    p.add_argument("--store", required=True, help="chain persistence path")
    p.add_argument(
        "--account", default=None, help="print one account instead of all"
    )
    _add_retarget(p)

    p = sub.add_parser(
        "pod",
        help="multi-host pod miner: N processes, one miner on the network",
    )
    # Not _add_common: the pod always runs the sharded mesh backend, so a
    # --backend flag would be a silent no-op.  chunk/batch MUST match
    # across processes (PodMiner validates at startup).
    p.add_argument("--difficulty", type=int, default=16)
    p.add_argument("--batch", type=int, default=None, help="per-device batch")
    p.add_argument("--chunk", type=int, default=None, help="miner abort granularity")
    p.add_argument("--coordinator", required=True, help="host:port of process 0")
    p.add_argument("--num-hosts", type=int, required=True)
    p.add_argument("--host-id", type=int, required=True)
    p.add_argument(
        "--platform",
        default=None,
        help="pin the JAX platform (e.g. cpu) before distributed init",
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0, help="leader's p2p port")
    p.add_argument("--peers", nargs="*", default=[], help="host:port ...")
    p.add_argument("--miner-id", default=None)
    p.add_argument("--store", default=None)
    p.add_argument(
        "--duration",
        type=float,
        default=None,
        help="leader: stop mining after N s; both roles also arm a "
        "peer-loss watchdog (force-exit after 600s with no lockstep "
        "progress — the grace covers first-search jit compile)",
    )
    p.set_defaults(no_mine=False, deadline=None, status_interval=10.0)

    p = sub.add_parser(
        "compact", help="rewrite a chain store to just its main branch"
    )
    p.add_argument("--store", required=True, help="chain persistence path")
    p.add_argument(
        "--out",
        default=None,
        help="write here instead of replacing the store in place",
    )
    _add_retarget(p)

    p = sub.add_parser(
        "fsck",
        help="scan a chain store offline: report per-record integrity and "
        "salvage every checksum-valid record into a fresh verified store "
        "(also upgrades v2 stores to the checksummed v3 framing); exit 0 "
        "= clean, 1 = salvaged with losses, 2 = unrecoverable",
    )
    p.add_argument("--store", required=True, help="chain persistence path")
    p.add_argument(
        "--out",
        default=None,
        help="write the salvaged store here instead of replacing in place",
    )
    p.add_argument(
        "--json",
        action="store_true",
        help="machine-readable per-segment report (one row per segment "
        "with its own verdict/spans/salvage counts; single-file stores "
        "report as one segment)",
    )

    p = sub.add_parser(
        "snapshot",
        help="ledger-state snapshots (chain/snapshot.py): create one "
        "from a chain store's latest checkpoint, verify a snapshot "
        "file's integrity (manifest, chunk digests, state root), or "
        "print its manifest; exit 0 clean / 1 salvageable issue / 2 "
        "unrecoverable",
    )
    p.add_argument(
        "action",
        choices=["create", "verify", "info"],
        help="create: --store -> --file; verify/info: --file",
    )
    p.add_argument("--store", default=None, help="chain store (create)")
    p.add_argument(
        "--file", default=None, help="snapshot file path (all actions)"
    )
    p.add_argument(
        "--interval",
        type=int,
        default=0,
        help="checkpoint interval override for create (0 = the chain "
        "default: the retarget window, else 64); the snapshot lands on "
        "the latest multiple at or below the store's tip",
    )
    _add_retarget(p)

    p = sub.add_parser(
        "serve",
        help="read-only replica worker(s): serve headers/filters/proof "
        "queries from a chain store over mmap, WITHOUT the writer lock "
        "— attach any number to a live node's store and scale query "
        "QPS with cores while the node only mines and validates",
    )
    p.add_argument("--store", required=True, help="chain persistence path")
    p.add_argument("--difficulty", type=int, default=16, help="chain selector")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument(
        "--port",
        type=int,
        default=9555,
        help="listen port (0 = ephemeral; --workers > 1 needs a real "
        "port, shared via SO_REUSEPORT)",
    )
    p.add_argument(
        "--workers",
        type=int,
        default=1,
        help="replica processes to run against this store on this port "
        "(SO_REUSEPORT fan-out; each worker holds its own mmap and "
        "caches)",
    )
    p.add_argument(
        "--refresh-interval",
        type=float,
        default=0.25,
        help="seconds between tail rescans for blocks the node appended",
    )
    p.add_argument(
        "--bootstrap",
        action="append",
        default=[],
        metavar="HOST:PORT",
        help="cold-start the store from this full node before serving: "
        "PoW-verified header skeleton, chunk-verified snapshot, adopted "
        "filter headers, then bodies above the base — seconds, not an "
        "IBD; repeatable (extra peers are failovers and the cross-check "
        "source); the worker then keeps pulling new blocks from the "
        "bootstrap peers while it serves",
    )
    p.add_argument(
        "--deadline",
        type=float,
        default=None,
        help="exit after this many seconds (tests/harnesses); default: "
        "serve until interrupted",
    )
    _add_retarget(p)

    p = sub.add_parser("net", help="N-node localhost net (config 4)")
    _add_common(p)
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument("--duration", type=float, default=10.0)
    p.add_argument("--base-port", type=int, default=19444)
    p.add_argument(
        "--byzantine",
        type=int,
        default=0,
        help="run this many actively malicious participants alongside "
        "the honest mesh (invalid signatures, overdraws, replays, "
        "forged compact blocks, ADDR spam, oversized frames — each "
        "from its own loopback alias so bans land on the attacker); "
        "the summary asserts the honest net converged, conserved, "
        "banned them, and stayed within memory bounds",
    )
    p.add_argument(
        "--tx-rate",
        type=float,
        default=0.0,
        help="inject ~R signed transfers/sec between the miners' accounts "
        "during the run (each node mines to a keyed account); the summary "
        "then audits ledger conservation (sum == reward x height) on "
        "every node",
    )
    p.add_argument(
        "--no-compact-gossip",
        action="store_true",
        help="children push full BLOCK frames instead of compact blocks",
    )
    p.add_argument(
        "--discover",
        action="store_true",
        help="bootstrap the topology via peer discovery: every node dials "
        "ONLY node 0 and must find the rest through GETADDR/ADDR (vs the "
        "default statically configured full mesh)",
    )
    _add_retarget(p)

    p = sub.add_parser(
        "sim",
        help="deterministic network-simulator scenarios (1000-node "
        "meshes in virtual time, one JSON report line)",
    )
    p.add_argument(
        "scenario",
        nargs="?",
        default="partition-heal",
        help="scenario name (see --list); default partition-heal",
    )
    p.add_argument(
        "--list", action="store_true", help="list scenarios and exit"
    )
    p.add_argument(
        "--seed",
        type=int,
        default=0,
        help="determinism seed: same seed => byte-identical event trace "
        "(the report's trace_digest)",
    )
    p.add_argument(
        "--nodes",
        type=int,
        default=None,
        help="mesh size (scenarios with a fixed shape ignore it)",
    )
    p.add_argument("--difficulty", type=int, default=8)
    p.add_argument(
        "--joiners", type=int, default=None, help="flash-crowd joiner count"
    )
    p.add_argument(
        "--cycles", type=int, default=None, help="churn stop/restart waves"
    )
    p.add_argument(
        "--attackers", type=int, default=None, help="eclipse attacker hosts"
    )
    p.add_argument(
        "--region-nodes", type=int, default=None, help="wan nodes per region"
    )
    p.add_argument(
        "--shards",
        type=int,
        default=None,
        help="far-field shard count (>1 = one OS process per shard over "
        "the pipe seam; the merged trace digest must not move with it)",
    )
    p.add_argument(
        "--days",
        type=float,
        default=None,
        help="soak scenario: virtual days to run (default 7)",
    )
    p.add_argument(
        "--no-telemetry",
        action="store_true",
        help="run the scenario's nodes with telemetry recording off — "
        "the trace digest must match the telemetry-on run (the "
        "observer contract; tests compare exactly this)",
    )

    p = sub.add_parser(
        "chaos",
        help="combined-fault search over the simulated mesh: seeded "
        "crash/disk/partition/adversary schedules, invariant checks, "
        "self-shrinking repros",
    )
    p.add_argument(
        "--seed",
        type=int,
        default=0,
        help="first schedule seed (determinism: same seed => "
        "byte-identical event trace)",
    )
    p.add_argument(
        "--schedules",
        type=int,
        default=1,
        help="how many consecutive seeds to sweep (default 1)",
    )
    p.add_argument("--nodes", type=int, default=6, help="mesh size per run")
    p.add_argument(
        "--events", type=int, default=12, help="fault events per schedule"
    )
    p.add_argument("--difficulty", type=int, default=8)
    p.add_argument(
        "--repro",
        metavar="FILE",
        help="replay a repro artifact instead of sweeping (exit 1 iff "
        "the recorded violation reproduces)",
    )
    p.add_argument(
        "--out",
        metavar="FILE",
        default="chaos_repro.json",
        help="where a violation's shrunk repro artifact is written "
        "(default chaos_repro.json)",
    )
    p.add_argument(
        "--no-shrink",
        action="store_true",
        help="on violation, write the full schedule without minimizing",
    )
    p.add_argument(
        "--inject-bug",
        choices=["relapse-disk", "deaf-recover"],
        help="TEST ONLY: seed a known recovery bug so the shrink/repro "
        "pipeline can be exercised against a guaranteed violation",
    )

    sub.add_parser("bench", help="headline benchmark (one JSON line)")

    p = sub.add_parser(
        "lint",
        help="determinism/async-safety static analysis over p1_tpu "
        "(exit 0 clean, 1 findings or stale grants, 2 usage)",
    )
    p.add_argument(
        "--json",
        action="store_true",
        dest="as_json",
        help="full machine-readable report on stdout",
    )
    p.add_argument(
        "--rule",
        action="append",
        default=None,
        metavar="NAME",
        help="run only this rule (repeatable; default: all registered "
        "rules — see docs/LINT.md for the catalog)",
    )
    p.add_argument(
        "--granted",
        action="store_true",
        help="also list allowlisted findings with their grant reasons",
    )
    p.add_argument(
        "--path",
        action="append",
        default=None,
        metavar="FILE_OR_DIR",
        help="report only findings under this file/directory (repeatable; "
        "package-relative like node/node.py, or a real path).  The "
        "analysis still runs whole-package and settlement stays global, "
        "so a scoped run can't hide a stale grant — this narrows what "
        "you LOOK at for fast pre-commit loops",
    )
    return parser


# -- mine ----------------------------------------------------------------


def _mine_chain(miner, difficulty: int, blocks: int):
    """Mine ``blocks`` headers from genesis; return (times, total_hashes)."""
    from p1_tpu.core.genesis import make_genesis
    from p1_tpu.core.header import BlockHeader

    if blocks < 1:
        raise SystemExit("--blocks must be >= 1")

    tip = make_genesis(difficulty).header
    times, hashes = [], 0
    for height in range(1, blocks + 1):
        draft = BlockHeader(
            1, tip.block_hash(), bytes(32), tip.timestamp + 1, difficulty, 0
        )
        t0 = time.perf_counter()
        sealed = miner.search_nonce(draft)
        dt = time.perf_counter() - t0
        assert sealed is not None
        times.append(dt)
        hashes += miner.last_stats.hashes_done
        logging.info(
            "block d=%d height=%d nonce=%d t=%.3fs hps=%.0f",
            difficulty,
            height,
            sealed.nonce,
            dt,
            miner.last_stats.hashes_per_sec,
        )
        tip = sealed
    return times, hashes


def cmd_mine(args) -> int:
    import contextlib

    from p1_tpu.hashx import get_backend
    from p1_tpu.miner import Miner

    kwargs = {"batch": args.batch} if args.batch else {}
    miner = Miner(backend=get_backend(args.backend, **kwargs), chunk=args.chunk)
    if args.profile:
        # SURVEY.md §5 tracing: a device trace of the real mining loop.
        # One warmup block first so the trace shows steady-state steps,
        # not Mosaic/XLA compilation.
        import jax

        _mine_chain(miner, args.difficulty, 1)
        profile_ctx = jax.profiler.trace(args.profile)
        logging.info("profiling mining loop into %s", args.profile)
    else:
        profile_ctx = contextlib.nullcontext()
    with profile_ctx:
        times, hashes = _mine_chain(miner, args.difficulty, args.blocks)
    total = sum(times)
    print(
        json.dumps(
            {
                "config": "mine",
                "backend": args.backend,
                "difficulty": args.difficulty,
                "blocks": args.blocks,
                "hashes_per_sec": round(hashes / total) if total else 0,
                "time_to_block_s": round(statistics.median(times), 4),
                "total_s": round(total, 3),
                **({"profile_dir": args.profile} if args.profile else {}),
            }
        )
    )
    return 0


def _parse_difficulties(spec: str) -> list[int]:
    try:
        if ":" in spec:
            lo, _, hi = spec.partition(":")
            out = list(range(int(lo), int(hi)))
        else:
            out = [int(d) for d in spec.split(",") if d]
    except ValueError:
        out = []
    if not out or not all(0 <= d <= 255 for d in out):
        raise SystemExit(
            f"bad difficulty spec {spec!r} (want LO:HI or a comma list)"
        )
    return out


def cmd_sweep(args) -> int:
    """Benchmark config 2: nonce-space scaling across difficulties.

    One JSON line per difficulty with median time-to-block and the
    aggregate hash rate, so the scaling curve (time ~ 2^d / rate, floored
    by dispatch latency) is reproducible from a single command.
    """
    from p1_tpu.hashx import get_backend
    from p1_tpu.miner import Miner

    kwargs = {"batch": args.batch} if args.batch else {}
    miner = Miner(backend=get_backend(args.backend, **kwargs), chunk=args.chunk)
    for difficulty in _parse_difficulties(args.difficulties):
        times, hashes = _mine_chain(miner, difficulty, args.blocks)
        total = sum(times)
        print(
            json.dumps(
                {
                    "config": "sweep",
                    "backend": args.backend,
                    "difficulty": difficulty,
                    "blocks": args.blocks,
                    "time_to_block_s": round(statistics.median(times), 4),
                    "hashes_per_sec": round(hashes / total) if total else 0,
                    "total_s": round(total, 3),
                }
            ),
            flush=True,
        )
    return 0


# -- replay --------------------------------------------------------------


def cmd_replay(args) -> int:
    from p1_tpu.chain import (
        generate_headers,
        replay_device,
        replay_host,
        replay_native,
    )
    from p1_tpu.core.header import HEADER_SIZE, BlockHeader
    from p1_tpu.hashx import get_backend

    rule = _retarget_rule(args)
    if rule is not None and args.method in ("device", "both"):
        # The host oracle and the C++ engine are both retarget-aware
        # (chain/replay.py, native p1_verify_chain_retarget); the DEVICE
        # tier implements the benchmark-config form (fixed difficulty:
        # the lax.scan carries one target) and would mis-report an
        # honest retargeting chain as invalid at the first adjustment.
        print(
            "retargeting chains verify with --method host/native/all "
            "(the device engine is fixed-difficulty)",
            file=sys.stderr,
        )
        return 2
    if args.verify:
        raw = open(args.verify, "rb").read()
        if len(raw) % HEADER_SIZE:
            print(f"{args.verify}: not a multiple of {HEADER_SIZE} bytes", file=sys.stderr)
            return 2
        headers = [
            BlockHeader.deserialize(raw[i : i + HEADER_SIZE])
            for i in range(0, len(raw), HEADER_SIZE)
        ]
        # Pin the file to the chain the operator selected: header[0] is
        # otherwise SELF-attested, and a forged file whose genesis claims
        # difficulty 1 would "verify" with no meaningful work behind it —
        # fatal for the light-client escalation path this command serves.
        from p1_tpu.core.genesis import make_genesis

        if (
            not headers
            or headers[0].block_hash()
            != make_genesis(args.difficulty, rule).block_hash()
        ):
            print(
                f"{args.verify}: does not start at this chain's genesis "
                "(check --difficulty / retarget flags)",
                file=sys.stderr,
            )
            return 2
    else:
        kwargs = {"batch": args.batch} if args.batch else {}
        backend = get_backend(args.backend, **kwargs)
        t0 = time.perf_counter()
        headers = generate_headers(
            args.n, args.difficulty, backend=backend, retarget=rule
        )
        logging.info("generated %d headers in %.1fs", args.n, time.perf_counter() - t0)
        if args.out:
            with open(args.out, "wb") as fh:
                for h in headers:
                    fh.write(h.serialize())

    reports = []
    if args.method in ("host", "both", "all"):
        reports.append(replay_host(headers, retarget=rule))
    if args.method in ("native", "all"):
        reports.append(replay_native(headers, retarget=rule))
    if args.method in ("device", "both", "all") and rule is None:
        # Fixed-difficulty only (the guard above rejects explicit device
        # requests on retargeting chains; `all` quietly covers what can
        # run: host + native).
        reports.append(replay_device(headers))
        reports.append(replay_device(headers))  # warm (compile amortized)
    ok = all(r.valid for r in reports)
    print(
        json.dumps(
            {
                "config": "replay",
                "n_headers": len(headers),
                "valid": ok,
                "first_invalid": next(
                    (r.first_invalid for r in reports if not r.valid), None
                ),
                "results": [
                    {
                        "method": r.method,
                        "headers_per_sec": round(r.headers_per_sec),
                        "elapsed_s": round(r.elapsed_s, 4),
                    }
                    for r in reports
                ],
            }
        )
    )
    return 0 if ok else 1


# -- node ----------------------------------------------------------------


def cmd_node(args) -> int:
    _retarget_rule(args)  # flag-pair validation: clean error, no traceback
    # The CPU miner thread is GIL-bound (hashlib holds the GIL for
    # 80-byte messages) and the default 5 ms switch interval lets it
    # convoy the event loop hard enough that a wallet's HELLO can starve
    # past its 10 s timeout on 1-vCPU hosts (observed live).  A 0.5 ms
    # interval hands the loop the GIL ~10x more often for a few percent
    # of hash throughput — only worth paying in the node process, where
    # p2p responsiveness under mining load is the product.
    sys.setswitchinterval(0.0005)
    if getattr(args, "platform", None):
        import jax

        jax.config.update("jax_platforms", args.platform)
    from p1_tpu.node.runner import run_node

    try:
        return asyncio.run(run_node(args))
    except KeyboardInterrupt:
        return 0


def cmd_status(args) -> int:
    """Query a running node's full status JSON (`p1 status`) — the same
    object the node logs, served over the wire (GETSTATUS/STATUS, v9),
    overload block included.  Works even while the node sheds load.

    ``--watch N`` re-polls every N seconds until Ctrl-C (clean exit 0)
    — the live operator view that used to need a shell loop.  One poll
    failing mid-watch prints the error and keeps watching (a node
    restarting must not kill the dashboard); without --watch a failure
    is exit 1 as before."""
    import time as _time

    from p1_tpu.node.client import get_status

    watch = getattr(args, "watch", None)
    if watch is not None and watch <= 0:
        print("--watch needs a positive interval", file=sys.stderr)
        return 2
    try:
        while True:
            try:
                status = asyncio.run(
                    get_status(
                        args.host,
                        args.port,
                        args.difficulty,
                        retarget=_retarget_rule(args),
                    )
                )
                print(
                    json.dumps(status, indent=2, sort_keys=True), flush=True
                )
            except (
                ConnectionError,
                OSError,
                ValueError,
                asyncio.TimeoutError,
                asyncio.IncompleteReadError,
            ) as e:
                print(f"status query failed: {e}", file=sys.stderr)
                if watch is None:
                    return 1
            if watch is None:
                return 0
            _time.sleep(watch)
    except KeyboardInterrupt:
        # Ctrl-C is how a watch ENDS, not an error: exit clean wherever
        # in the poll/sleep cycle it lands.
        return 0


def cmd_maintain(args) -> int:
    """Drive a running node's maintenance plane (`p1 maintain`,
    GETMAINTAIN/MAINTAIN v13).  Exit-code contract, test-pinned: 0 when
    the node answered ``{"ok": true}``; 1 when it REFUSED (``{"ok":
    false}`` — busy, assumed chain, degraded store, nothing to do at
    this height) or the wire failed; 2 on usage errors caught locally.
    The refusal detail lands on stderr, the full reply JSON on stdout
    either way — scripts branch on the exit code, operators read the
    reply."""
    from p1_tpu.node.client import maintain

    if args.keep is not None and args.keep < 0:
        print("--keep must be >= 0", file=sys.stderr)
        return 2
    if args.keep is not None and args.op in ("status", "compact"):
        print(f"--keep does not apply to {args.op!r}", file=sys.stderr)
        return 2
    command: dict = {"op": args.op}
    if args.keep is not None:
        command["keep"] = args.keep
    try:
        reply = asyncio.run(
            maintain(
                args.host,
                args.port,
                command,
                args.difficulty,
                retarget=_retarget_rule(args),
            )
        )
    except (
        ConnectionError,
        OSError,
        ValueError,
        asyncio.TimeoutError,
        asyncio.IncompleteReadError,
    ) as e:
        print(f"maintain command failed: {e}", file=sys.stderr)
        return 1
    print(json.dumps(reply, indent=2, sort_keys=True), flush=True)
    if not (isinstance(reply, dict) and reply.get("ok") is True):
        error = reply.get("error") if isinstance(reply, dict) else reply
        print(f"maintain refused: {error}", file=sys.stderr)
        return 1
    return 0


def cmd_metrics(args) -> int:
    """Query a node's (or `p1 serve` replica's) telemetry registry
    (`p1 metrics`, GETMETRICS/METRICS v12) and render it: human latency
    table by default, ``--json`` for the raw snapshot, ``--prom`` for
    Prometheus text exposition.  The render runs on the wire payload —
    the CLI holds no registry of its own, so what you see is exactly
    what the node exported."""
    from p1_tpu.node.client import get_metrics
    from p1_tpu.node.telemetry import format_prometheus, format_table

    try:
        snap = asyncio.run(
            get_metrics(
                args.host,
                args.port,
                args.difficulty,
                retarget=_retarget_rule(args),
            )
        )
    except (
        ConnectionError,
        OSError,
        ValueError,
        asyncio.TimeoutError,
        asyncio.IncompleteReadError,
    ) as e:
        print(f"metrics query failed: {e}", file=sys.stderr)
        return 1
    if args.as_json:
        print(json.dumps(snap, indent=2, sort_keys=True))
    elif args.prom:
        sys.stdout.write(format_prometheus(snap))
    else:
        for key in ("role", "miner_id", "height"):
            if key in snap:
                print(f"{key}: {snap[key]}")
        print(format_table(snap))
    return 0


# -- tx ------------------------------------------------------------------


def cmd_tx(args) -> int:
    from p1_tpu.core.keys import Keypair
    from p1_tpu.core.tx import Transaction
    from p1_tpu.node.client import send_tx

    try:
        from p1_tpu.core.genesis import genesis_hash
        from p1_tpu.node.client import get_account

        key = Keypair.load(args.key)
        rule = _retarget_rule(args)
        if args.fee == "auto":
            from p1_tpu.node.client import get_fees

            stats = asyncio.run(
                get_fees(args.host, args.port, args.difficulty, retarget=rule)
            )
            fee = max(1, stats.p50)
            if fee > args.max_fee:
                # The quote is the PEER's number; signing it unseen would
                # let one hostile node drain the account through fees.
                print(
                    f"refusing auto fee {fee} above --max-fee "
                    f"{args.max_fee} (node quote p50={stats.p50} over "
                    f"{stats.samples} samples); pass an explicit --fee "
                    f"or raise --max-fee to accept",
                    file=sys.stderr,
                )
                return 2
        else:
            fee = args.fee
        seq = args.seq
        if seq is None:
            # Wallet convenience: consensus wants the exact next nonce, so
            # ask the node (chain nonce advanced past its pending pool).
            state = asyncio.run(
                get_account(
                    args.host,
                    args.port,
                    key.account,
                    args.difficulty,
                    retarget=rule,
                )
            )
            seq = state.next_seq
        tx = Transaction.transfer(
            key,
            args.recipient,
            args.amount,
            fee,
            seq,
            chain=genesis_hash(args.difficulty, rule),
        )
        height = asyncio.run(
            send_tx(args.host, args.port, tx, args.difficulty, retarget=rule)
        )
    except (
        ConnectionError,
        OSError,
        ValueError,
        asyncio.TimeoutError,
        asyncio.IncompleteReadError,  # clean close mid-handshake (EOFError)
    ) as e:
        print(f"tx submission failed: {e}", file=sys.stderr)
        return 1
    print(
        json.dumps(
            {
                "config": "tx",
                "txid": tx.txid().hex(),
                "sender": tx.sender,
                "seq": seq,
                "fee": fee,
                "peer_height": height,
            }
        )
    )
    return 0


# -- account -------------------------------------------------------------


def cmd_account(args) -> int:
    from p1_tpu.core.keys import Keypair
    from p1_tpu.node.client import get_account

    if (args.account is None) == (args.key is None):
        print("pass exactly one of --account / --key", file=sys.stderr)
        return 2
    try:
        account = args.account or Keypair.load(args.key).account
        state = asyncio.run(
            get_account(
                args.host,
                args.port,
                account,
                args.difficulty,
                retarget=_retarget_rule(args),
            )
        )
    except (
        ConnectionError,
        OSError,
        ValueError,
        asyncio.TimeoutError,
        asyncio.IncompleteReadError,
    ) as e:
        print(f"account query failed: {e}", file=sys.stderr)
        return 1
    print(
        json.dumps(
            {
                "config": "account",
                "account": state.account,
                "balance": state.balance,
                "nonce": state.nonce,
                "next_seq": state.next_seq,
                "height": state.tip_height,
            }
        )
    )
    return 0


# -- fees ----------------------------------------------------------------


def cmd_fees(args) -> int:
    from p1_tpu.node.client import get_fees

    try:
        stats = asyncio.run(
            get_fees(
                args.host,
                args.port,
                args.difficulty,
                window=args.window,
                retarget=_retarget_rule(args),
            )
        )
    except (
        ConnectionError,
        OSError,
        ValueError,
        asyncio.TimeoutError,
        asyncio.IncompleteReadError,
    ) as e:
        print(f"fee query failed: {e}", file=sys.stderr)
        return 1
    print(
        json.dumps(
            {
                "config": "fees",
                "window_blocks": stats.window_blocks,
                "samples": stats.samples,
                "p25": stats.p25,
                "p50": stats.p50,
                "p75": stats.p75,
                "suggested_fee": max(1, stats.p50),
                "height": stats.tip_height,
            }
        )
    )
    return 0


# -- proof ---------------------------------------------------------------


def cmd_proof(args) -> int:
    """Fetch an SPV inclusion proof and verify it CLIENT-SIDE.

    Exit codes: 0 = confirmed and proof verifies; 1 = query failed;
    3 = not confirmed on the peer's main chain; 4 = the peer served a
    proof that FAILS verification (a lying or broken peer — loud exit).
    """
    from p1_tpu.chain.proof import SPVError, verify_tx_proof
    from p1_tpu.core.genesis import genesis_hash
    from p1_tpu.node.client import get_proof

    try:
        rule = _retarget_rule(args)
        txid = bytes.fromhex(args.txid)
        if len(txid) != 32:
            raise ValueError("txid must be 32 hex-encoded bytes")
        proof = asyncio.run(
            get_proof(
                args.host, args.port, txid, args.difficulty, retarget=rule
            )
        )
    except (
        ConnectionError,
        OSError,
        ValueError,
        asyncio.TimeoutError,
        asyncio.IncompleteReadError,
    ) as e:
        print(f"proof query failed: {e}", file=sys.stderr)
        return 1
    if proof is None:
        print(json.dumps({"config": "proof", "confirmed": False}))
        return 3
    try:
        verify_tx_proof(
            proof,
            args.difficulty,
            genesis_hash(args.difficulty, rule),
            txid=txid,
            retarget=rule,
        )
    except SPVError as e:
        print(f"peer served an INVALID proof: {e}", file=sys.stderr)
        return 4
    confirmations = proof.confirmations  # the peer's claim...
    anchored = False
    if rule is not None and not args.headers:
        # Retargeting chains verify at the header's claimed difficulty
        # (schedule-floored — chain/proof.py), and height/tip/
        # confirmations are all the peer's claims; only --headers
        # anchoring pins them to a locally verified chain.  Say so
        # loudly rather than letting scripts equate the two modes.
        print(
            "warning: retargeting chain without --headers — proof "
            "verified at its claimed difficulty only, and the height/"
            "confirmation figures are the peer's unverified claims; "
            "anchor against `p1 headers` output for real light-client "
            "verification",
            file=sys.stderr,
        )
    if args.headers:
        # ...unless anchored: the proof's block must sit at its claimed
        # height on a LOCALLY verified header chain, and confirmations are
        # then computed from that chain — no peer claims left anywhere.
        headers = _load_header_file(args.headers, args.difficulty, rule)
        if (
            proof.height >= len(headers)
            or headers[proof.height].block_hash()
            != proof.header.block_hash()
        ):
            print(
                "proof's block is not on the locally verified header "
                "chain (stale, side-branch, or forged)",
                file=sys.stderr,
            )
            return 4
        confirmations = len(headers) - proof.height
        anchored = True
    print(
        json.dumps(
            {
                "config": "proof",
                "confirmed": True,
                "verified": True,
                "txid": args.txid,
                "height": proof.height,
                "confirmations": confirmations,
                "anchored": anchored,
                "block": proof.header.block_hash().hex(),
                # The work bar this evidence meets (== chain difficulty on
                # fixed chains; the header's claim on retargeting chains).
                "difficulty": proof.header.difficulty,
                "index": proof.index,
                "branch_len": len(proof.branch),
                "amount": proof.tx.amount,
                "recipient": proof.tx.recipient,
            }
        )
    )
    return 0


# -- headers -------------------------------------------------------------


def _load_header_file(path: str, difficulty: int, rule):
    """Read + fully verify a header file as this chain's header chain.
    Returns the genesis-first header list; raises SystemExit on any
    failure (wrong chain, bad PoW/linkage/schedule) — a light client must
    never proceed on unverified headers."""
    from p1_tpu.chain import parse_headers, replay_packed
    from p1_tpu.core.genesis import make_genesis
    from p1_tpu.core.hashutil import sha256d
    from p1_tpu.core.header import HEADER_SIZE

    raw = open(path, "rb").read()
    if not raw or len(raw) % HEADER_SIZE:
        print(f"{path}: not a header file", file=sys.stderr)
        raise SystemExit(2)
    # Packed-bytes plane end to end: genesis pinning hashes the first 80
    # bytes directly, verification hands the whole file to the native
    # engine in one call (replay_packed), and the object parse happens
    # once, after the chain has proven itself — seeding each header's
    # encoding cache with the file's exact bytes.
    if sha256d(raw[:HEADER_SIZE]) != make_genesis(difficulty, rule).block_hash():
        print(
            f"{path}: does not start at this chain's genesis "
            "(check --difficulty / retarget flags)",
            file=sys.stderr,
        )
        raise SystemExit(2)
    report = replay_packed(raw, retarget=rule)
    if not report.valid:
        print(
            f"{path}: header chain INVALID at index {report.first_invalid}",
            file=sys.stderr,
        )
        raise SystemExit(4)
    return parse_headers(raw)


def cmd_headers(args) -> int:
    """Light-client sync: fetch the peer's header chain (~80 B/block) and
    verify it locally — PoW, linkage, and (with the retarget flags) the
    full contextual difficulty schedule.  Trusts nothing but work."""
    from p1_tpu.chain import replay_fast
    from p1_tpu.node.client import get_headers

    rule = _retarget_rule(args)

    def _addr(spec: str) -> tuple[str, int]:
        host, _, port = spec.rpartition(":")
        return (host or "127.0.0.1", int(port))

    try:
        headers = asyncio.run(
            get_headers(
                args.host,
                args.port,
                args.difficulty,
                retarget=rule,
                stall_timeout_s=args.stall_timeout,
                fallback_peers=[_addr(s) for s in args.fallback],
            )
        )
    except (
        ConnectionError,
        OSError,
        ValueError,
        asyncio.TimeoutError,
        asyncio.IncompleteReadError,
    ) as e:
        print(f"header sync failed: {e}", file=sys.stderr)
        return 1
    report = replay_fast(headers, retarget=rule)
    if report.valid and args.out:
        with open(args.out, "wb") as fh:
            for h in headers:
                fh.write(h.serialize())
    print(
        json.dumps(
            {
                "config": "headers",
                "height": len(headers) - 1,
                "tip": headers[-1].block_hash().hex(),
                "tip_difficulty": headers[-1].difficulty,
                "valid": report.valid,
                "first_invalid": report.first_invalid,
                "verify_headers_per_sec": round(report.headers_per_sec),
                "out": args.out if report.valid else None,
            }
        )
    )
    # A peer serving an invalid chain is loud (4), like a lying proof.
    return 0 if report.valid else 4


# -- watch ---------------------------------------------------------------


def cmd_watch(args) -> int:
    """Live wallet notifications: subscribe to a node or replica,
    verify every pushed event against the filter-header commitment
    chain (client.watch does the believing only after checking), and
    print one JSON line per verified block — matched ones carry the
    confirmed txids.  A peer caught lying is demoted and the watch
    fails over to --fallback replicas at the last verified cursor, so
    no confirmation is missed across the switch; when every peer is
    proven dishonest the exit is loud (4), like a lying proof."""
    from p1_tpu.node.client import CommitmentViolation, watch

    rule = _retarget_rule(args)

    def _addr(spec: str) -> tuple[str, int]:
        host, _, port = spec.rpartition(":")
        return (host or "127.0.0.1", int(port))

    def _item(s: str):
        # 64 hex chars = a raw txid; anything else is an account id.
        if len(s) == 64:
            try:
                return bytes.fromhex(s)
            except ValueError:
                pass
        return s

    items = [args.account, *(_item(s) for s in args.item)]

    from pathlib import Path

    # --fallback is repeatable and each use takes a list; --fallback-file
    # adds a host:port-per-line roster.  Order is preserved (flag order,
    # then file order) and duplicates collapse — the ReplicaSet inside
    # client.watch treats the order as the tie-break preference.
    specs: list[str] = []
    for group in args.fallback:
        specs.extend(group if isinstance(group, list) else [group])
    if args.fallback_file is not None:
        try:
            for line in Path(args.fallback_file).read_text().splitlines():
                line = line.split("#", 1)[0].strip()
                if line:
                    specs.append(line)
        except OSError as e:
            print(f"watch failed: --fallback-file: {e}", file=sys.stderr)
            return 2
    fallbacks = list(dict.fromkeys(_addr(s) for s in specs))

    async def _run() -> int:
        gen = watch(
            args.host,
            args.port,
            items,
            args.difficulty,
            retarget=rule,
            fallback_peers=fallbacks,
            cross_check_every=args.cross_check_every,
            max_session_failures=args.max_session_failures,
        )
        n = 0
        try:
            async for ev in gen:
                print(
                    json.dumps(
                        {
                            "height": ev["height"],
                            "block": ev["block_hash"].hex(),
                            "filter_header": ev["filter_header"].hex(),
                            "matched": ev["matched"],
                            "txids": [t.hex() for t in ev["txids"]]
                            if ev["matched"]
                            else [],
                            "peer": f"{ev['peer'][0]}:{ev['peer'][1]}",
                            "target": f"{ev['peer'][0]}:{ev['peer'][1]}",
                            "failovers": ev["failovers"],
                        }
                    ),
                    flush=True,
                )
                n += 1
                if args.max_events and n >= args.max_events:
                    return 0
        finally:
            await gen.aclose()
        return 0

    try:
        if args.deadline is not None:
            try:
                return asyncio.run(asyncio.wait_for(_run(), args.deadline))
            except (asyncio.TimeoutError, TimeoutError):
                return 0  # the deadline is a clean exit, like `p1 serve`
        return asyncio.run(_run())
    except CommitmentViolation as e:
        print(f"watch failed: {e}", file=sys.stderr)
        return 4
    except (
        ConnectionError,
        OSError,
        ValueError,
        asyncio.IncompleteReadError,
    ) as e:
        print(f"watch failed: {e}", file=sys.stderr)
        return 1


# -- keygen --------------------------------------------------------------


def cmd_keygen(args) -> int:
    from p1_tpu.core.keys import Keypair

    key = (
        Keypair.from_seed_text(args.seed_text)
        if args.seed_text is not None
        else Keypair.generate()
    )
    try:
        key.save(args.out, overwrite=args.force)
    except FileExistsError:
        print(
            f"{args.out} already exists; refusing to destroy its seed "
            "(use --force to overwrite)",
            file=sys.stderr,
        )
        return 2
    print(json.dumps({"config": "keygen", "account": key.account, "path": args.out}))
    return 0


# -- pod -----------------------------------------------------------------


def cmd_pod(args) -> int:
    """Multi-host mining (north star config 5, multi-host form): every
    process joins one jax.distributed mesh and mirrors the same sharded
    search in lockstep; process 0 additionally runs the p2p node, so the
    whole pod presents as a single miner on the gossip network.

    Failure handling: each role arms a no-progress watchdog (bounded runs
    only; ``parallel/watchdog.py``).  A follower that loses the pod exits
    3 (``POD_LOST_EXIT``) — restart it with the same ``--host-id`` under
    any supervisor (systemd ``Restart=on-failure``, a shell loop) once
    the pod coordinator is back.  The LEADER owns the chain store and
    the gossip identity, so it does NOT go dark: the watchdog re-execs
    it into single-process sharded mining against the same
    store/port/peers (``pod_leader_failover``) and the chain keeps
    growing while the pod is rebuilt."""
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    from p1_tpu.hashx import get_backend
    from p1_tpu.parallel import PodMiner, init_distributed
    from p1_tpu.parallel.watchdog import (
        POD_LOST_EXIT,
        PodWatchdog,
        pod_leader_failover,
    )

    init_distributed(args.coordinator, args.num_hosts, args.host_id)
    is_leader = args.host_id == 0
    # Arm the watchdog BEFORE any blocking collective (the construction
    # handshake included): a peer that dies during startup must not hang a
    # bounded run.  Long-running services (no --duration) supervise
    # externally.
    watchdog = None
    if args.duration is not None:
        deadline = time.time() + args.duration
        on_trip = (
            (lambda: pod_leader_failover(args, deadline)) if is_leader else None
        )
        watchdog = PodWatchdog(
            role="leader" if is_leader else "follower", on_trip=on_trip
        )
    kwargs = {"batch": args.batch} if args.batch else {}
    backend = get_backend("sharded", **kwargs)
    try:
        miner = PodMiner(is_leader=is_leader, backend=backend, chunk=args.chunk)
    except ValueError as e:
        # The pod is already broken (config mismatch); a normal exit would
        # hang in jax.distributed's atexit barrier waiting for peers that
        # will never agree — leave immediately and loudly.
        import os

        print(f"pod startup failed: {e}", file=sys.stderr, flush=True)
        os._exit(2)
    logging.info(
        "pod process %d/%d: %d global devices, %s",
        args.host_id,
        args.num_hosts,
        backend.n_devices,
        "leader" if is_leader else "follower",
    )
    if watchdog is not None:
        miner.heartbeat = watchdog.beat
    if not is_leader:
        try:
            mirrored = miner.follow()
        except Exception as e:
            # Losing the pod mid-collective races two detectors: usually
            # the survivor BLOCKS in the dead collective and the
            # watchdog's no-progress trip exits 3 — but under host
            # contention the runtime can instead RAISE out of the
            # collective first, which used to end the process with a
            # traceback and exit code 1.  Same event, same contract:
            # exit POD_LOST_EXIT either way, so supervisors (and
            # tests/test_pod.py) see one deterministic code.  os._exit,
            # like every other pod death path: a normal return would
            # hang in jax.distributed's atexit barrier.
            import os

            print(f"pod follower lost the mesh: {e}", file=sys.stderr, flush=True)
            os._exit(POD_LOST_EXIT)
        if watchdog is not None:
            watchdog.cancel()
        print(json.dumps({"config": "pod", "role": "follower", "searches": mirrored}))
        return 0
    args.backend = "sharded"  # for run_node's NodeConfig (miner overrides)
    from p1_tpu.node.runner import run_node

    try:
        return asyncio.run(run_node(args, miner=miner))
    finally:
        miner.shutdown()
        if watchdog is not None:
            watchdog.cancel()


# -- balances (engine in chain/tooling.py) --------------------------------


def cmd_balances(args) -> int:
    from p1_tpu.chain.tooling import run_balances

    return run_balances(
        args.store,
        args.account,
        expected_difficulty=args.difficulty,
        retarget=_retarget_rule(args),
    )


# -- compact / fsck / net (engines in chain/tooling.py, node/netharness.py) --


def cmd_compact(args) -> int:
    from p1_tpu.chain.tooling import run_compact

    return run_compact(args.store, args.out, retarget=_retarget_rule(args))


def cmd_fsck(args) -> int:
    from p1_tpu.chain.tooling import run_fsck

    return run_fsck(args.store, args.out, json_out=args.json)


def cmd_snapshot(args) -> int:
    from p1_tpu.chain.tooling import run_snapshot

    return run_snapshot(
        args.action,
        args.store,
        args.file,
        interval=args.interval,
        retarget=_retarget_rule(args),
    )


def cmd_serve(args) -> int:
    """Read-only replica worker(s) over a chain store (`p1 serve`).

    Each worker mmaps the store WITHOUT the writer flock (a live node
    keeps appending underneath; the worker's refresh loop follows the
    tail) and answers headers/filters/proof/blocks/status queries behind
    governor admission — node/queryplane.py.  ``--workers N`` forks N
    processes sharing one port via SO_REUSEPORT, so query throughput
    scales with cores.  Prints one JSON line per worker with the bound
    port once serving."""
    import os
    import signal

    from p1_tpu.node.queryplane import serve_replica

    retarget = _retarget_rule(args)
    if args.workers > 1 and args.port == 0:
        print("--workers > 1 needs an explicit --port", file=sys.stderr)
        return 2
    if args.workers < 1:
        print("--workers must be >= 1", file=sys.stderr)
        return 2

    def _addr(spec: str) -> tuple[str, int]:
        host, _, port = spec.rpartition(":")
        return (host or "127.0.0.1", int(port))

    upstreams = [_addr(s) for s in args.bootstrap]
    if upstreams:
        # Cold start BEFORE any worker serves (and before SO_REUSEPORT
        # forks — exactly one process writes the store): PoW-verified
        # skeleton, chunk-verified snapshot pinned to it, adopted filter
        # headers, bodies above the base.  node/provision.py.
        from p1_tpu.node.provision import BootstrapError, bootstrap_store

        try:
            report = asyncio.run(
                bootstrap_store(
                    args.store,
                    upstreams,
                    args.difficulty,
                    retarget=retarget,
                    progress=lambda m: print(f"bootstrap: {m}", file=sys.stderr),
                )
            )
        except (BootstrapError, ConnectionError, OSError, ValueError) as e:
            print(f"serve failed: bootstrap: {e}", file=sys.stderr)
            return 1
        print(json.dumps({"config": "bootstrap", **report}), flush=True)

    def _worker(primary: bool = True) -> int:
        async def _run() -> int:
            try:
                srv = await serve_replica(
                    args.store,
                    args.difficulty,
                    retarget=retarget,
                    host=args.host,
                    port=args.port,
                    refresh_interval_s=args.refresh_interval,
                    reuse_port=args.workers > 1,
                )
            except (OSError, ValueError) as e:
                print(f"serve failed: {e}", file=sys.stderr)
                return 1
            sync = None
            if upstreams and primary:
                # Only the primary worker writes the store; siblings see
                # the appends through their own refresh loops.
                from p1_tpu.chain.store import ChainStore
                from p1_tpu.node.provision import UpstreamSync

                sync_store = ChainStore(args.store, fsync=False)
                sync = UpstreamSync(
                    sync_store,
                    srv.view,
                    upstreams,
                    args.difficulty,
                    retarget=retarget,
                    poll_interval_s=max(args.refresh_interval, 0.25),
                )
                sync.start()
            print(
                json.dumps(
                    {
                        "config": "serve",
                        "port": srv.port,
                        "height": srv.view.tip_height,
                        "records": srv.view.records,
                        "assumed_base": srv.view.assumed_base,
                        "pid": os.getpid(),
                    }
                ),
                flush=True,
            )
            # Graceful drain on SIGTERM: stop accepting, push every live
            # session a final cursor marker, then exit 0 — a wallet sees
            # an ordinary gap event and fails over mid-stream, not a
            # dead socket it must time out on.
            term = asyncio.Event()
            loop = asyncio.get_running_loop()
            try:
                loop.add_signal_handler(signal.SIGTERM, term.set)
            except (NotImplementedError, RuntimeError):
                pass  # non-unix / nested loop: deadline still works
            try:
                if args.deadline is not None:
                    await asyncio.wait_for(term.wait(), args.deadline)
                else:
                    await term.wait()
            except (asyncio.TimeoutError, TimeoutError):
                pass
            except asyncio.CancelledError:
                pass
            finally:
                if sync is not None:
                    await sync.stop()
                    sync_store.close()
                drained = await srv.drain()
                print(
                    json.dumps(
                        {"config": "drain", "sessions": drained,
                         "pid": os.getpid()}
                    ),
                    flush=True,
                )
            return 0

        try:
            return asyncio.run(_run())
        except KeyboardInterrupt:
            return 0

    procs = []
    if args.workers > 1:
        import multiprocessing

        for _ in range(args.workers - 1):
            proc = multiprocessing.Process(
                target=_worker, args=(False,), daemon=True
            )
            proc.start()
            procs.append(proc)
    try:
        return _worker()
    finally:
        for proc in procs:
            proc.terminate()
            proc.join(timeout=5)


def cmd_sim(args) -> int:
    """Run one simulator scenario (node/scenarios.py) and print its
    report as a single JSON line — exit 0 iff the scenario's invariant
    held.  Pure virtual time: the 1000-node default runs in tier-1
    minutes of wall clock on one host."""
    import inspect

    from p1_tpu.node.scenarios import SCENARIOS, run_scenario

    if args.list:
        for name, fn in sorted(SCENARIOS.items()):
            doc = (inspect.getdoc(fn) or "").split(".")[0].replace("\n", " ")
            print(f"{name}: {doc}")
        return 0
    if args.scenario not in SCENARIOS:
        raise SystemExit(
            f"unknown scenario {args.scenario!r}; "
            f"have: {', '.join(sorted(SCENARIOS))} (p1 sim --list)"
        )
    accepted = inspect.signature(SCENARIOS[args.scenario]).parameters
    flag_map = {
        "nodes": args.nodes,
        "joiners": args.joiners,
        "cycles": args.cycles,
        "attackers": args.attackers,
        "region_nodes": args.region_nodes,
        "shards": args.shards,
        "days": args.days,
        # Only passed when disabling: scenarios default telemetry on.
        "telemetry": False if args.no_telemetry else None,
    }
    kwargs = {
        k: v for k, v in flag_map.items() if v is not None and k in accepted
    }
    report = run_scenario(
        args.scenario, seed=args.seed, difficulty=args.difficulty, **kwargs
    )
    print(json.dumps(report))
    return 0 if report.get("ok") else 1


def cmd_chaos(args) -> int:
    """Chaos sweep / repro replay (node/chaos.py).  Exit-code contract:
    0 = every schedule's invariants held, 1 = a violation was found and
    its (shrunk) repro artifact written — or, under --repro, the
    artifact's violation reproduced — 2 = usage error (argparse's own
    exit, plus unreadable/foreign repro files)."""
    from p1_tpu.node import chaos

    if args.repro:
        try:
            report, artifact = chaos.run_repro(args.repro)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2
        hit = sorted({v["invariant"] for v in report["violations"]})
        print(
            json.dumps(
                {
                    "repro": args.repro,
                    "expected": artifact["expected_violations"],
                    "observed": hit,
                    "trace_digest": report["trace_digest"],
                    "digest_match": report["trace_digest"]
                    == artifact["expected_trace_digest"],
                    "reproduced": bool(hit),
                }
            )
        )
        return 1 if hit else 0
    digests = []
    for seed in range(args.seed, args.seed + args.schedules):
        events = chaos.generate_schedule(seed, args.nodes, args.events)
        report = chaos.run_chaos(
            seed,
            nodes=args.nodes,
            events=events,
            difficulty=args.difficulty,
            inject_bug=args.inject_bug,
        )
        digests.append(report["trace_digest"])
        if report["ok"]:
            continue
        target = report["violations"][0]["invariant"]
        shrunk, runs = events, 0
        if not args.no_shrink:

            def reproduces(subset):
                rep = chaos.run_chaos(
                    seed,
                    nodes=args.nodes,
                    events=subset,
                    difficulty=args.difficulty,
                    inject_bug=args.inject_bug,
                )
                return any(
                    v["invariant"] == target for v in rep["violations"]
                )

            shrunk, runs = chaos.shrink_schedule(events, reproduces)
        final = chaos.run_chaos(
            seed,
            nodes=args.nodes,
            events=shrunk,
            difficulty=args.difficulty,
            inject_bug=args.inject_bug,
        )
        chaos.write_repro(
            args.out,
            final,
            shrunk,
            seed=seed,
            nodes=args.nodes,
            difficulty=args.difficulty,
            inject_bug=args.inject_bug,
        )
        print(
            json.dumps(
                {
                    "ok": False,
                    "seed": seed,
                    "violations": final["violations"],
                    "schedule_events": len(events),
                    "shrunk_events": len(shrunk),
                    "shrink_runs": runs,
                    "repro": args.out,
                    "trace_digest": final["trace_digest"],
                }
            )
        )
        return 1
    print(
        json.dumps(
            {
                "ok": True,
                "schedules": args.schedules,
                "seed_first": args.seed,
                "nodes": args.nodes,
                "events_per_schedule": args.events,
                "trace_digests": digests,
            }
        )
    )
    return 0


def cmd_net(args) -> int:
    from p1_tpu.node.netharness import run_net

    return run_net(args)


def cmd_lint(args) -> int:
    """`p1 lint`: the AST determinism/async-safety pass (p1_tpu/analysis).

    Exit-code contract (tests/test_cli.py pins it): 0 = every rule
    clean (no unallowlisted findings, no stale grants), 1 = violations,
    2 = usage (argparse errors, unknown --rule names, bad --path)."""
    from pathlib import Path

    from p1_tpu.analysis import RULES, run_analysis
    from p1_tpu.analysis.allowlist import GRANTS
    from p1_tpu.analysis.engine import PKG_ROOT

    if args.rule:
        unknown = [r for r in args.rule if r not in RULES]
        if unknown:
            print(
                f"p1 lint: unknown rule(s) {', '.join(sorted(unknown))} "
                f"(have: {', '.join(sorted(RULES))})",
                file=sys.stderr,
            )
            return 2
        rules = [RULES[r] for r in args.rule]
    else:
        rules = None

    paths = None
    if args.path:
        paths = []
        for raw in args.path:
            # package-relative spellings first (the common pre-commit
            # case: `p1 lint --path node/node.py` from anywhere), then
            # real filesystem paths.
            p = (PKG_ROOT / raw).resolve()
            if not p.exists():
                p = Path(raw).resolve()
            if not p.exists():
                print(f"p1 lint: no such path: {raw}", file=sys.stderr)
                return 2
            try:
                rel = p.relative_to(PKG_ROOT).as_posix()
            except ValueError:
                print(
                    f"p1 lint: {raw} is outside the analyzed package "
                    f"({PKG_ROOT})",
                    file=sys.stderr,
                )
                return 2
            if rel == ".":
                continue  # the whole package: no constraint
            paths.append(rel + "/" if p.is_dir() else rel)
        paths = paths or None

    report = run_analysis(rules=rules, paths=paths)
    if args.as_json:
        print(json.dumps(report.to_json()))
    else:
        for f in report.violations:
            print(f)
        for s in report.stale:
            print(f"stale grant: {s}")
        for e in report.parse_errors:
            print(f"parse error: {e}")
        if args.granted:
            for f in report.granted:
                reason = GRANTS[f.rule][f.file][f.key]
                print(f"granted: {f}  [{reason}]")
        scoped = (
            f", scoped to {', '.join(report.scoped_to)}"
            if report.scoped_to
            else ""
        )
        print(
            f"p1 lint: {report.files} files, {len(report.rules)} rules, "
            f"{len(report.violations)} violation(s), "
            f"{len(report.granted)} granted, {len(report.stale)} stale "
            f"grant(s){scoped}"
        )
    return 0 if report.clean else 1


def cmd_bench(args) -> int:
    # bench.py lives at the repo root (the driver contract), one level above
    # the package — resolve it by path so `p1 bench` works from any cwd.
    import importlib.util
    from pathlib import Path

    bench_path = Path(__file__).resolve().parent.parent / "bench.py"
    spec = importlib.util.spec_from_file_location("bench", bench_path)
    assert spec is not None and spec.loader is not None
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    bench.main()
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        stream=sys.stderr,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    handler = {
        "mine": cmd_mine,
        "sweep": cmd_sweep,
        "replay": cmd_replay,
        "node": cmd_node,
        "status": cmd_status,
        "metrics": cmd_metrics,
        "maintain": cmd_maintain,
        "tx": cmd_tx,
        "keygen": cmd_keygen,
        "account": cmd_account,
        "proof": cmd_proof,
        "fees": cmd_fees,
        "headers": cmd_headers,
        "watch": cmd_watch,
        "balances": cmd_balances,
        "compact": cmd_compact,
        "fsck": cmd_fsck,
        "snapshot": cmd_snapshot,
        "serve": cmd_serve,
        "pod": cmd_pod,
        "net": cmd_net,
        "sim": cmd_sim,
        "chaos": cmd_chaos,
        "lint": cmd_lint,
        "bench": cmd_bench,
    }[args.cmd]
    return handler(args)


if __name__ == "__main__":
    sys.exit(main())
