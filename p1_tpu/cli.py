"""Command line: run nodes, miners, replays, and benchmarks.

SURVEY.md §7 step 7 — every benchmark config reproducible from one command
(BASELINE.json:6-12):

  config 1/2: p1 mine   --difficulty 16 --blocks 10 --backend jax
  config 3:   p1 replay --n 10000 --difficulty 12
  config 4:   p1 net    --nodes 4 --difficulty 20 --duration 10
  one node:   p1 node   --port 9444 --peers host:port --mine
  headline:   p1 bench

(``p1`` = ``python -m p1_tpu``.)  Structured logs go to stderr; metric
output is JSON on stdout, one object per line, so the driver and shell
pipelines can consume it.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import statistics
import sys
import time


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--difficulty", type=int, default=16)
    p.add_argument(
        "--backend",
        default="cpu",
        help="hash backend registry name (cpu, numpy, jax, sharded, ...)",
    )
    p.add_argument("--batch", type=int, default=None, help="device batch override")
    p.add_argument("--chunk", type=int, default=None, help="miner abort granularity")


def _add_retarget(p: argparse.ArgumentParser) -> None:
    """Chain-identity flags for opt-in difficulty retargeting.  They ride
    every command that selects a chain (node/net and the wallet tools):
    the rule is committed into genesis, so a client that omits them cannot
    even handshake with a retargeting node."""
    p.add_argument(
        "--retarget-window",
        type=int,
        default=0,
        help="adjust difficulty every N blocks (0 = fixed difficulty; "
        "all chain participants must agree — the rule is part of the "
        "chain's genesis identity)",
    )
    p.add_argument(
        "--target-spacing",
        type=int,
        default=0,
        help="target seconds per block for retargeting (set together "
        "with --retarget-window)",
    )


def _fee_arg(value: str):
    """--fee: an integer or the literal 'auto' — validated by argparse so
    a typo is a usage error, not a runtime failure after other work."""
    if value == "auto":
        return value
    try:
        return int(value)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"fee must be an integer or 'auto', got {value!r}"
        )


def _retarget_rule(args):
    """The ``RetargetRule`` selected by the flags, or None (fixed) — flag
    validation lives in ``RetargetRule.from_params``; here only the
    ValueError→SystemExit mapping."""
    from p1_tpu.core.retarget import RetargetRule

    try:
        return RetargetRule.from_params(
            getattr(args, "retarget_window", 0),
            getattr(args, "target_spacing", 0),
        )
    except ValueError as e:
        raise SystemExit(str(e))


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="p1_tpu", description="TPU-native proof-of-work blockchain node"
    )
    parser.add_argument("-v", "--verbose", action="store_true")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("mine", help="mine N blocks from genesis (configs 1/2)")
    _add_common(p)
    p.add_argument("--blocks", type=int, default=10)
    p.add_argument(
        "--profile",
        default=None,
        metavar="DIR",
        help="capture a jax.profiler device trace of the mining loop into "
        "DIR (view with tensorboard or xprof)",
    )

    p = sub.add_parser(
        "sweep", help="difficulty sweep: time-to-block scaling (config 2)"
    )
    _add_common(p)
    p.add_argument(
        "--difficulties",
        default="16:25",
        help="half-open range LO:HI (e.g. 16:25) or comma list (16,20,24)",
    )
    p.add_argument("--blocks", type=int, default=5, help="blocks per difficulty")

    p = sub.add_parser("replay", help="generate+verify a header chain (config 3)")
    _add_common(p)
    p.add_argument("--n", type=int, default=10_000)
    p.add_argument(
        "--method",
        choices=["host", "native", "device", "both", "all"],
        default="both",
        help="verification engine(s): host=hashlib oracle, native=C++ "
        "SHA-NI, device=one-dispatch lax.scan; both=host+device, all=every "
        "engine",
    )
    p.add_argument("--out", default=None, help="write generated headers here")
    p.add_argument("--verify", default=None, help="verify this header file instead")
    _add_retarget(p)

    p = sub.add_parser("node", help="run one p2p node")
    _add_common(p)
    p.add_argument(
        "--platform",
        default=None,
        help="pin the JAX platform (e.g. cpu) before backend init — the "
        "axon sitecustomize overrides the JAX_PLATFORMS env var, so an "
        "explicit pin is the only reliable way to force CPU",
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9444)
    p.add_argument("--peers", nargs="*", default=[], help="host:port ...")
    p.add_argument("--no-mine", action="store_true")
    p.add_argument(
        "--miner-id",
        default=None,
        help="coinbase recipient id (default: random per process)",
    )
    p.add_argument("--store", default=None, help="chain persistence path")
    p.add_argument(
        "--revalidate-store",
        action="store_true",
        help="re-run full stateless validation (PoW, merkle, Ed25519) "
        "over the stored chain at boot instead of the trusted fast "
        "resume (the store is this node's own validated, flocked log)",
    )
    p.add_argument(
        "--store-degraded-exit",
        action="store_true",
        help="exit (code 4) on the first store write failure instead of "
        "the default degraded serve-only mode (which keeps answering "
        "headers/blocks/proof queries while retrying the disk with "
        "backoff) — for operators who prefer a supervisor restart",
    )
    p.add_argument("--duration", type=float, default=None, help="exit after N s")
    p.add_argument(
        "--deadline",
        default=None,
        help="unix time to stop mining at (overrides --duration; lets a "
        "multi-process net quiesce simultaneously), or 'stdin' to print a "
        "ready line and read the deadline from stdin once the parent has "
        "seen every node come up (interpreter startup on a loaded host "
        "can cost many seconds, so parent-computed wall times are unsafe)",
    )
    p.add_argument("--status-interval", type=float, default=10.0)
    p.add_argument(
        "--no-compact-gossip",
        action="store_true",
        help="push full BLOCK frames instead of compact blocks (local "
        "preference; compact and full nodes interoperate)",
    )
    p.add_argument(
        "--mempool-ttl",
        type=float,
        default=3600.0,
        help="drop pool transactions older than this many seconds "
        "(hygiene for unmineable spends; 0 = never)",
    )
    p.add_argument(
        "--target-peers",
        type=int,
        default=0,
        help="peer-discovery out-degree: dial addresses learned via "
        "GETADDR/ADDR gossip until this many connections hold (0 = only "
        "the configured --peers; one seed peer bootstraps the rest)",
    )
    p.add_argument(
        "--handshake-timeout",
        type=float,
        default=10.0,
        help="seconds a new connection gets to complete HELLO before "
        "being reaped (liveness layer)",
    )
    p.add_argument(
        "--ping-interval",
        type=float,
        default=60.0,
        help="probe a peer with PING after this many seconds of silence; "
        "any received frame counts as liveness",
    )
    p.add_argument(
        "--pong-timeout",
        type=float,
        default=20.0,
        help="seconds of continued silence after a PING probe before the "
        "peer is evicted and its slot reused",
    )
    p.add_argument(
        "--sync-stall-timeout",
        type=float,
        default=10.0,
        help="progress deadline on an in-flight chain/mempool sync: a "
        "peer that advances nothing (blocks accepted, pages consumed — "
        "not mere liveness) within this window is demoted and the "
        "request re-issued to another peer (0 disables supervision)",
    )
    p.add_argument(
        "--sync-attempts",
        type=int,
        default=8,
        help="failover budget per catch-up episode: consecutive "
        "no-progress re-issues before the node stops chasing and waits "
        "for a fresh sync trigger (progress resets the budget)",
    )
    _add_retarget(p)

    p = sub.add_parser("tx", help="submit a signed transaction to a running node")
    p.add_argument("--difficulty", type=int, default=16, help="chain selector")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9444)
    p.add_argument(
        "--key",
        required=True,
        help="sender key file from `p1 keygen` (the sender id is the "
        "key's account fingerprint — spends are signed, not asserted)",
    )
    p.add_argument("--recipient", required=True)
    p.add_argument("--amount", type=int, required=True)
    p.add_argument(
        "--fee",
        type=_fee_arg,
        default=1,
        help="fee units, or 'auto' to price at the node's recent "
        "confirmed-fee median (floor 1)",
    )
    p.add_argument(
        "--max-fee",
        type=int,
        default=100,
        help="refuse an --fee auto quote above this many units — the "
        "quote is peer-supplied, and a hostile or broken node must not "
        "be able to price a wallet's spend unbounded (explicit --fee N "
        "is never capped: the user stated the number)",
    )
    p.add_argument(
        "--seq",
        type=int,
        default=None,
        help="account nonce to spend (consensus requires the sender's "
        "exact next nonce; default: query the node via GETACCOUNT and "
        "use its next usable seq)",
    )
    _add_retarget(p)

    p = sub.add_parser(
        "account",
        help="query an account's balance/nonce from a running node",
    )
    p.add_argument("--difficulty", type=int, default=16, help="chain selector")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9444)
    p.add_argument(
        "--account", default=None, help="account id (or use --key)"
    )
    p.add_argument(
        "--key", default=None, help="key file; queries its fingerprint account"
    )
    _add_retarget(p)

    p = sub.add_parser(
        "fees", help="query confirmed-fee percentiles from a running node"
    )
    p.add_argument("--difficulty", type=int, default=16, help="chain selector")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9444)
    p.add_argument(
        "--window", type=int, default=0, help="blocks to sample (0 = node default)"
    )
    _add_retarget(p)

    p = sub.add_parser(
        "proof",
        help="fetch + SPV-verify a transaction inclusion proof from a node",
    )
    p.add_argument("--difficulty", type=int, default=16, help="chain selector")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9444)
    p.add_argument(
        "--txid", required=True, help="hex txid (printed by `p1 tx`)"
    )
    p.add_argument(
        "--headers",
        default=None,
        metavar="FILE",
        help="anchor the proof against a locally verified header chain "
        "(from `p1 headers --out FILE`) instead of trusting the peer's "
        "tip claim — full light-client confirmation",
    )
    _add_retarget(p)

    p = sub.add_parser(
        "headers",
        help="light client: fetch + locally verify a node's header chain",
    )
    p.add_argument("--difficulty", type=int, default=16, help="chain selector")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9444)
    p.add_argument(
        "--out", default=None, help="write the verified headers here "
        "(80 bytes each; feeds `p1 replay --verify` and `p1 proof --headers`)"
    )
    p.add_argument(
        "--stall-timeout",
        type=float,
        default=15.0,
        help="per-round progress deadline: a GETHEADERS round that grows "
        "nothing within this window abandons the session and retries "
        "(against --fallback peers, round-robin, when given)",
    )
    p.add_argument(
        "--fallback",
        nargs="*",
        default=[],
        help="host:port alternates to fail over to when the primary "
        "stalls mid-sync (accumulated headers are kept)",
    )
    _add_retarget(p)

    p = sub.add_parser(
        "keygen", help="create an Ed25519 spending key (account = fingerprint)"
    )
    p.add_argument("--out", required=True, help="key file to write (0600)")
    p.add_argument(
        "--seed-text",
        default=None,
        help="derive deterministically from this label (TESTS ONLY: the "
        "seed is sha256(label), so the account is publicly spendable)",
    )
    p.add_argument(
        "--force",
        action="store_true",
        help="overwrite an existing key file (DESTROYS the old seed — "
        "coins held by its account become unspendable)",
    )

    p = sub.add_parser(
        "balances", help="account balances from a persisted chain"
    )
    p.add_argument(
        "--difficulty",
        type=int,
        default=None,
        help="chain selector (default: inferred from the store's records)",
    )
    p.add_argument("--store", required=True, help="chain persistence path")
    p.add_argument(
        "--account", default=None, help="print one account instead of all"
    )
    _add_retarget(p)

    p = sub.add_parser(
        "pod",
        help="multi-host pod miner: N processes, one miner on the network",
    )
    # Not _add_common: the pod always runs the sharded mesh backend, so a
    # --backend flag would be a silent no-op.  chunk/batch MUST match
    # across processes (PodMiner validates at startup).
    p.add_argument("--difficulty", type=int, default=16)
    p.add_argument("--batch", type=int, default=None, help="per-device batch")
    p.add_argument("--chunk", type=int, default=None, help="miner abort granularity")
    p.add_argument("--coordinator", required=True, help="host:port of process 0")
    p.add_argument("--num-hosts", type=int, required=True)
    p.add_argument("--host-id", type=int, required=True)
    p.add_argument(
        "--platform",
        default=None,
        help="pin the JAX platform (e.g. cpu) before distributed init",
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0, help="leader's p2p port")
    p.add_argument("--peers", nargs="*", default=[], help="host:port ...")
    p.add_argument("--miner-id", default=None)
    p.add_argument("--store", default=None)
    p.add_argument(
        "--duration",
        type=float,
        default=None,
        help="leader: stop mining after N s; both roles also arm a "
        "peer-loss watchdog (force-exit after 600s with no lockstep "
        "progress — the grace covers first-search jit compile)",
    )
    p.set_defaults(no_mine=False, deadline=None, status_interval=10.0)

    p = sub.add_parser(
        "compact", help="rewrite a chain store to just its main branch"
    )
    p.add_argument("--store", required=True, help="chain persistence path")
    p.add_argument(
        "--out",
        default=None,
        help="write here instead of replacing the store in place",
    )
    _add_retarget(p)

    p = sub.add_parser(
        "fsck",
        help="scan a chain store offline: report per-record integrity and "
        "salvage every checksum-valid record into a fresh verified store "
        "(also upgrades v2 stores to the checksummed v3 framing); exit 0 "
        "= clean, 1 = salvaged with losses, 2 = unrecoverable",
    )
    p.add_argument("--store", required=True, help="chain persistence path")
    p.add_argument(
        "--out",
        default=None,
        help="write the salvaged store here instead of replacing in place",
    )

    p = sub.add_parser("net", help="N-node localhost net (config 4)")
    _add_common(p)
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument("--duration", type=float, default=10.0)
    p.add_argument("--base-port", type=int, default=19444)
    p.add_argument(
        "--byzantine",
        type=int,
        default=0,
        help="run this many actively malicious participants alongside "
        "the honest mesh (invalid signatures, overdraws, replays, "
        "forged compact blocks, ADDR spam, oversized frames — each "
        "from its own loopback alias so bans land on the attacker); "
        "the summary asserts the honest net converged, conserved, "
        "banned them, and stayed within memory bounds",
    )
    p.add_argument(
        "--tx-rate",
        type=float,
        default=0.0,
        help="inject ~R signed transfers/sec between the miners' accounts "
        "during the run (each node mines to a keyed account); the summary "
        "then audits ledger conservation (sum == reward x height) on "
        "every node",
    )
    p.add_argument(
        "--no-compact-gossip",
        action="store_true",
        help="children push full BLOCK frames instead of compact blocks",
    )
    p.add_argument(
        "--discover",
        action="store_true",
        help="bootstrap the topology via peer discovery: every node dials "
        "ONLY node 0 and must find the rest through GETADDR/ADDR (vs the "
        "default statically configured full mesh)",
    )
    _add_retarget(p)

    sub.add_parser("bench", help="headline benchmark (one JSON line)")
    return parser


# -- mine ----------------------------------------------------------------


def _mine_chain(miner, difficulty: int, blocks: int):
    """Mine ``blocks`` headers from genesis; return (times, total_hashes)."""
    from p1_tpu.core.genesis import make_genesis
    from p1_tpu.core.header import BlockHeader

    if blocks < 1:
        raise SystemExit("--blocks must be >= 1")

    tip = make_genesis(difficulty).header
    times, hashes = [], 0
    for height in range(1, blocks + 1):
        draft = BlockHeader(
            1, tip.block_hash(), bytes(32), tip.timestamp + 1, difficulty, 0
        )
        t0 = time.perf_counter()
        sealed = miner.search_nonce(draft)
        dt = time.perf_counter() - t0
        assert sealed is not None
        times.append(dt)
        hashes += miner.last_stats.hashes_done
        logging.info(
            "block d=%d height=%d nonce=%d t=%.3fs hps=%.0f",
            difficulty,
            height,
            sealed.nonce,
            dt,
            miner.last_stats.hashes_per_sec,
        )
        tip = sealed
    return times, hashes


def cmd_mine(args) -> int:
    import contextlib

    from p1_tpu.hashx import get_backend
    from p1_tpu.miner import Miner

    kwargs = {"batch": args.batch} if args.batch else {}
    miner = Miner(backend=get_backend(args.backend, **kwargs), chunk=args.chunk)
    if args.profile:
        # SURVEY.md §5 tracing: a device trace of the real mining loop.
        # One warmup block first so the trace shows steady-state steps,
        # not Mosaic/XLA compilation.
        import jax

        _mine_chain(miner, args.difficulty, 1)
        profile_ctx = jax.profiler.trace(args.profile)
        logging.info("profiling mining loop into %s", args.profile)
    else:
        profile_ctx = contextlib.nullcontext()
    with profile_ctx:
        times, hashes = _mine_chain(miner, args.difficulty, args.blocks)
    total = sum(times)
    print(
        json.dumps(
            {
                "config": "mine",
                "backend": args.backend,
                "difficulty": args.difficulty,
                "blocks": args.blocks,
                "hashes_per_sec": round(hashes / total) if total else 0,
                "time_to_block_s": round(statistics.median(times), 4),
                "total_s": round(total, 3),
                **({"profile_dir": args.profile} if args.profile else {}),
            }
        )
    )
    return 0


def _parse_difficulties(spec: str) -> list[int]:
    try:
        if ":" in spec:
            lo, _, hi = spec.partition(":")
            out = list(range(int(lo), int(hi)))
        else:
            out = [int(d) for d in spec.split(",") if d]
    except ValueError:
        out = []
    if not out or not all(0 <= d <= 255 for d in out):
        raise SystemExit(
            f"bad difficulty spec {spec!r} (want LO:HI or a comma list)"
        )
    return out


def cmd_sweep(args) -> int:
    """Benchmark config 2: nonce-space scaling across difficulties.

    One JSON line per difficulty with median time-to-block and the
    aggregate hash rate, so the scaling curve (time ~ 2^d / rate, floored
    by dispatch latency) is reproducible from a single command.
    """
    from p1_tpu.hashx import get_backend
    from p1_tpu.miner import Miner

    kwargs = {"batch": args.batch} if args.batch else {}
    miner = Miner(backend=get_backend(args.backend, **kwargs), chunk=args.chunk)
    for difficulty in _parse_difficulties(args.difficulties):
        times, hashes = _mine_chain(miner, difficulty, args.blocks)
        total = sum(times)
        print(
            json.dumps(
                {
                    "config": "sweep",
                    "backend": args.backend,
                    "difficulty": difficulty,
                    "blocks": args.blocks,
                    "time_to_block_s": round(statistics.median(times), 4),
                    "hashes_per_sec": round(hashes / total) if total else 0,
                    "total_s": round(total, 3),
                }
            ),
            flush=True,
        )
    return 0


# -- replay --------------------------------------------------------------


def cmd_replay(args) -> int:
    from p1_tpu.chain import (
        generate_headers,
        replay_device,
        replay_host,
        replay_native,
    )
    from p1_tpu.core.header import HEADER_SIZE, BlockHeader
    from p1_tpu.hashx import get_backend

    rule = _retarget_rule(args)
    if rule is not None and args.method in ("device", "both"):
        # The host oracle and the C++ engine are both retarget-aware
        # (chain/replay.py, native p1_verify_chain_retarget); the DEVICE
        # tier implements the benchmark-config form (fixed difficulty:
        # the lax.scan carries one target) and would mis-report an
        # honest retargeting chain as invalid at the first adjustment.
        print(
            "retargeting chains verify with --method host/native/all "
            "(the device engine is fixed-difficulty)",
            file=sys.stderr,
        )
        return 2
    if args.verify:
        raw = open(args.verify, "rb").read()
        if len(raw) % HEADER_SIZE:
            print(f"{args.verify}: not a multiple of {HEADER_SIZE} bytes", file=sys.stderr)
            return 2
        headers = [
            BlockHeader.deserialize(raw[i : i + HEADER_SIZE])
            for i in range(0, len(raw), HEADER_SIZE)
        ]
        # Pin the file to the chain the operator selected: header[0] is
        # otherwise SELF-attested, and a forged file whose genesis claims
        # difficulty 1 would "verify" with no meaningful work behind it —
        # fatal for the light-client escalation path this command serves.
        from p1_tpu.core.genesis import make_genesis

        if (
            not headers
            or headers[0].block_hash()
            != make_genesis(args.difficulty, rule).block_hash()
        ):
            print(
                f"{args.verify}: does not start at this chain's genesis "
                "(check --difficulty / retarget flags)",
                file=sys.stderr,
            )
            return 2
    else:
        kwargs = {"batch": args.batch} if args.batch else {}
        backend = get_backend(args.backend, **kwargs)
        t0 = time.perf_counter()
        headers = generate_headers(
            args.n, args.difficulty, backend=backend, retarget=rule
        )
        logging.info("generated %d headers in %.1fs", args.n, time.perf_counter() - t0)
        if args.out:
            with open(args.out, "wb") as fh:
                for h in headers:
                    fh.write(h.serialize())

    reports = []
    if args.method in ("host", "both", "all"):
        reports.append(replay_host(headers, retarget=rule))
    if args.method in ("native", "all"):
        reports.append(replay_native(headers, retarget=rule))
    if args.method in ("device", "both", "all") and rule is None:
        # Fixed-difficulty only (the guard above rejects explicit device
        # requests on retargeting chains; `all` quietly covers what can
        # run: host + native).
        reports.append(replay_device(headers))
        reports.append(replay_device(headers))  # warm (compile amortized)
    ok = all(r.valid for r in reports)
    print(
        json.dumps(
            {
                "config": "replay",
                "n_headers": len(headers),
                "valid": ok,
                "first_invalid": next(
                    (r.first_invalid for r in reports if not r.valid), None
                ),
                "results": [
                    {
                        "method": r.method,
                        "headers_per_sec": round(r.headers_per_sec),
                        "elapsed_s": round(r.elapsed_s, 4),
                    }
                    for r in reports
                ],
            }
        )
    )
    return 0 if ok else 1


# -- node ----------------------------------------------------------------


async def _run_node(args, miner=None) -> int:
    from p1_tpu.config import NodeConfig
    from p1_tpu.node import Node

    config = NodeConfig(
        difficulty=args.difficulty,
        backend=args.backend,
        host=args.host,
        port=args.port,
        peers=tuple(args.peers),
        mine=not args.no_mine,
        store_path=args.store,
        batch=args.batch,
        chunk=args.chunk,
        miner_id=args.miner_id,
        # getattr: `p1 pod` reuses this runner with its own arg namespace,
        # which has no retarget or compact-gossip flags (pod mining is
        # fixed-difficulty — config 5's shape).
        retarget_window=getattr(args, "retarget_window", 0),
        target_spacing=getattr(args, "target_spacing", 0),
        compact_gossip=not getattr(args, "no_compact_gossip", False),
        target_peers=getattr(args, "target_peers", 0),
        mempool_ttl_s=getattr(args, "mempool_ttl", 3600.0),
        handshake_timeout_s=getattr(args, "handshake_timeout", 10.0),
        ping_interval_s=getattr(args, "ping_interval", 60.0),
        pong_timeout_s=getattr(args, "pong_timeout", 20.0),
        sync_stall_timeout_s=getattr(args, "sync_stall_timeout", 10.0),
        sync_attempts_max=getattr(args, "sync_attempts", 8),
        revalidate_store=getattr(args, "revalidate_store", False),
        store_degraded_exit=getattr(args, "store_degraded_exit", False),
    )
    node = Node(config, miner=miner)
    await node.start()
    # --store-degraded-exit watch: the node signals instead of exiting
    # itself so teardown (final status line, mempool save, store close)
    # still runs through the one path below.  Exit code 4.
    fatal = asyncio.ensure_future(node.store_fatal.wait())
    rc = 0
    try:
        if args.deadline is not None or args.duration is not None:
            if args.deadline == "stdin":
                print(json.dumps({"ready": node.port}), flush=True)
                loop = asyncio.get_running_loop()
                line = await loop.run_in_executor(None, sys.stdin.readline)
                deadline = float(line.strip())
            elif args.deadline is not None:
                deadline = float(args.deadline)
            else:
                deadline = time.time() + args.duration
            window = max(0.0, deadline - time.time())
            logging.info("mining window: %.2fs until deadline", window)
            await asyncio.wait({fatal}, timeout=window)
            if fatal.done():
                rc = 4
            else:
                # Quiesce: stop producing, then wait for the gossip
                # backlog to drain (GIL-bound mining starves the event
                # loop, so a fixed sleep can undershoot): exit once the
                # chain has been stable for a full second, or after 20s
                # regardless.
                await node.stop_mining()
                await node.request_sync()
                t_end = time.monotonic() + 20.0
                stable = (node.chain.tip_hash, node.metrics.blocks_accepted)
                stable_since = time.monotonic()
                while time.monotonic() < t_end:
                    await asyncio.sleep(0.1)
                    now_state = (
                        node.chain.tip_hash,
                        node.metrics.blocks_accepted,
                    )
                    if now_state != stable:
                        stable, stable_since = now_state, time.monotonic()
                        await node.request_sync()
                    elif time.monotonic() - stable_since >= 1.0:
                        break
        else:
            while True:
                await asyncio.wait({fatal}, timeout=args.status_interval)
                if fatal.done():
                    rc = 4
                    break
                print(json.dumps(node.status()), flush=True)
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    finally:
        fatal.cancel()
        print(json.dumps(node.status()), flush=True)
        await node.stop()
    return rc


def cmd_node(args) -> int:
    _retarget_rule(args)  # flag-pair validation: clean error, no traceback
    # The CPU miner thread is GIL-bound (hashlib holds the GIL for
    # 80-byte messages) and the default 5 ms switch interval lets it
    # convoy the event loop hard enough that a wallet's HELLO can starve
    # past its 10 s timeout on 1-vCPU hosts (observed live).  A 0.5 ms
    # interval hands the loop the GIL ~10x more often for a few percent
    # of hash throughput — only worth paying in the node process, where
    # p2p responsiveness under mining load is the product.
    sys.setswitchinterval(0.0005)
    if getattr(args, "platform", None):
        import jax

        jax.config.update("jax_platforms", args.platform)
    try:
        return asyncio.run(_run_node(args))
    except KeyboardInterrupt:
        return 0


# -- tx ------------------------------------------------------------------


def cmd_tx(args) -> int:
    from p1_tpu.core.keys import Keypair
    from p1_tpu.core.tx import Transaction
    from p1_tpu.node.client import send_tx

    try:
        from p1_tpu.core.genesis import genesis_hash
        from p1_tpu.node.client import get_account

        key = Keypair.load(args.key)
        rule = _retarget_rule(args)
        if args.fee == "auto":
            from p1_tpu.node.client import get_fees

            stats = asyncio.run(
                get_fees(args.host, args.port, args.difficulty, retarget=rule)
            )
            fee = max(1, stats.p50)
            if fee > args.max_fee:
                # The quote is the PEER's number; signing it unseen would
                # let one hostile node drain the account through fees.
                print(
                    f"refusing auto fee {fee} above --max-fee "
                    f"{args.max_fee} (node quote p50={stats.p50} over "
                    f"{stats.samples} samples); pass an explicit --fee "
                    f"or raise --max-fee to accept",
                    file=sys.stderr,
                )
                return 2
        else:
            fee = args.fee
        seq = args.seq
        if seq is None:
            # Wallet convenience: consensus wants the exact next nonce, so
            # ask the node (chain nonce advanced past its pending pool).
            state = asyncio.run(
                get_account(
                    args.host,
                    args.port,
                    key.account,
                    args.difficulty,
                    retarget=rule,
                )
            )
            seq = state.next_seq
        tx = Transaction.transfer(
            key,
            args.recipient,
            args.amount,
            fee,
            seq,
            chain=genesis_hash(args.difficulty, rule),
        )
        height = asyncio.run(
            send_tx(args.host, args.port, tx, args.difficulty, retarget=rule)
        )
    except (
        ConnectionError,
        OSError,
        ValueError,
        asyncio.TimeoutError,
        asyncio.IncompleteReadError,  # clean close mid-handshake (EOFError)
    ) as e:
        print(f"tx submission failed: {e}", file=sys.stderr)
        return 1
    print(
        json.dumps(
            {
                "config": "tx",
                "txid": tx.txid().hex(),
                "sender": tx.sender,
                "seq": seq,
                "fee": fee,
                "peer_height": height,
            }
        )
    )
    return 0


# -- account -------------------------------------------------------------


def cmd_account(args) -> int:
    from p1_tpu.core.keys import Keypair
    from p1_tpu.node.client import get_account

    if (args.account is None) == (args.key is None):
        print("pass exactly one of --account / --key", file=sys.stderr)
        return 2
    try:
        account = args.account or Keypair.load(args.key).account
        state = asyncio.run(
            get_account(
                args.host,
                args.port,
                account,
                args.difficulty,
                retarget=_retarget_rule(args),
            )
        )
    except (
        ConnectionError,
        OSError,
        ValueError,
        asyncio.TimeoutError,
        asyncio.IncompleteReadError,
    ) as e:
        print(f"account query failed: {e}", file=sys.stderr)
        return 1
    print(
        json.dumps(
            {
                "config": "account",
                "account": state.account,
                "balance": state.balance,
                "nonce": state.nonce,
                "next_seq": state.next_seq,
                "height": state.tip_height,
            }
        )
    )
    return 0


# -- fees ----------------------------------------------------------------


def cmd_fees(args) -> int:
    from p1_tpu.node.client import get_fees

    try:
        stats = asyncio.run(
            get_fees(
                args.host,
                args.port,
                args.difficulty,
                window=args.window,
                retarget=_retarget_rule(args),
            )
        )
    except (
        ConnectionError,
        OSError,
        ValueError,
        asyncio.TimeoutError,
        asyncio.IncompleteReadError,
    ) as e:
        print(f"fee query failed: {e}", file=sys.stderr)
        return 1
    print(
        json.dumps(
            {
                "config": "fees",
                "window_blocks": stats.window_blocks,
                "samples": stats.samples,
                "p25": stats.p25,
                "p50": stats.p50,
                "p75": stats.p75,
                "suggested_fee": max(1, stats.p50),
                "height": stats.tip_height,
            }
        )
    )
    return 0


# -- proof ---------------------------------------------------------------


def cmd_proof(args) -> int:
    """Fetch an SPV inclusion proof and verify it CLIENT-SIDE.

    Exit codes: 0 = confirmed and proof verifies; 1 = query failed;
    3 = not confirmed on the peer's main chain; 4 = the peer served a
    proof that FAILS verification (a lying or broken peer — loud exit).
    """
    from p1_tpu.chain.proof import SPVError, verify_tx_proof
    from p1_tpu.core.genesis import genesis_hash
    from p1_tpu.node.client import get_proof

    try:
        rule = _retarget_rule(args)
        txid = bytes.fromhex(args.txid)
        if len(txid) != 32:
            raise ValueError("txid must be 32 hex-encoded bytes")
        proof = asyncio.run(
            get_proof(
                args.host, args.port, txid, args.difficulty, retarget=rule
            )
        )
    except (
        ConnectionError,
        OSError,
        ValueError,
        asyncio.TimeoutError,
        asyncio.IncompleteReadError,
    ) as e:
        print(f"proof query failed: {e}", file=sys.stderr)
        return 1
    if proof is None:
        print(json.dumps({"config": "proof", "confirmed": False}))
        return 3
    try:
        verify_tx_proof(
            proof,
            args.difficulty,
            genesis_hash(args.difficulty, rule),
            txid=txid,
            retarget=rule,
        )
    except SPVError as e:
        print(f"peer served an INVALID proof: {e}", file=sys.stderr)
        return 4
    confirmations = proof.confirmations  # the peer's claim...
    anchored = False
    if rule is not None and not args.headers:
        # Retargeting chains verify at the header's claimed difficulty
        # (schedule-floored — chain/proof.py), and height/tip/
        # confirmations are all the peer's claims; only --headers
        # anchoring pins them to a locally verified chain.  Say so
        # loudly rather than letting scripts equate the two modes.
        print(
            "warning: retargeting chain without --headers — proof "
            "verified at its claimed difficulty only, and the height/"
            "confirmation figures are the peer's unverified claims; "
            "anchor against `p1 headers` output for real light-client "
            "verification",
            file=sys.stderr,
        )
    if args.headers:
        # ...unless anchored: the proof's block must sit at its claimed
        # height on a LOCALLY verified header chain, and confirmations are
        # then computed from that chain — no peer claims left anywhere.
        headers = _load_header_file(args.headers, args.difficulty, rule)
        if (
            proof.height >= len(headers)
            or headers[proof.height].block_hash()
            != proof.header.block_hash()
        ):
            print(
                "proof's block is not on the locally verified header "
                "chain (stale, side-branch, or forged)",
                file=sys.stderr,
            )
            return 4
        confirmations = len(headers) - proof.height
        anchored = True
    print(
        json.dumps(
            {
                "config": "proof",
                "confirmed": True,
                "verified": True,
                "txid": args.txid,
                "height": proof.height,
                "confirmations": confirmations,
                "anchored": anchored,
                "block": proof.header.block_hash().hex(),
                # The work bar this evidence meets (== chain difficulty on
                # fixed chains; the header's claim on retargeting chains).
                "difficulty": proof.header.difficulty,
                "index": proof.index,
                "branch_len": len(proof.branch),
                "amount": proof.tx.amount,
                "recipient": proof.tx.recipient,
            }
        )
    )
    return 0


# -- headers -------------------------------------------------------------


def _load_header_file(path: str, difficulty: int, rule):
    """Read + fully verify a header file as this chain's header chain.
    Returns the genesis-first header list; raises SystemExit on any
    failure (wrong chain, bad PoW/linkage/schedule) — a light client must
    never proceed on unverified headers."""
    from p1_tpu.chain import parse_headers, replay_packed
    from p1_tpu.core.genesis import make_genesis
    from p1_tpu.core.hashutil import sha256d
    from p1_tpu.core.header import HEADER_SIZE

    raw = open(path, "rb").read()
    if not raw or len(raw) % HEADER_SIZE:
        print(f"{path}: not a header file", file=sys.stderr)
        raise SystemExit(2)
    # Packed-bytes plane end to end: genesis pinning hashes the first 80
    # bytes directly, verification hands the whole file to the native
    # engine in one call (replay_packed), and the object parse happens
    # once, after the chain has proven itself — seeding each header's
    # encoding cache with the file's exact bytes.
    if sha256d(raw[:HEADER_SIZE]) != make_genesis(difficulty, rule).block_hash():
        print(
            f"{path}: does not start at this chain's genesis "
            "(check --difficulty / retarget flags)",
            file=sys.stderr,
        )
        raise SystemExit(2)
    report = replay_packed(raw, retarget=rule)
    if not report.valid:
        print(
            f"{path}: header chain INVALID at index {report.first_invalid}",
            file=sys.stderr,
        )
        raise SystemExit(4)
    return parse_headers(raw)


def cmd_headers(args) -> int:
    """Light-client sync: fetch the peer's header chain (~80 B/block) and
    verify it locally — PoW, linkage, and (with the retarget flags) the
    full contextual difficulty schedule.  Trusts nothing but work."""
    from p1_tpu.chain import replay_fast
    from p1_tpu.node.client import get_headers

    rule = _retarget_rule(args)

    def _addr(spec: str) -> tuple[str, int]:
        host, _, port = spec.rpartition(":")
        return (host or "127.0.0.1", int(port))

    try:
        headers = asyncio.run(
            get_headers(
                args.host,
                args.port,
                args.difficulty,
                retarget=rule,
                stall_timeout_s=args.stall_timeout,
                fallback_peers=[_addr(s) for s in args.fallback],
            )
        )
    except (
        ConnectionError,
        OSError,
        ValueError,
        asyncio.TimeoutError,
        asyncio.IncompleteReadError,
    ) as e:
        print(f"header sync failed: {e}", file=sys.stderr)
        return 1
    report = replay_fast(headers, retarget=rule)
    if report.valid and args.out:
        with open(args.out, "wb") as fh:
            for h in headers:
                fh.write(h.serialize())
    print(
        json.dumps(
            {
                "config": "headers",
                "height": len(headers) - 1,
                "tip": headers[-1].block_hash().hex(),
                "tip_difficulty": headers[-1].difficulty,
                "valid": report.valid,
                "first_invalid": report.first_invalid,
                "verify_headers_per_sec": round(report.headers_per_sec),
                "out": args.out if report.valid else None,
            }
        )
    )
    # A peer serving an invalid chain is loud (4), like a lying proof.
    return 0 if report.valid else 4


# -- keygen --------------------------------------------------------------


def cmd_keygen(args) -> int:
    from p1_tpu.core.keys import Keypair

    key = (
        Keypair.from_seed_text(args.seed_text)
        if args.seed_text is not None
        else Keypair.generate()
    )
    try:
        key.save(args.out, overwrite=args.force)
    except FileExistsError:
        print(
            f"{args.out} already exists; refusing to destroy its seed "
            "(use --force to overwrite)",
            file=sys.stderr,
        )
        return 2
    print(json.dumps({"config": "keygen", "account": key.account, "path": args.out}))
    return 0


# -- pod -----------------------------------------------------------------


class _PodWatchdog:
    """No-progress failsafe: a vanished pod peer leaves the survivor
    blocked inside a collective forever (aborts can't unblock it, and
    interpreter exit would hang on the executor join), so if no lockstep
    point is reached for ``grace`` seconds the process fails over.
    ``grace`` covers the longest LEGITIMATE inter-beat gap — the first
    search's jit compile on a real mesh plus one chunk — independent of
    run length (progress-based, not an absolute deadline).  Override with
    ``P1_POD_GRACE_S`` (tests shrink it; operators can tune it).

    On trip the watchdog runs ``on_trip`` — the LEADER re-execs itself
    into a single-process ``p1 node`` against the same store and identity
    (SURVEY §5 elastic recovery: mining degrades instead of going dark;
    see ``cmd_pod``), while followers, whose chain state lives in the
    leader, still just exit 3 for their external supervisor to restart.

    ``beat()`` is a plain monotonic-timestamp store (the hot path runs it
    per chunk); one long-lived daemon thread polls, instead of spawning a
    Timer thread per beat.
    """

    _POLL_S = 1.0

    def __init__(self, role: str, on_trip=None):
        import threading

        self.role = role
        self.grace_s = float(os.environ.get("P1_POD_GRACE_S", "600"))
        self._on_trip = on_trip
        self._last = time.monotonic()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._poll, daemon=True)
        self._thread.start()

    def beat(self) -> None:
        self._last = time.monotonic()

    def cancel(self) -> None:
        self._stop.set()

    def _poll(self) -> None:
        while not self._stop.wait(self._POLL_S):
            if time.monotonic() - self._last > self.grace_s:
                logging.error(
                    "pod watchdog (%s): no lockstep progress for %.0fs "
                    "(peer lost?), failing over",
                    self.role,
                    self.grace_s,
                )
                if self._on_trip is not None:
                    try:
                        self._on_trip()
                    except Exception:
                        # A failed leader failover (os.execv can raise
                        # ENOMEM/E2BIG, or the interpreter path vanished)
                        # must still END the wedged process — the exit
                        # code is the supervisor's only signal.
                        logging.exception("pod failover failed")
                os._exit(3)  # followers, or a failed on_trip


def _pod_leader_failover(args, deadline: float) -> None:
    """Degrade the pod leader to a single-process ``p1 node`` when a pod
    peer vanishes (VERDICT r3 item 8 / SURVEY §5 elastic recovery).

    ``os.execv`` replaces the wedged process image in place: the thread
    stuck inside the dead collective, the jax.distributed client, and the
    executor all go with it, while the pid (for the operator) and the
    environment (JAX platform pins, XLA flags) survive.  The store's
    writer flock is released automatically — Python opens files
    close-on-exec — so the SAME process re-acquires the SAME store and
    mining continues on the persisted chain with the same coinbase
    identity and peer list, for the remainder of the original window.
    Followers hold no chain state, so they still exit for their
    supervisor (cmd_pod docstring documents the recipe).  A leader
    configured with ``--port 0`` re-binds a fresh ephemeral port; pinned
    ports are re-bound exactly (the old socket died with the exec).
    """
    argv = [
        sys.executable, "-m", "p1_tpu", "node",
        "--difficulty", str(args.difficulty),
        "--backend", "sharded",  # local mesh only, no jax.distributed
        "--host", args.host,
        "--port", str(args.port),
        "--duration", f"{max(5.0, deadline - time.time()):.1f}",
    ]
    if args.peers:
        argv += ["--peers", *args.peers]
    if args.miner_id:
        argv += ["--miner-id", args.miner_id]
    if args.store:
        argv += ["--store", args.store]
    if args.chunk:
        argv += ["--chunk", str(args.chunk)]
    if args.batch:
        argv += ["--batch", str(args.batch)]
    if args.platform:
        argv += ["--platform", args.platform]
    logging.error("pod leader failing over to solo mining: %s", " ".join(argv))
    sys.stderr.flush()
    os.execv(sys.executable, argv)


def cmd_pod(args) -> int:
    """Multi-host mining (north star config 5, multi-host form): every
    process joins one jax.distributed mesh and mirrors the same sharded
    search in lockstep; process 0 additionally runs the p2p node, so the
    whole pod presents as a single miner on the gossip network.

    Failure handling: each role arms a no-progress watchdog (bounded runs
    only).  A follower that loses the pod exits 3 — restart it with the
    same ``--host-id`` under any supervisor (systemd ``Restart=on-failure``,
    a shell loop) once the pod coordinator is back.  The LEADER owns the
    chain store and the gossip identity, so it does NOT go dark: the
    watchdog re-execs it into single-process sharded mining against the
    same store/port/peers (``_pod_leader_failover``) and the chain keeps
    growing while the pod is rebuilt."""
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    from p1_tpu.hashx import get_backend
    from p1_tpu.parallel import PodMiner, init_distributed

    init_distributed(args.coordinator, args.num_hosts, args.host_id)
    is_leader = args.host_id == 0
    # Arm the watchdog BEFORE any blocking collective (the construction
    # handshake included): a peer that dies during startup must not hang a
    # bounded run.  Long-running services (no --duration) supervise
    # externally.
    watchdog = None
    if args.duration is not None:
        deadline = time.time() + args.duration
        on_trip = (
            (lambda: _pod_leader_failover(args, deadline)) if is_leader else None
        )
        watchdog = _PodWatchdog(
            role="leader" if is_leader else "follower", on_trip=on_trip
        )
    kwargs = {"batch": args.batch} if args.batch else {}
    backend = get_backend("sharded", **kwargs)
    try:
        miner = PodMiner(is_leader=is_leader, backend=backend, chunk=args.chunk)
    except ValueError as e:
        # The pod is already broken (config mismatch); a normal exit would
        # hang in jax.distributed's atexit barrier waiting for peers that
        # will never agree — leave immediately and loudly.
        import os

        print(f"pod startup failed: {e}", file=sys.stderr, flush=True)
        os._exit(2)
    logging.info(
        "pod process %d/%d: %d global devices, %s",
        args.host_id,
        args.num_hosts,
        backend.n_devices,
        "leader" if is_leader else "follower",
    )
    if watchdog is not None:
        miner.heartbeat = watchdog.beat
    if not is_leader:
        mirrored = miner.follow()
        if watchdog is not None:
            watchdog.cancel()
        print(json.dumps({"config": "pod", "role": "follower", "searches": mirrored}))
        return 0
    args.backend = "sharded"  # for _run_node's NodeConfig (miner overrides)
    try:
        return asyncio.run(_run_node(args, miner=miner))
    finally:
        miner.shutdown()
        if watchdog is not None:
            watchdog.cancel()


# -- balances ------------------------------------------------------------


def _load_store(
    path: str, expected_difficulty: int | None = None, retarget=None
):
    """(blocks, chain) from a persisted store, difficulty inferred from the
    records (every block declares the chain difficulty — validation
    enforces it — so the store is self-describing; the retarget rule is
    NOT, so retarget chains need their flags).  Raises SystemExit 2 for an
    empty/missing store, an ``expected_difficulty`` mismatch, or records
    that do not connect to the selected genesis (wrong retarget flags)."""
    from p1_tpu.chain import ChainStore

    store = ChainStore(path)
    try:
        blocks = store.load_blocks()
    finally:
        store.close()
    if not blocks:
        print(f"{path}: empty or missing chain store", file=sys.stderr)
        raise SystemExit(2)
    stored = blocks[0].header.difficulty
    if expected_difficulty is not None and expected_difficulty != stored:
        # A wrong flag would otherwise silently yield an empty chain.
        print(
            f"--difficulty {expected_difficulty} does not match the store's "
            f"chain (difficulty {stored})",
            file=sys.stderr,
        )
        raise SystemExit(2)
    try:
        chain = store.load_chain(stored, blocks, retarget=retarget)
    except ValueError as e:  # none-connected guard (store.py)
        print(str(e), file=sys.stderr)
        raise SystemExit(2)
    return blocks, chain


def cmd_balances(args) -> int:
    from p1_tpu.chain import balances

    blocks, chain = _load_store(
        args.store, args.difficulty, retarget=_retarget_rule(args)
    )
    ledger = balances(chain.main_chain())
    if args.account is not None:
        print(
            json.dumps(
                {
                    "config": "balances",
                    "height": chain.height,
                    "account": args.account,
                    "balance": ledger.get(args.account, 0),
                }
            )
        )
        return 0
    # Offline audit: the store loads through full consensus validation, so
    # the view must agree with the incremental ledger, hold nothing
    # negative, and conserve exactly — total = coinbase minted minus the
    # fees burned by the rare coinbase-less blocks.  A False here means a
    # corrupted store or a consensus bug — surface it in the exit code.
    minted = burned = 0
    for b in chain.main_chain():
        if b.txs and b.txs[0].is_coinbase:
            minted += b.txs[0].amount
        else:
            burned += sum(t.fee for t in b.txs)
    conserved = (
        sum(ledger.values()) == minted - burned
        and all(v >= 0 for v in ledger.values())
        and {a: v for a, v in ledger.items() if v} == chain.balances_snapshot()
    )
    print(
        json.dumps(
            {
                "config": "balances",
                "height": chain.height,
                "conserved": conserved,
                "balances": dict(sorted(ledger.items())),
            }
        )
    )
    return 0 if conserved else 1


# -- compact -------------------------------------------------------------


def cmd_compact(args) -> int:
    """Store maintenance: the append-only log keeps every side branch and
    reorged-away block forever (that's what makes restarts deterministic);
    compaction snapshots just the current main branch, shrinking the file
    while resume behavior for the surviving chain is unchanged."""
    import os

    from p1_tpu.chain import ChainStore, save_chain

    if not os.path.exists(args.store):
        print(f"{args.store}: empty or missing chain store", file=sys.stderr)
        return 2
    # Lock FIRST, then load: records appended between an unlocked read and
    # the rewrite would be silently dropped, and replacing the inode under
    # a live node would orphan everything it appends afterwards.
    src = ChainStore(args.store)
    try:
        try:
            # allow_v2: compaction IS the upgrade path for pre-checksum
            # stores (the snapshot below is written in v3 framing).
            src.acquire(allow_v2=True)
        except RuntimeError as e:
            print(f"{e} — stop it before compacting", file=sys.stderr)
            return 2
        blocks = src.load_blocks()
        if not blocks:
            print(f"{args.store}: empty chain store", file=sys.stderr)
            return 2
        try:
            chain = src.load_chain(
                blocks[0].header.difficulty,
                blocks,
                retarget=_retarget_rule(args),
            )
        except ValueError as e:
            # Without this, compacting a retarget store with forgotten
            # flags would REPLACE it with a genesis-only snapshot of the
            # wrong chain — the one unrecoverable failure mode here.
            print(str(e), file=sys.stderr)
            return 2
        before = os.path.getsize(args.store)
        out = args.out or args.store
        dst = None
        if args.out and os.path.realpath(out) != os.path.realpath(args.store):
            # The destination needs the same in-use guard: replacing it
            # would orphan a live node's inode there.
            dst = ChainStore(out)
            try:
                dst.acquire()
            except RuntimeError as e:
                print(f"{e} — stop it before overwriting", file=sys.stderr)
                return 2
        else:
            out = args.store
        try:
            # Always write a sibling temp file and atomically replace, so
            # a crash mid-write can never leave EITHER path deleted or
            # truncated.
            tmp = f"{out}.compact.{os.getpid()}"
            save_chain(chain, tmp)
            # Prove the snapshot BEFORE it replaces the original: the
            # main branch is linear, so its packed headers verify (PoW +
            # linkage + difficulty) in one native call straight off the
            # bytes just written — a torn or miswritten snapshot can
            # never clobber a good log.
            from p1_tpu.chain import replay_packed

            raw_headers, n_headers = ChainStore(tmp).packed_headers()
            snap = replay_packed(raw_headers, retarget=_retarget_rule(args))
            if not snap.valid:
                os.unlink(tmp)
                print(
                    f"snapshot self-check failed at record "
                    f"{snap.first_invalid} of {n_headers} — original store "
                    "left untouched",
                    file=sys.stderr,
                )
                return 3
            os.replace(tmp, out)
            # The rename itself must survive a metadata-journal loss:
            # save_chain fsynced the tmp's data and directory entry, but
            # the replace is a second directory mutation.
            from p1_tpu.chain.store import fsync_dir

            fsync_dir(os.path.dirname(os.path.abspath(out)))
        finally:
            if dst is not None:
                dst.close()
    finally:
        src.close()
    print(
        json.dumps(
            {
                "config": "compact",
                "height": chain.height,
                "records_before": len(blocks),
                "records_after": chain.height + 1,
                "bytes_before": before,
                "bytes_after": os.path.getsize(out),
                "out": out,
            }
        )
    )
    return 0


# -- fsck ----------------------------------------------------------------


def cmd_fsck(args) -> int:
    """Offline store integrity scan + salvage (the disk counterpart of
    Bitcoin's -checkblocks/salvagewallet tooling).  Exit contract:

    - **0 clean** — every record checksum-valid, nothing rewritten (a
      lossless v2→v3 upgrade also exits 0: no information was lost);
    - **1 salvaged** — corruption or a torn tail was found; every
      checksum-valid record was rewritten into a fresh verified store,
      bad spans quarantined to the ``.quarantine`` sidecar;
    - **2 unrecoverable** — missing/empty/locked store, unrecognizable
      magic, or zero salvageable records.

    Unlike ``p1 compact`` this preserves insertion order and side
    branches (it salvages the LOG, not the main branch), so the
    self-check is framing-level — every salvaged record re-reads
    checksum-valid and byte-identical — rather than the linear-chain
    ``replay_packed`` proof compaction can afford."""
    import os

    from p1_tpu.chain import ChainStore
    from p1_tpu.chain.store import fsync_dir
    from p1_tpu.core.block import Block

    if not os.path.exists(args.store) or os.path.getsize(args.store) == 0:
        print(f"{args.store}: empty or missing chain store", file=sys.stderr)
        return 2
    store = ChainStore(args.store)
    try:
        try:
            # Lock first (a live node's in-flight appends must not race
            # the rewrite), scan without healing: fsck owns the salvage
            # decision and must report BEFORE mutating.
            store.acquire(allow_v2=True, heal=False)
        except RuntimeError as e:
            print(str(e), file=sys.stderr)
            return 2
        data = store._read_bytes()
        scan = store.scan(data)
        report = {
            "config": "fsck",
            "store": args.store,
            "version": scan.version,
            "records_valid": len(scan.spans),
            "bad_spans": len(scan.bad_spans),
            "bytes_quarantined": scan.quarantined_bytes,
            "torn_tail_bytes": (
                scan.size - scan.torn_tail if scan.torn_tail is not None else 0
            ),
        }
        if scan.version == 3 and scan.clean:
            print(json.dumps({**report, "status": "clean"}))
            return 0

        # Salvage: every checksum-valid record that still parses as a
        # block, in original insertion order, into a fresh v3 store.
        blocks, parse_failures = [], 0
        for off, n in scan.spans:
            try:
                blocks.append(Block.deserialize(data[off : off + n]))
            except ValueError:
                parse_failures += 1
        report["parse_failures"] = parse_failures
        if not blocks:
            print(
                json.dumps({**report, "status": "unrecoverable"}),
            )
            print(
                f"{args.store}: no salvageable records", file=sys.stderr
            )
            return 2
        if scan.bad_spans:
            # Evidence first, durably, before the original bytes go away.
            qpath = store.quarantine_path()
            import struct as _struct

            with open(qpath, "ab") as qf:
                for s, e in scan.bad_spans:
                    qf.write(_struct.pack(">QI", s, e - s))
                    qf.write(data[s:e])
                qf.flush()
                os.fsync(qf.fileno())
            report["quarantine"] = str(qpath)
        out = args.out or args.store
        tmp = f"{out}.fsck.{os.getpid()}"
        dst = ChainStore(tmp, fsync=False)
        try:
            for block in blocks:
                dst.append(block)
            dst.sync()
            dst._fsync_dir()
        finally:
            dst.close()
        # Self-check BEFORE the replace: the fresh store must re-scan
        # clean with every record byte-identical to what was salvaged —
        # a miswritten salvage must never clobber the evidence.
        vdata = ChainStore(tmp)._read_checked()
        vscan = ChainStore.scan(vdata)
        ok = (
            vscan.version == 3
            and vscan.clean
            and len(vscan.spans) == len(blocks)
            and all(
                vdata[off : off + n] == block.serialize()
                for (off, n), block in zip(vscan.spans, blocks)
            )
        )
        if not ok:
            os.unlink(tmp)
            print(
                "salvage self-check failed — original store left untouched",
                file=sys.stderr,
            )
            return 2
        os.replace(tmp, out)
        fsync_dir(os.path.dirname(os.path.abspath(out)))
        lossless = (
            not scan.bad_spans
            and scan.torn_tail is None
            and not parse_failures
        )
        report.update(
            {
                "records_salvaged": len(blocks),
                "out": out,
                "status": "upgraded" if lossless else "salvaged",
            }
        )
        print(json.dumps(report))
        return 0 if lossless else 1
    finally:
        store.close()


# -- net -----------------------------------------------------------------


async def _inject_txs(
    ports, keys, difficulty, deadline, rate, retarget=None
) -> tuple[int, int]:
    """Drive a live economy during a `p1 net` run: ~``rate`` transfers/sec,
    each one a real wallet round — GETACCOUNT for the sender's next seq at
    its own node, sign chain-bound, push via the tx client.  Best-effort:
    a busy node (GIL-bound mining) or an unaffordable pick just skips a
    beat; the audit invariant is conservation, not delivery."""
    import random

    from p1_tpu.core.genesis import genesis_hash
    from p1_tpu.core.tx import Transaction
    from p1_tpu.node.client import get_account, send_tx

    tag = genesis_hash(difficulty, retarget)
    submitted = failed = 0
    rng = random.Random(0xD1CE)
    period = 1.0 / rate
    while time.time() < deadline - 1.0:
        i = rng.randrange(len(keys))
        recipient = keys[rng.randrange(len(keys))].account
        try:
            state = await get_account(
                "127.0.0.1",
                ports[i],
                keys[i].account,
                difficulty,
                timeout=5,
                retarget=retarget,
            )
            amount = rng.randint(1, 5)
            if state.balance >= amount + 1:
                tx = Transaction.transfer(
                    keys[i], recipient, amount, 1, state.next_seq, chain=tag
                )
                await send_tx(
                    "127.0.0.1",
                    ports[i],
                    tx,
                    difficulty,
                    timeout=5,
                    retarget=retarget,
                )
                submitted += 1
        except (
            ConnectionError,
            OSError,
            ValueError,
            asyncio.TimeoutError,
            asyncio.IncompleteReadError,
        ):
            failed += 1
        await asyncio.sleep(period)
    return submitted, failed


async def _byzantine_actor(
    actor: int, ports, difficulty, deadline, retarget, stats: dict
) -> None:
    """One actively malicious participant (VERDICT r4 weak #5): connects
    to honest nodes from its own loopback alias (127.0.0.{10+actor}, so
    misbehavior bans hit the attacker's address, not the honest mesh's)
    and cycles the whole hostile repertoire — invalid signatures,
    overdraws, replays of confirmed transfers, forged compact-block
    material, unsolicited BLOCKTXN, ADDR spam, oversized frames, random
    garbage.  Counts what it sent and how often the node refused it at
    accept time (= an active ban).  Every attack is fire-and-observe:
    the honest invariants are asserted from the nodes' final statuses,
    not from here."""
    import dataclasses
    import random
    import struct

    from p1_tpu.core.genesis import make_genesis
    from p1_tpu.core.header import BlockHeader
    from p1_tpu.core.keys import Keypair
    from p1_tpu.core.tx import Transaction
    from p1_tpu.node import protocol
    from p1_tpu.node.protocol import Hello, MsgType

    rng = random.Random(0xBAD + actor)
    source = f"127.0.0.{10 + actor}"
    genesis = make_genesis(difficulty, retarget)
    gh = genesis.block_hash()
    tag = gh
    key = Keypair.from_seed_text(f"p1-byz-{actor}")
    harvested_txs: list[bytes] = []  # raw TX payloads seen in gossip
    harvested_headers: list[BlockHeader] = []

    def bump(name: str) -> None:
        stats["attacks"][name] = stats["attacks"].get(name, 0) + 1

    while time.time() < deadline - 1.0:
        port = ports[rng.randrange(len(ports))]
        try:
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", port, local_addr=(source, 0)
            )
        except OSError:
            await asyncio.sleep(0.2)
            continue
        try:
            first = await asyncio.wait_for(protocol.read_frame(reader), 5)
            mtype, _ = protocol.decode(first)
            assert mtype is MsgType.HELLO
        except asyncio.TimeoutError:
            # Slow HELLO ≠ ban: a GIL-loaded honest node can take
            # seconds — counting it as a refusal would let bans_fired
            # read true with the ban machinery broken.
            stats["slow_hellos"] = stats.get("slow_hellos", 0) + 1
            writer.close()
            await asyncio.sleep(0.2)
            continue
        except (
            asyncio.IncompleteReadError,
            ConnectionError,
            OSError,
            ValueError,
        ):
            # Immediate hang-up before HELLO: the accept-time ban said no.
            stats["refused_connects"] += 1
            writer.close()
            await asyncio.sleep(0.2)
            continue
        harvester = None
        try:
            await protocol.write_frame(
                writer, protocol.encode_hello(Hello(gh, 0, 0, 0))
            )
            session_end = min(deadline - 0.5, time.time() + 2.0)

            async def harvest() -> None:
                try:
                    while True:
                        payload = await protocol.read_frame(reader)
                        if not payload:
                            continue
                        if (
                            payload[0] == MsgType.TX
                            and len(harvested_txs) < 64
                        ):
                            harvested_txs.append(payload)
                        elif payload[0] == MsgType.BLOCK:
                            try:
                                _, (_ts, blk) = protocol.decode(payload)
                                if len(harvested_headers) < 16:
                                    harvested_headers.append(blk.header)
                            except ValueError:
                                pass
                except (
                    asyncio.IncompleteReadError,
                    ConnectionError,
                    OSError,
                ):
                    return  # node hung up on us (a ban working) — done

            harvester = asyncio.create_task(harvest())
            if deadline - time.time() >= 25.0 and rng.random() < 0.25:
                # A CAMPING session — the round-4 verdict's exact
                # slot-pinning profile: hold the connection, reading but
                # never sending, until the liveness layer reaps us.
                # Decided ONCE per session with small probability (a
                # per-iteration draw converted ~99% of sessions into
                # camps and starved the ban machinery the containment
                # contract asserts), and skipped near the deadline so
                # short runs still exercise every other attack.  The
                # session sends nothing after HELLO, so a teardown here
                # is attributable to the keepalive probe (accept-time
                # bans close pre-HELLO and never reach this point).
                bump("camp")
                camp_end = time.time() + 20.0
                while time.time() < camp_end:
                    if writer.is_closing() or harvester.done():
                        stats["camp_evictions"] += 1
                        break
                    await asyncio.sleep(0.5)
            else:
                while time.time() < session_end:
                    attack = rng.choice(
                        (
                            "badsig",
                            "overdraw",
                            "replay",
                            "cblock",
                            "blocktxn",
                            "addr_spam",
                            "garbage",
                        )
                    )
                    if attack == "replay" and not harvested_txs:
                        attack = "garbage"  # nothing harvested yet
                    if attack == "cblock" and not harvested_headers:
                        attack = "garbage"
                    if attack == "badsig":
                        tx = Transaction.transfer(
                            key, "p1deadbeefdeadbeef", 1, 1, 0, chain=tag
                        )
                        forged = dataclasses.replace(
                            tx, sig=bytes(64)  # zeroed signature
                        )
                        await protocol.write_frame(
                            writer, protocol.encode_tx(forged)
                        )
                    elif attack == "overdraw":
                        tx = Transaction.transfer(
                            key,
                            "p1deadbeefdeadbeef",
                            10**12,  # the attacker's balance is zero
                            1,
                            0,
                            chain=tag,
                        )
                        await protocol.write_frame(writer, protocol.encode_tx(tx))
                    elif attack == "replay":
                        # A transfer harvested from gossip earlier: by now
                        # confirmed on-chain — a definite nonce replay.
                        await protocol.write_frame(
                            writer, harvested_txs[rng.randrange(len(harvested_txs))]
                        )
                    elif attack == "cblock":
                        # Real recent header with the nonce bumped: parent
                        # known, PoW broken — must die at the work gate.
                        h = harvested_headers[-1]
                        fake = dataclasses.replace(h, nonce=h.nonce ^ 1)
                        payload = (
                            bytes([MsgType.CBLOCK])
                            + struct.pack(">d", time.time())
                            + fake.serialize()
                            + struct.pack(">HH", 1, 0)
                            + bytes(32)
                        )
                        await protocol.write_frame(writer, payload)
                    elif attack == "blocktxn":
                        await protocol.write_frame(
                            writer,
                            protocol.encode_blocktxn(
                                rng.randbytes(32), [rng.randbytes(40)]
                            ),
                        )
                    elif attack == "addr_spam":
                        addrs = [
                            (f"10.66.{rng.randrange(256)}.{rng.randrange(256)}",
                             rng.randrange(1, 0xFFFF))
                            for _ in range(64)
                        ]
                        await protocol.write_frame(
                            writer, protocol.encode_addr(addrs)
                        )
                    else:  # garbage: malformed bytes — a scorable violation
                        writer.write(
                            (rng.randrange(1, 64)).to_bytes(4, "big")
                            + rng.randbytes(rng.randrange(1, 64))
                        )
                        await writer.drain()
                    bump(attack)
                    await asyncio.sleep(0.05)
                # Sign off with the canonical scorable violation so bans
                # accumulate: a hostile length prefix.
                writer.write((64 << 20).to_bytes(4, "big"))
                await writer.drain()
                bump("oversized")
        except (ConnectionError, OSError, asyncio.TimeoutError):
            pass  # node dropped us mid-attack: working as intended
        finally:
            if harvester is not None:
                harvester.cancel()  # no-op if it already returned; its
                # own except clause swallows disconnects, so no
                # unretrieved-exception warnings either way
            writer.close()
        await asyncio.sleep(0.1)


async def _net_drive(
    ports, keys, difficulty, deadline, rate, n_byzantine, retarget=None
):
    """Run the benign economy and the byzantine actors concurrently."""
    byz_stats = {
        "attacks": {},
        "refused_connects": 0,
        "slow_hellos": 0,
        "camp_evictions": 0,
    }
    tasks = []
    if rate > 0:
        tasks.append(
            _inject_txs(ports, keys, difficulty, deadline, rate, retarget)
        )
    for actor in range(n_byzantine):
        tasks.append(
            _byzantine_actor(
                actor, ports, difficulty, deadline, retarget, byz_stats
            )
        )
    results = await asyncio.gather(*tasks, return_exceptions=True)
    submitted = failed = 0
    for r in results:
        if isinstance(r, tuple):
            submitted, failed = r
        elif isinstance(r, BaseException):
            raise r
    return submitted, failed, byz_stats


def cmd_net(args) -> int:
    """Spawn N `p1_tpu node` subprocesses in a full mesh and check they
    converge on one tip (benchmark config 4, BASELINE.json:10).  With
    ``--tx-rate`` the run carries a live signed-transfer economy between
    the miners' accounts, and the summary audits every node's ledger for
    exact conservation — the whole consensus stack (signatures, nonces,
    overdraw rejection, reorg undo) exercised under real concurrent
    forks."""
    import subprocess

    from p1_tpu.core.keys import Keypair

    # Validate the retarget flag pair up front: a bad pair must be ONE
    # clean CLI error here, not N child-node tracebacks (or — for a lone
    # --target-spacing — a silently fixed-difficulty run).
    net_rule = _retarget_rule(args)
    ports = [args.base_port + i for i in range(args.nodes)]
    keys = [
        Keypair.from_seed_text(f"p1-net-{args.base_port}-{i}")
        for i in range(args.nodes)
    ]
    procs = []
    for i, port in enumerate(ports):
        cmd = [
            sys.executable,
            "-m",
            "p1_tpu",
            "node",
            "--port",
            str(port),
            "--difficulty",
            str(args.difficulty),
            "--backend",
            args.backend,
            "--deadline",
            "stdin",
            "--miner-id",
            keys[i].account if args.tx_rate > 0 else f"node{i}",
        ]
        if args.chunk:
            cmd += ["--chunk", str(args.chunk)]
        if args.batch:
            cmd += ["--batch", str(args.batch)]
        # Tight liveness deadlines for the localhost mesh: a silent
        # camper (the byzantine "camp" attack, or any wedged peer) is
        # probed within 10 s and evicted 5 s later, so soak statuses
        # show the keepalive layer actually firing.  Honest miners
        # gossip constantly and never get probed.
        cmd += ["--ping-interval", "10", "--pong-timeout", "5"]
        # Tight sync supervision to match: a localhost batch turns
        # around in milliseconds, so a 5 s no-progress window on a
        # catch-up is decisively a stall — soak statuses surface the
        # failover layer under byzantine serve-and-starve peers while
        # honest syncs (progress resets the deadline) never trip it.
        cmd += ["--sync-stall-timeout", "5"]
        if net_rule is not None:
            cmd += [
                "--retarget-window", str(net_rule.window),
                "--target-spacing", str(net_rule.spacing),
            ]
        if args.no_compact_gossip:
            cmd += ["--no-compact-gossip"]
        if args.discover:
            # One seed only; discovery must assemble the mesh.
            peers = [f"127.0.0.1:{ports[0]}"] if i else []
            cmd += ["--target-peers", str(args.nodes - 1)]
        else:
            peers = [f"127.0.0.1:{p}" for p in ports[:i]]
        if peers:
            cmd += ["--peers", *peers]
        procs.append(
            subprocess.Popen(
                cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True
            )
        )
    statuses = []
    try:
        # Readiness handshake: interpreter startup can cost many seconds on
        # a loaded host, so a deadline computed before the children exist
        # could expire before they boot.  Every child prints a ready line;
        # only then does the shared mining deadline start counting.
        for proc in procs:
            ready = json.loads(proc.stdout.readline())
            assert "ready" in ready, ready
        deadline = time.time() + args.duration
        for proc in procs:
            proc.stdin.write(f"{deadline!r}\n")
            proc.stdin.flush()  # leave stdin open: communicate() closes it
        txs_submitted = txs_failed = 0
        byz_stats = None
        n_byz = getattr(args, "byzantine", 0)
        if args.tx_rate > 0 or n_byz > 0:
            txs_submitted, txs_failed, byz_stats = asyncio.run(
                _net_drive(
                    ports,
                    keys,
                    args.difficulty,
                    deadline,
                    args.tx_rate,
                    n_byz,
                    retarget=net_rule,
                )
            )
        for proc in procs:
            out, _ = proc.communicate(timeout=args.duration + 120)
            lines = (out or "").strip().splitlines()
            if not lines:
                raise RuntimeError(f"node pid {proc.pid} produced no status output")
            statuses.append(json.loads(lines[-1]))
    finally:
        for proc in procs:  # never leave orphaned miners holding the ports
            if proc.poll() is None:
                proc.kill()
    tips = {s["tip"] for s in statuses}
    result = {
        "config": "net",
        "nodes": args.nodes,
        "difficulty": args.difficulty,
        "converged": len(tips) == 1,
        "height": max(s["height"] for s in statuses),
        "blocks_mined_total": sum(s["blocks_mined"] for s in statuses),
        "reorgs_total": sum(s["reorgs"] for s in statuses),
        # Gossip bandwidth elided by compact block relay, net-wide.
        "compact_bytes_saved_total": sum(
            s["compact"]["bytes_saved"] for s in statuses
        ),
        "compact_tx_hit_total": sum(
            s["compact"]["tx_hits"] for s in statuses
        ),
        "compact_tx_fetched_total": sum(
            s["compact"]["tx_fetched"] for s in statuses
        ),
        "wire_bytes_total": sum(
            s["wire"]["bytes_sent"] for s in statuses
        ),
        # Network-level propagation delay (gossip send -> accept), the
        # worst node's view: median of per-node medians would hide a slow
        # peer, so report the max median and the max p95 across nodes.
        "propagation_delay_ms": {
            "max_median": max(
                (s["propagation"]["median_ms"] or 0.0 for s in statuses),
                default=0.0,
            ),
            "max_p95": max(
                (s["propagation"]["p95_ms"] or 0.0 for s in statuses),
                default=0.0,
            ),
            "samples_total": sum(s["propagation"]["samples"] for s in statuses),
        },
        "statuses": statuses,
    }
    if args.tx_rate > 0:
        from p1_tpu.core.tx import BLOCK_REWARD

        # Conservation: every block carries a coinbase and fees credit the
        # miner, so each node's ledger must sum to exactly reward x its
        # height — across hundreds of reorgs and a live spend stream.
        conserved = all(
            s["ledger_sum"] == BLOCK_REWARD * s["height"] for s in statuses
        )
        result["economy"] = {
            "txs_submitted": txs_submitted,
            "txs_failed": txs_failed,
            "txs_accepted_total": sum(s["txs_accepted"] for s in statuses),
            "ledger_conserved": conserved,
        }
        if not conserved:
            result["converged"] = False  # fail loudly: consensus bug
    if n_byz > 0 and byz_stats is not None:
        # The byzantine soak's containment contract, asserted in the
        # summary rather than left to log-reading: honest nodes must
        # have (a) kept converging and conserving (checked above),
        # (b) actually banned the attackers (their oversized/garbage
        # frames are scorable, so refused connects must appear), and
        # (c) stayed within their memory bounds — the address book and
        # pool caps hold under spam.
        from p1_tpu.mempool import Mempool
        from p1_tpu.node.node import MAX_KNOWN_ADDRS, MAX_TRIED_ADDRS

        attacks_sent = sum(byz_stats["attacks"].values())
        bans_fired = byz_stats["refused_connects"] > 0
        pool_cap = Mempool().max_txs  # the node's actual bound
        memory_bounded = all(
            s["known_addrs"] <= MAX_KNOWN_ADDRS + MAX_TRIED_ADDRS
            and s["mempool"] <= pool_cap
            for s in statuses
        )
        result["byzantine"] = {
            "attackers": n_byz,
            "attacks_sent": attacks_sent,
            "attacks": byz_stats["attacks"],
            "refused_connects": byz_stats["refused_connects"],
            "slow_hellos": byz_stats["slow_hellos"],
            # Silent-camper sessions the ATTACKERS saw torn down early
            # (camping sessions send nothing after HELLO, so these are
            # keepalive reaps), next to the nodes' aggregate idle-
            # eviction telemetry — an upper bound that can also include
            # an honest peer evicted during a GIL stall.
            "camp_evictions": byz_stats["camp_evictions"],
            "idle_evictions_total": sum(
                s.get("liveness", {}).get("peers_evicted_idle", 0)
                for s in statuses
            ),
            "bans_fired": bans_fired,
            "memory_bounded": memory_bounded,
            "contained": bool(
                result["converged"] and bans_fired and memory_bounded
            ),
        }
        if not result["byzantine"]["contained"]:
            result["converged"] = False
    print(json.dumps(result))
    return 0 if result["converged"] else 1


def cmd_bench(args) -> int:
    # bench.py lives at the repo root (the driver contract), one level above
    # the package — resolve it by path so `p1 bench` works from any cwd.
    import importlib.util
    from pathlib import Path

    bench_path = Path(__file__).resolve().parent.parent / "bench.py"
    spec = importlib.util.spec_from_file_location("bench", bench_path)
    assert spec is not None and spec.loader is not None
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    bench.main()
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        stream=sys.stderr,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    handler = {
        "mine": cmd_mine,
        "sweep": cmd_sweep,
        "replay": cmd_replay,
        "node": cmd_node,
        "tx": cmd_tx,
        "keygen": cmd_keygen,
        "account": cmd_account,
        "proof": cmd_proof,
        "fees": cmd_fees,
        "headers": cmd_headers,
        "balances": cmd_balances,
        "compact": cmd_compact,
        "fsck": cmd_fsck,
        "pod": cmd_pod,
        "net": cmd_net,
        "bench": cmd_bench,
    }[args.cmd]
    return handler(args)


if __name__ == "__main__":
    sys.exit(main())
