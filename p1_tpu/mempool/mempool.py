"""Mempool: pending transactions feeding block assembly.

Capability parity: the reference's mempool (BASELINE.json:5).  Fee-priority
selection with insertion-order tie-breaks (deterministic for tests), txid
dedup for gossip, eviction of mined transactions, and resurrection of
transactions from blocks a reorg abandoned — wired to the removed/added
paths ``Chain.add_block`` reports.
"""

from __future__ import annotations

from p1_tpu.core.block import Block
from p1_tpu.core.tx import Transaction


class Mempool:
    """Txid-keyed pending-transaction pool."""

    def __init__(self, max_txs: int = 100_000):
        self.max_txs = max_txs
        self._txs: dict[bytes, Transaction] = {}  # insertion-ordered

    def __len__(self) -> int:
        return len(self._txs)

    def __contains__(self, txid: bytes) -> bool:
        return txid in self._txs

    def add(self, tx: Transaction) -> bool:
        """Admit ``tx``; False if coinbase, already known, or the pool is full.

        Coinbases never belong in a mempool: they are minted per block by
        the assembling miner, so a gossiped one is invalid and a reorg's
        resurrection path (``apply_block_delta``) must drop the abandoned
        branch's rewards rather than re-mine them into the new branch.
        """
        if tx.is_coinbase:
            return False
        txid = tx.txid()
        if txid in self._txs or len(self._txs) >= self.max_txs:
            return False
        self._txs[txid] = tx
        return True

    def select(self, max_txs: int = 1000) -> list[Transaction]:
        """Highest-fee-first block candidates (insertion order on ties —
        dict order is insertion order, so enumerate() supplies the rank)."""
        ranked = sorted(
            enumerate(self._txs.values()), key=lambda iv: (-iv[1].fee, iv[0])
        )
        return [tx for _, tx in ranked[:max_txs]]

    def apply_block_delta(
        self, removed: tuple[Block, ...], added: tuple[Block, ...]
    ) -> None:
        """Sync the pool with a tip movement reported by ``Chain.add_block``.

        Transactions in newly-connected blocks leave the pool; transactions
        from abandoned blocks come back (unless the new branch also
        confirmed them — eviction runs last to win that race).
        """
        for block in removed:
            for tx in block.txs:
                self.add(tx)
        for block in added:
            for tx in block.txs:
                self._txs.pop(tx.txid(), None)
