"""Mempool: pending transactions feeding block assembly.

Capability parity: the reference's mempool (BASELINE.json:5).  Fee-priority
selection with insertion-order tie-breaks (deterministic for tests), txid
dedup for gossip, **per-(sender, seq) replay suppression with
replace-by-fee** (the ``seq`` field's documented purpose — see
``Transaction.seq`` in core/tx.py: two competing spends of one sequence
slot never sit in the pool together, the higher fee wins, and slots
confirmed within a bounded recent window are refused re-entry), eviction
of mined transactions, and resurrection of transactions from blocks a
reorg abandoned — wired to the removed/added paths ``Chain.add_block``
reports.

Scope note: this is *pool-level anti-spam*, not consensus.  The chain
itself carries no account state, so a spend of a long-ago-confirmed seq
(older than the confirmed-slot window) is not invalid at block level —
bounded memory is traded for a bounded suppression window.
"""

from __future__ import annotations

import collections

from p1_tpu.core.block import Block
from p1_tpu.core.tx import Transaction

def sync_key(fee: int, txid: bytes) -> tuple[int, bytes]:
    """The mempool-sync page ordering: fee-descending, txid-ascending.
    One definition shared by the pager and both requester-side cursor
    computations (continuation pick + strictly-advancing check) so the
    ordering cannot drift between sites."""
    return (-fee, txid)


#: How many recently-confirmed (sender, seq) slots to remember (FIFO).
#: A replayed spend of a confirmed slot is refused while the slot is in
#: the window — sized to cover any realistic gossip-reordering horizon.
CONFIRMED_SLOT_WINDOW = 16_384


class Mempool:
    """Txid-keyed pending-transaction pool with per-(sender, seq) slots."""

    def __init__(self, max_txs: int = 100_000):
        self.max_txs = max_txs
        self._txs: dict[bytes, Transaction] = {}  # insertion-ordered
        self._by_slot: dict[tuple[str, int], bytes] = {}  # (sender, seq) -> txid
        #: FIFO window of recently confirmed slots -> confirmation count.
        #: Counted, not a set: nothing validates per-chain slot uniqueness,
        #: so one slot can be confirmed by several connected blocks and a
        #: partial reorg must not reopen it while another confirmation
        #: still stands.
        self._confirmed_slots: collections.OrderedDict[
            tuple[str, int], int
        ] = collections.OrderedDict()

    def __len__(self) -> int:
        return len(self._txs)

    def __contains__(self, txid: bytes) -> bool:
        return txid in self._txs

    def add(self, tx: Transaction) -> bool:
        """Admit ``tx``; False if coinbase, already known, outbid, or full.

        Coinbases never belong in a mempool: they are minted per block by
        the assembling miner, so a gossiped one is invalid and a reorg's
        resurrection path (``apply_block_delta``) must drop the abandoned
        branch's rewards rather than re-mine them into the new branch.

        A transaction occupying an already-pending (sender, seq) slot must
        strictly outbid the incumbent's fee to replace it (replace-by-fee;
        fees are integers, so "strictly more" is an absolute bump of >= 1 —
        an N-replacement gossip flood costs the attacker an N-unit fee,
        keeping amplification linear-cost).  Replacement frees the
        incumbent's capacity, so it works even when the pool is otherwise
        full.  A slot confirmed within the recent window is refused
        outright — a reordered or replayed spend of it can't re-enter.
        """
        if tx.is_coinbase:
            return False
        txid = tx.txid()
        if txid in self._txs:
            return False
        slot = (tx.sender, tx.seq)
        if slot in self._confirmed_slots:
            return False
        incumbent = self._by_slot.get(slot)
        if incumbent is not None:
            if tx.fee <= self._txs[incumbent].fee:
                return False
            del self._txs[incumbent]
        elif len(self._txs) >= self.max_txs:
            return False
        self._txs[txid] = tx
        self._by_slot[slot] = txid
        return True

    def _evict(self, tx: Transaction) -> None:
        """Mark ``tx``'s (sender, seq) slot confirmed: its pending occupant
        (``tx`` itself or a rival spend) leaves the pool, and the slot
        enters the bounded confirmed window so late replays are refused.

        (Any tx present in ``_txs`` is its slot's occupant — the maintained
        invariant — so the slot pop alone removes it.)
        """
        occupant = self._by_slot.pop((tx.sender, tx.seq), None)
        if occupant is not None:
            self._txs.pop(occupant, None)
        if not tx.is_coinbase:  # coinbase slots can never re-enter anyway
            slot = (tx.sender, tx.seq)
            self._confirmed_slots[slot] = self._confirmed_slots.get(slot, 0) + 1
            self._confirmed_slots.move_to_end(slot)
            while len(self._confirmed_slots) > CONFIRMED_SLOT_WINDOW:
                self._confirmed_slots.popitem(last=False)

    def sync_page(
        self, cursor: tuple[int, bytes] | None, max_txs: int
    ) -> tuple[list[Transaction], bool]:
        """One page of the pool for peer sync: fee-descending (txid-ascending
        on ties), strictly after ``cursor`` = (fee, txid) of the last
        transaction the requester already has.  Returns (page, more).

        The cursor is a *stable key*, not a position: evictions and
        replacements between pages can't shift unseen transactions behind
        it (a positional offset would silently skip them under churn), and
        transactions added mid-sync reach the requester through normal TX
        gossip since it is a connected peer by then.
        """
        import heapq

        def key(item: tuple[bytes, Transaction]) -> tuple[int, bytes]:
            txid, tx = item
            return sync_key(tx.fee, txid)

        ckey = sync_key(*cursor) if cursor is not None else None
        eligible = [
            item for item in self._txs.items() if ckey is None or key(item) > ckey
        ]
        page = heapq.nsmallest(max_txs, eligible, key=key)
        return [tx for _, tx in page], len(eligible) > len(page)

    def select(self, max_txs: int = 1000) -> list[Transaction]:
        """Highest-fee-first block candidates (insertion order on ties —
        dict order is insertion order, so enumerate() supplies the rank)."""
        ranked = sorted(
            enumerate(self._txs.values()), key=lambda iv: (-iv[1].fee, iv[0])
        )
        return [tx for _, tx in ranked[:max_txs]]

    def apply_block_delta(
        self, removed: tuple[Block, ...], added: tuple[Block, ...]
    ) -> None:
        """Sync the pool with a tip movement reported by ``Chain.add_block``.

        Transactions in newly-connected blocks leave the pool; transactions
        from abandoned blocks come back (unless the new branch also
        confirmed them — eviction runs last to win that race).
        """
        for block in removed:
            for tx in block.txs:
                # ONE confirmation of this slot is being rolled back; the
                # slot reopens only when no other connected block still
                # confirms it (hence the count, not a set-discard).
                slot = (tx.sender, tx.seq)
                count = self._confirmed_slots.get(slot)
                if count is not None:
                    if count <= 1:
                        del self._confirmed_slots[slot]
                    else:
                        self._confirmed_slots[slot] = count - 1
                self.add(tx)
        for block in added:
            for tx in block.txs:
                self._evict(tx)
