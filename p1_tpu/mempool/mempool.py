"""Mempool: pending transactions feeding block assembly.

Capability parity: the reference's mempool (BASELINE.json:5).  Fee-priority
selection with insertion-order tie-breaks (deterministic for tests), txid
dedup for gossip, **per-(sender, seq) replay suppression with
replace-by-fee** (the ``seq`` field's documented purpose — see
``Transaction.seq`` in core/tx.py: two competing spends of one sequence
slot never sit in the pool together, the higher fee wins, and slots
confirmed within a bounded recent window are refused re-entry), eviction
of mined transactions, and resurrection of transactions from blocks a
reorg abandoned — wired to the removed/added paths ``Chain.add_block``
reports.

Round 4: admission also requires an Ed25519 ownership proof
(``Transaction.verify_signature``), the pool's chain tag (cross-chain
replays), a not-yet-consumed seq (``nonce_of``), and — when ``balance_of``
is wired to the chain's consensus ledger — that the sender can afford the
transfer net of its other pending spends; ``select`` additionally emits
only gap-free per-sender seq runs, so assembled blocks never violate the
chain's connect-time overdraw/nonce rules.  Same-chain replay protection
is CONSENSUS now (strict account nonces, ledger.py); the (sender, seq)
slot window on top is plain pool hygiene — one pending spend per slot,
highest fee wins.
"""

from __future__ import annotations

import bisect
import collections
import time

from p1_tpu.core.block import Block
from p1_tpu.core.tx import Transaction

def sync_key(fee: int, txid: bytes) -> tuple[int, bytes]:
    """The mempool-sync page ordering: fee-descending, txid-ascending.
    One definition shared by the pager and both requester-side cursor
    computations (continuation pick + strictly-advancing check) so the
    ordering cannot drift between sites."""
    return (-fee, txid)


#: How many recently-confirmed (sender, seq) slots to remember (FIFO).
#: A replayed spend of a confirmed slot is refused while the slot is in
#: the window — sized to cover any realistic gossip-reordering horizon.
CONFIRMED_SLOT_WINDOW = 16_384


#: Mempool persistence file magic + layout version (bump on change).
MEMPOOL_MAGIC = b"P1MP0001"


def dump_mempool(rows: list[tuple[Transaction, float]]) -> bytes:
    """Serialize a ``Mempool.snapshot()`` for persistence.  Layout:
    MAGIC + u32 count + per tx (f64 age_s + u32 len + wire bytes).
    Split from the file write so the node can take the snapshot on the
    event loop (where the pool is mutated) and do the encoding + disk
    I/O in a worker thread.  ``tx.serialize()`` is memoized (core/tx.py),
    so the periodic checkpoint re-emits each pending transaction's
    gossip bytes rather than re-packing the pool every interval."""
    import struct as _struct

    parts = [MEMPOOL_MAGIC, _struct.pack(">I", len(rows))]
    for tx, age in rows:
        raw = tx.serialize()
        parts.append(_struct.pack(">dI", age, len(raw)))
        parts.append(raw)
    return b"".join(parts)


def write_mempool_file(data: bytes, path) -> None:
    """Atomic tmp+replace write (like the address book — never torn),
    DURABLE both sides of the rename: the tmp's data is fsynced before
    ``replace`` publishes it (or a power cut could commit the rename's
    metadata while the data pages were still dirty — a complete rename
    pointing at an empty/torn file), and the directory is fsynced after,
    so the rename itself survives a metadata-journal loss."""
    import os
    import pathlib

    path = pathlib.Path(path)
    tmp = path.with_suffix(path.suffix + ".tmp")
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    dfd = os.open(path.parent, os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def save_mempool(pool: "Mempool", path) -> int:
    """Persist the pending pool to ``path``; returns the tx count.

    Bitcoin's ``mempool.dat`` analog (VERDICT r4 missing #4): without
    it, a restarting single-node miner loses every pending transaction
    outright, and a networked node only re-learns them if some peer
    still holds them.  Ages rather than timestamps: admission stamps
    are monotonic-clock values, meaningless across processes.
    """
    rows = pool.snapshot()
    write_mempool_file(dump_mempool(rows), path)
    return len(rows)


def load_mempool(pool: "Mempool", path) -> tuple[int, int]:
    """Reload a persisted pool through FULL re-validation — every entry
    passes ordinary admission (signature, chain tag, consumed nonces,
    affordability against the CURRENT ledger), so stale or invalid
    records are dropped, not trusted.  Returns (restored, dropped).
    A corrupt or truncated file restores its readable prefix and stops —
    the pool is a cache, never worth failing startup over."""
    import pathlib
    import struct as _struct

    path = pathlib.Path(path)
    try:
        raw = path.read_bytes()
    except OSError:
        return (0, 0)
    if len(raw) < len(MEMPOOL_MAGIC) + 4 or not raw.startswith(MEMPOOL_MAGIC):
        return (0, 0)
    (count,) = _struct.unpack_from(">I", raw, len(MEMPOOL_MAGIC))
    off = len(MEMPOOL_MAGIC) + 4
    restored = dropped = 0
    now = pool._clock()
    for _ in range(count):
        if len(raw) < off + 12:
            break  # truncated tail: keep what we have
        age, tlen = _struct.unpack_from(">dI", raw, off)
        off += 12
        if len(raw) < off + tlen:
            break
        try:
            tx = Transaction.deserialize(raw[off : off + tlen])
        except ValueError:
            dropped += 1
            off += tlen
            continue
        off += tlen
        if pool.restore(tx, age, now=now):
            restored += 1
        else:
            dropped += 1
    return (restored, dropped)


class Mempool:
    """Txid-keyed pending-transaction pool with per-(sender, seq) slots."""

    def __init__(
        self,
        max_txs: int = 100_000,
        balance_of=None,
        chain_tag=None,
        nonce_of=None,
        sig_cache=None,
        clock=time.monotonic,
    ):
        self.max_txs = max_txs
        #: Monotonic time source for admission stamps / TTL expiry.  A
        #: bare reference, never called at import: the node injects its
        #: transport clock (node/transport.py) so pool ages ride VIRTUAL
        #: time under the simulator — chaos schedules that crash and
        #: recover nodes must see deterministic checkpoint ages, and the
        #: wall-clock lint (tests/test_simlint.py) holds mempool/ to the
        #: same seam discipline as node/ and chain/.
        self._clock = clock
        #: Verify-once signature cache (core/sigcache.py) admission
        #: populates: a transfer verified here is NOT re-verified when
        #: the block carrying it connects (or when mining re-assembles
        #: it) — the sigcache double-verify fix.  None = the process
        #: default; a Node wires its own instance, shared with its Chain.
        self.sig_cache = sig_cache
        #: Optional ``account -> confirmed nonce`` callable (wire it to
        #: ``Chain.nonce``).  When set, admission refuses transfers whose
        #: seq is already consumed on the chain (definite replays), and
        #: ``select`` only emits per-sender runs that start at the
        #: confirmed nonce with no gaps — consensus requires strictly
        #: sequential seqs, so anything else could not connect.
        self.nonce_of = nonce_of
        #: Genesis hash of the chain this pool feeds.  When set, admission
        #: refuses transfers whose chain-bound signature names any other
        #: chain (mirror of the consensus check, so assembled blocks can't
        #: be rejected for a foreign tag).  None (unit tests, codec tools)
        #: skips the check.
        self.chain_tag = chain_tag
        #: Optional ``account -> confirmed balance`` callable (wire it to
        #: ``Chain.balance``).  When set, admission requires the sender to
        #: afford the transfer *net of its other pending spends* — the
        #: pool-level mirror of the consensus overdraw rule, so an
        #: assembled block is never rejected at connect time for
        #: overdrawing.  When None (unit tests, codec tools) the pool is
        #: balance-blind, exactly as before.
        self.balance_of = balance_of
        self._txs: dict[bytes, Transaction] = {}  # insertion-ordered
        #: txid -> monotonic admission time, for age-based expiry
        #: (``expire``): a transfer that cannot mine — gapped seq, drained
        #: balance, owner walked away — must not occupy pool capacity
        #: forever.  Kept in lockstep with ``_txs``.
        self._admitted_at: dict[bytes, float] = {}
        self._by_slot: dict[tuple[str, int], bytes] = {}  # (sender, seq) -> txid
        #: sender -> sum(amount + fee) over its pending transactions;
        #: maintained on every add/replace/evict so the affordability
        #: check is O(1).
        self._pending_debit: dict[str, int] = {}
        #: All pending ``sync_key``s in sorted order — the pager's index.
        #: Serving one sync page is O(log n + page) against it (VERDICT r3
        #: item 9: the previous filter-everything pager made a full paged
        #: sync O(n²/page)); maintenance is one ``insort``/``del`` per
        #: add/remove (O(n) memmove worst case, but C-speed and amortized
        #: far below the per-tx signature verify).
        self._sorted: list[tuple[int, bytes]] = []
        #: FIFO window of recently confirmed slots -> confirmation count.
        #: Counted, not a set: nothing validates per-chain slot uniqueness,
        #: so one slot can be confirmed by several connected blocks and a
        #: partial reorg must not reopen it while another confirmation
        #: still stands.
        self._confirmed_slots: collections.OrderedDict[
            tuple[str, int], int
        ] = collections.OrderedDict()
        #: Monotonic mutation counter (bumped on every add/drop): lets
        #: the node's periodic checkpoint skip the disk write when the
        #: pool hasn't changed since the last save.
        self.mutations = 0
        #: Serialized bytes of every pending transaction, maintained on
        #: add/drop — the pool's term in the node's overload memory
        #: gauge (node/governor.py).  ``serialize`` is memoized, so the
        #: tally is a cached-bytes len, never a re-pack.
        self.bytes_pending = 0

    def __len__(self) -> int:
        return len(self._txs)

    def __contains__(self, txid: bytes) -> bool:
        return txid in self._txs

    def get(self, txid: bytes) -> Transaction | None:
        """The pending transaction with this txid, if any — compact-block
        reconstruction's lookup (txid = SHA-256d of the exact wire bytes,
        so a hit IS the block's transaction)."""
        return self._txs.get(txid)

    def txids(self) -> tuple:
        """Every pending txid, insertion-ordered — the reconciliation
        plane's full-pool enumeration (node/reconcile.py short IDs are
        computed per peer over exactly this set)."""
        return tuple(self._txs)

    def add(self, tx: Transaction) -> bool:
        """Admit ``tx``; False if coinbase, already known, outbid, or full.

        Coinbases never belong in a mempool: they are minted per block by
        the assembling miner, so a gossiped one is invalid and a reorg's
        resurrection path (``apply_block_delta``) must drop the abandoned
        branch's rewards rather than re-mine them into the new branch.

        A transaction occupying an already-pending (sender, seq) slot must
        strictly outbid the incumbent's fee to replace it (replace-by-fee;
        fees are integers, so "strictly more" is an absolute bump of >= 1 —
        an N-replacement gossip flood costs the attacker an N-unit fee,
        keeping amplification linear-cost).  Replacement frees the
        incumbent's capacity, so it works even when the pool is otherwise
        full.  A slot confirmed within the recent window is refused
        outright — a reordered or replayed spend of it can't re-enter.
        """
        if tx.is_coinbase:
            return False
        if self.chain_tag is not None and tx.chain != self.chain_tag:
            return False  # signed for a different chain (replay)
        if self.nonce_of is not None and tx.seq < self.nonce_of(tx.sender):
            return False  # seq already consumed on-chain (replay)
        if not tx.verify_signature(cache=self.sig_cache):
            # Unowned spends never enter the pool; re-admissions from
            # reorg resurrection re-check for free (verify-once cache),
            # and the block that later carries this transfer connects
            # without re-paying the backend at all.
            return False
        txid = tx.txid()
        if txid in self._txs:
            return False
        slot = (tx.sender, tx.seq)
        if slot in self._confirmed_slots:
            return False
        incumbent = self._by_slot.get(slot)
        if incumbent is not None:
            if tx.fee <= self._txs[incumbent].fee:
                return False
        elif len(self._txs) >= self.max_txs:
            return False
        if self.balance_of is not None:
            # Spendable = confirmed balance minus what this sender's OTHER
            # pending transactions already commit (the incumbent it would
            # replace doesn't count — both can never be in the pool).
            committed = self._pending_debit.get(tx.sender, 0)
            if incumbent is not None:
                inc = self._txs[incumbent]
                committed -= inc.amount + inc.fee
            if self.balance_of(tx.sender) - committed < tx.amount + tx.fee:
                return False
        if incumbent is not None:
            self._drop(self._txs[incumbent])
        self._txs[txid] = tx
        self._admitted_at[txid] = self._clock()
        self.bytes_pending += len(tx.serialize())
        self._by_slot[slot] = txid
        self._pending_debit[tx.sender] = (
            self._pending_debit.get(tx.sender, 0) + tx.amount + tx.fee
        )
        bisect.insort(self._sorted, sync_key(tx.fee, txid))
        self.mutations += 1
        return True

    def _drop(self, tx: Transaction) -> None:
        """Remove a pending ``tx`` from the pool + its debit tally + the
        sync index."""
        txid = tx.txid()
        if self._txs.pop(txid, None) is not None:
            self.bytes_pending -= len(tx.serialize())
        self._admitted_at.pop(txid, None)
        d = self._pending_debit.get(tx.sender, 0) - (tx.amount + tx.fee)
        if d > 0:
            self._pending_debit[tx.sender] = d
        else:
            self._pending_debit.pop(tx.sender, None)
        key = sync_key(tx.fee, txid)
        i = bisect.bisect_left(self._sorted, key)
        if i < len(self._sorted) and self._sorted[i] == key:
            del self._sorted[i]
        self.mutations += 1

    def _evict(self, tx: Transaction) -> None:
        """Mark ``tx``'s (sender, seq) slot confirmed: its pending occupant
        (``tx`` itself or a rival spend) leaves the pool, and the slot
        enters the bounded confirmed window so late replays are refused.

        (Any tx present in ``_txs`` is its slot's occupant — the maintained
        invariant — so the slot pop alone removes it.)
        """
        occupant = self._by_slot.pop((tx.sender, tx.seq), None)
        if occupant is not None and occupant in self._txs:
            self._drop(self._txs[occupant])
        if not tx.is_coinbase:  # coinbase slots can never re-enter anyway
            slot = (tx.sender, tx.seq)
            self._confirmed_slots[slot] = self._confirmed_slots.get(slot, 0) + 1
            self._confirmed_slots.move_to_end(slot)
            while len(self._confirmed_slots) > CONFIRMED_SLOT_WINDOW:
                self._confirmed_slots.popitem(last=False)

    def expire(self, max_age_s: float, now: float | None = None) -> int:
        """Drop transactions admitted more than ``max_age_s`` ago; return
        how many.  Pool hygiene, not consensus: an expired transfer's
        signature stays valid and its owner can rebroadcast — but a spend
        that has sat unmineable (gapped seq, drained balance) past any
        realistic confirmation horizon should stop occupying capacity and
        sync bandwidth.  ``now`` is injectable for deterministic tests.
        """
        now = self._clock() if now is None else now
        stale = [
            txid
            for txid, t in self._admitted_at.items()
            if now - t > max_age_s
        ]
        dropped = 0
        for txid in stale:
            tx = self._txs.get(txid)
            if tx is None:
                # Lockstep with _txs is a maintained invariant; if a future
                # edit breaks it, clear the orphaned stamp here rather than
                # re-reporting the same ghost on every pass.
                self._admitted_at.pop(txid, None)
                continue
            self._by_slot.pop((tx.sender, tx.seq), None)
            self._drop(tx)
            dropped += 1
        return dropped

    def pending_next_seq(self, sender: str, floor: int) -> int:
        """The seq a NEW transfer from ``sender`` should carry: ``floor``
        (the chain's confirmed nonce) advanced through the CONTIGUOUS run
        of pending slots.  Contiguous, not max+1: a stray gapped pending
        tx (someone pinned --seq far ahead) can never mine, and jumping
        past it would poison every auto-seq wallet tx after it — the
        contiguous walk hands out the seq that actually fills the gap."""
        seq = floor
        while (sender, seq) in self._by_slot:
            seq += 1
        return seq

    def sync_page(
        self, cursor: tuple[int, bytes] | None, max_txs: int
    ) -> tuple[list[Transaction], bool]:
        """One page of the pool for peer sync: fee-descending (txid-ascending
        on ties), strictly after ``cursor`` = (fee, txid) of the last
        transaction the requester already has.  Returns (page, more).

        The cursor is a *stable key*, not a position: evictions and
        replacements between pages can't shift unseen transactions behind
        it (a positional offset would silently skip them under churn), and
        transactions added mid-sync reach the requester through normal TX
        gossip since it is a connected peer by then.  Served from the
        maintained sorted index: O(log n + page) per call.
        """
        start = (
            bisect.bisect_right(self._sorted, sync_key(*cursor))
            if cursor is not None
            else 0
        )
        page = self._sorted[start : start + max_txs]
        return (
            [self._txs[txid] for _, txid in page],
            start + len(page) < len(self._sorted),
        )

    def select(self, max_txs: int = 1000) -> list[Transaction]:
        """Highest-fee-first block candidates, txid-ascending on fee ties —
        served straight off the maintained ``_sorted`` index, so assembly
        is O(selection), not O(n log n) per mined block.  (The tie-break is
        the same ``sync_key`` order the pager uses: deterministic and
        node-independent, which insertion order was not.)

        With ``balance_of``/``nonce_of`` wired, the selection is guaranteed
        connectable: each sender's summed debits within the selection stay
        within its confirmed balance (conservative — intra-block credits
        only help, so the sequential consensus check can only be looser
        than this one), and each sender's seqs form a gap-free run from
        its confirmed nonce (the consensus replay rule).  Ineligible
        transactions are skipped, not dropped: a reorg, a deposit, or a
        gap-filling arrival may qualify them later.

        Shape: a heap of each sender's *currently eligible* transaction
        (the one at its next nonce), popped best-fee-first; picking one
        unlocks the sender's next seq.  O(n log n) per assembly — a naive
        rescan-until-fixpoint is O(picked·n) and a single sender fee-
        bumping a long seq run (ascending fees = descending rank) makes
        that quadratic on the mining hot path.
        """
        if self.balance_of is None and self.nonce_of is None:
            return [self._txs[txid] for _, txid in self._sorted[:max_txs]]
        if self.nonce_of is None:
            # Affordability only: one fee-ordered pass, no seq coupling.
            picked = []
            spent: dict[str, int] = {}
            for _, txid in self._sorted:
                if len(picked) >= max_txs:
                    break
                tx = self._txs[txid]
                cost = tx.amount + tx.fee
                already = spent.get(tx.sender, 0)
                if self.balance_of(tx.sender) - already < cost:
                    continue
                spent[tx.sender] = already + cost
                picked.append(tx)
            return picked

        import heapq

        by_sender: dict[str, dict[int, Transaction]] = {}
        for tx in self._txs.values():
            by_sender.setdefault(tx.sender, {})[tx.seq] = tx
        heap: list[tuple[int, bytes]] = []  # sync_key of eligible txs
        for sender, seqs in by_sender.items():
            tx = seqs.get(self.nonce_of(sender))
            if tx is not None:
                heap.append(sync_key(tx.fee, tx.txid()))
        heapq.heapify(heap)
        picked = []
        spent = {}
        while heap and len(picked) < max_txs:
            _, txid = heapq.heappop(heap)
            tx = self._txs[txid]
            if self.balance_of is not None:
                cost = tx.amount + tx.fee
                already = spent.get(tx.sender, 0)
                if self.balance_of(tx.sender) - already < cost:
                    # Later seqs of this sender would gap behind the
                    # unaffordable one — the sender's run ends here.
                    continue
                spent[tx.sender] = already + cost
            picked.append(tx)
            nxt = by_sender[tx.sender].get(tx.seq + 1)
            if nxt is not None:
                heapq.heappush(heap, sync_key(nxt.fee, nxt.txid()))
        return picked

    def restore(self, tx: Transaction, age_s: float, now: float | None = None) -> bool:
        """Re-admit a persisted transaction with its pre-restart age,
        through FULL admission validation (signature, chain tag, nonce,
        affordability — the chain may have moved while the node was
        down).  Backdating the admission stamp keeps the TTL clock honest
        across restarts: a transfer that sat unmineable for an hour
        before the restart does not get a fresh hour after it."""
        if not self.add(tx):
            return False
        now = self._clock() if now is None else now
        self._admitted_at[tx.txid()] = now - max(0.0, age_s)
        return True

    def snapshot(self, now: float | None = None) -> list[tuple[Transaction, float]]:
        """(transaction, age_seconds) for every pending transaction —
        what persistence saves.  Ages, not absolute stamps: admission
        times are monotonic-clock values, meaningless across processes."""
        now = self._clock() if now is None else now
        return [
            (tx, max(0.0, now - self._admitted_at[txid]))
            for txid, tx in self._txs.items()
        ]

    def apply_block_delta(
        self, removed: tuple[Block, ...], added: tuple[Block, ...]
    ) -> None:
        """Sync the pool with a tip movement reported by ``Chain.add_block``.

        Transactions in newly-connected blocks leave the pool; transactions
        from abandoned blocks come back (unless the new branch also
        confirmed them — eviction runs last to win that race).

        Known, accepted loss (ADVICE r3): when a block confirms a slot, a
        *pending higher-fee rival* of that slot is evicted and NOT
        remembered — if the block is later reorged away, only the mined
        transaction is resurrected here, so the outbid rival is gone even
        though it would have won RBF.  Re-admitting it would require an
        unbounded evicted-rival archive; the rival's owner simply
        rebroadcasts (its signature is still valid and its slot reopened).
        """
        for block in removed:
            for tx in block.txs:
                # ONE confirmation of this slot is being rolled back; the
                # slot reopens only when no other connected block still
                # confirms it (hence the count, not a set-discard).
                slot = (tx.sender, tx.seq)
                count = self._confirmed_slots.get(slot)
                if count is not None:
                    if count <= 1:
                        del self._confirmed_slots[slot]
                    else:
                        self._confirmed_slots[slot] = count - 1
                self.add(tx)
        for block in added:
            for tx in block.txs:
                self._evict(tx)
