from p1_tpu.mempool.mempool import Mempool

__all__ = ["Mempool"]
