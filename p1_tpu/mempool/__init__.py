from p1_tpu.mempool.mempool import Mempool, sync_key

__all__ = ["Mempool", "sync_key"]
