from p1_tpu.mempool.mempool import (
    Mempool,
    dump_mempool,
    load_mempool,
    save_mempool,
    sync_key,
    write_mempool_file,
)

__all__ = [
    "Mempool",
    "dump_mempool",
    "load_mempool",
    "save_mempool",
    "sync_key",
    "write_mempool_file",
]
