from p1_tpu.miner.miner import MineStats, Miner

__all__ = ["Miner", "MineStats"]
