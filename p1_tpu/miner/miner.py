"""``Miner.search_nonce()``: the proof-of-work search loop.

Capability parity: the reference miner's inner loop — "double-SHA-256 over a
serialized ``BlockHeader`` with an incrementing nonce" (BASELINE.json:5) —
restructured for a device-stepped world: the miner asks its ``HashBackend``
to scan a *chunk* of nonce space per call (millions of candidates for the
JAX/TPU backends, which internally pipeline jitted device steps), checks the
abort signal between chunks so a new chain tip cancels stale work promptly,
and rolls the header timestamp to reopen the nonce space when all 2**32
candidates are exhausted (the classic extra-nonce trick, without touching
the merkle root).
"""

from __future__ import annotations

import dataclasses
import threading
import time

from p1_tpu.core.header import BlockHeader
from p1_tpu.hashx.backend import HashBackend, get_backend

NONCE_SPACE = 1 << 32


@dataclasses.dataclass
class MineStats:
    """Counters from one ``search_nonce`` call (metrics surface)."""

    hashes_done: int = 0
    elapsed_s: float = 0.0
    timestamp_rolls: int = 0
    aborted: bool = False

    @property
    def hashes_per_sec(self) -> float:
        return self.hashes_done / self.elapsed_s if self.elapsed_s > 0 else 0.0


class Miner:
    """Drives a ``HashBackend`` over nonce space to seal block headers.

    ``chunk`` is the number of nonces requested per backend call — the abort
    granularity.  The JAX backends pipeline device steps *within* a chunk, so
    the chunk should span several device batches; ``chunk=None`` derives
    4x the backend's ``step_span`` (the nonces one device step covers —
    mesh-wide for the sharded backend) when it has one, keeping the
    pipeline full, else a CPU-friendly 2**22.
    """

    def __init__(
        self,
        backend: str | HashBackend = "cpu",
        chunk: int | None = None,
        max_timestamp_rolls: int | None = None,
    ):
        self.backend = get_backend(backend) if isinstance(backend, str) else backend
        if chunk is None:
            span = getattr(self.backend, "step_span", None)
            chunk = 4 * span if span else 1 << 22
        if chunk <= 0:
            raise ValueError("chunk must be positive")
        self.chunk = chunk
        self.max_timestamp_rolls = max_timestamp_rolls
        self.last_stats = MineStats()

    def search_nonce(
        self,
        header: BlockHeader,
        abort: threading.Event | None = None,
        start_nonce: int = 0,
    ) -> BlockHeader | None:
        """Find a sealed header whose hash meets ``header.difficulty``.

        Returns the input header with the winning nonce (and possibly a
        rolled timestamp) attached, or None if ``abort`` was set first.
        The search is deterministic for a given header: nonce space is
        scanned in increasing order from ``start_nonce``, so the earliest
        valid nonce at the original timestamp is always preferred.
        """
        stats = MineStats()
        self.last_stats = stats
        t0 = time.perf_counter()
        try:
            while True:
                prefix = header.mining_prefix()
                nonce = start_nonce
                while nonce < NONCE_SPACE:
                    if self._chunk_sync(abort):
                        stats.aborted = True
                        return None
                    count = min(self.chunk, NONCE_SPACE - nonce)
                    res = self.backend.search(
                        prefix, nonce, count, header.difficulty
                    )
                    stats.hashes_done += res.hashes_done
                    if res.nonce is not None:
                        return header.with_nonce(res.nonce)
                    nonce += count
                # Nonce space exhausted: roll the timestamp and rescan.
                if (
                    self.max_timestamp_rolls is not None
                    and stats.timestamp_rolls >= self.max_timestamp_rolls
                ):
                    return None
                stats.timestamp_rolls += 1
                header = header.with_timestamp(header.timestamp + 1)
                start_nonce = 0
        finally:
            stats.elapsed_s = time.perf_counter() - t0

    def _chunk_sync(self, abort: threading.Event | None) -> bool:
        """Per-chunk stop decision, called before every backend call.

        Hook point for lockstep mining: the default is a local abort-event
        check; the multi-host PodMiner (p1_tpu/parallel/pod.py) overrides
        it to broadcast the leader's decision so every process leaves the
        chunk loop at the same iteration.
        """
        return abort is not None and abort.is_set()
