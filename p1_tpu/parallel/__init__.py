from p1_tpu.parallel.pod import PodMiner, init_distributed

__all__ = ["PodMiner", "init_distributed"]
