"""Multi-host pod mining: many processes, one miner on the gossip network.

Capability parity: the north star's pod-scale mode — "a v5e-8 pod presents
as a single miner on the gossip network" (BASELINE.json:5, config 5 at
BASELINE.json:11) — extended to MULTI-HOST the way the reference's
NCCL/MPI-style backend would scale: ``jax.distributed`` forms one global
device mesh across processes/hosts, the unmodified ``sharded`` backend's
``shard_map``+``pmin`` step runs over it (collectives ride ICI within a
host and the JAX distributed transport across hosts), and only the leader
process speaks the p2p gossip protocol.

**Lockstep design** (multi-controller SPMD): every process must execute
the same sequence of jitted collectives.  Everything inside a nonce search
is deterministic given its inputs — the sharded backend's fixed step spans,
the chunk loop, the timestamp roll, and the ``pmin``-reduced result that
every process observes identically — so only two things ever need
host-level agreement, both broadcast from the leader with
``multihost_utils.broadcast_one_to_all``:

1. what to search (START: the 80-byte draft header + start nonce), and
2. whether to keep going (one CONTINUE/ABORT byte per chunk, hooked into
   ``Miner._chunk_sync`` — the leader's abort event, e.g. "new tip arrived
   via gossip", reaches every process at the same chunk boundary).

A follower therefore runs the IDENTICAL ``Miner.search_nonce`` loop and
leaves it at the same iteration with the same result; it just discards
the sealed header (the leader's node gossips the block).
"""

from __future__ import annotations

import threading

import numpy as np

from p1_tpu.core.header import HEADER_SIZE, BlockHeader
from p1_tpu.miner import Miner

# START/SHUTDOWN frame: op(1) + pad(7) + start_nonce(u64) + header(80).
_CTRL = 96
_OP_START = 1
_OP_SHUTDOWN = 2


def init_distributed(
    coordinator: str, num_processes: int, process_id: int
) -> None:
    """Join the JAX distributed runtime (call before ANY other JAX use).

    After this, ``jax.devices()`` is the global mesh across all processes
    and ``get_backend("sharded")`` shards nonce ranges over every chip of
    every host.
    """
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


def _broadcast_bytes(data: bytes | None, size: int) -> bytes:
    """Leader (data != None) -> everyone; returns the agreed bytes."""
    from jax.experimental import multihost_utils

    buf = np.zeros((size,), dtype=np.uint8)
    if data is not None:
        if len(data) > size:
            raise ValueError(f"control frame {len(data)} > {size}")
        buf[: len(data)] = np.frombuffer(data, dtype=np.uint8)
    out = multihost_utils.broadcast_one_to_all(buf)
    return bytes(np.asarray(out))


class PodMiner(Miner):
    """A Miner whose chunk loop runs in lockstep across all processes.

    Leader (process 0): plug into a ``Node`` like any Miner — every
    ``search_nonce`` broadcasts a START frame, then mines normally with
    per-chunk CONTINUE/ABORT broadcasts.  Followers: call ``follow()``,
    which mirrors each search until ``shutdown()``.
    """

    def __init__(self, *, is_leader: bool, **kwargs):
        super().__init__(**kwargs)
        self.is_leader = is_leader
        self._cv = threading.Condition()
        self._busy = False
        #: Optional liveness callback, invoked at every lockstep point
        #: (search start, each chunk, each mirrored search) — the CLI wires
        #: a watchdog to it so a vanished peer doesn't hang the survivor in
        #: a collective forever.
        self.heartbeat = None
        # Construction-time config handshake: lockstep depends on every
        # process using the same chunk and per-step span — a mismatch would
        # diverge the collective sequence and hang the pod with no
        # diagnostic.  allgather (not broadcast) so EVERY rank — leader
        # included — sees the disagreement and fails loudly.
        from jax.experimental import multihost_utils

        mine = np.array(
            [self.chunk, getattr(self.backend, "step_span", 0)], dtype=np.int64
        )
        everyone = np.asarray(multihost_utils.process_allgather(mine))
        if not (everyone == mine).all():
            raise ValueError(
                "pod config mismatch: per-process (chunk, step_span) = "
                f"{everyone.tolist()} — launch every process with identical "
                "--chunk/--batch"
            )

    # -- leader ----------------------------------------------------------

    def search_nonce(
        self,
        header: BlockHeader,
        abort: threading.Event | None = None,
        start_nonce: int = 0,
    ) -> BlockHeader | None:
        if not self.is_leader:
            raise RuntimeError("followers mirror via follow(), not search_nonce")
        with self._cv:
            self._busy = True
        try:
            if self.heartbeat is not None:
                self.heartbeat()
            frame = (
                bytes([_OP_START])
                + bytes(7)
                + int(start_nonce).to_bytes(8, "big")
                + header.serialize()
            )
            _broadcast_bytes(frame, _CTRL)
            return super().search_nonce(header, abort, start_nonce)
        finally:
            with self._cv:
                self._busy = False
                self._cv.notify_all()

    def shutdown(self, timeout: float = 120.0) -> None:
        """Leader: release followers from ``follow()``.

        Joins any in-flight search first: its worker thread still owes the
        followers per-chunk broadcasts, and a SHUTDOWN frame interleaved
        with those would desync the collective sequence pod-wide.  The
        caller must have aborted the search already (Node.stop_mining does)
        or this times out.
        """
        if not self.is_leader:
            return
        with self._cv:
            if not self._cv.wait_for(lambda: not self._busy, timeout=timeout):
                raise RuntimeError(
                    "shutdown() while a search is still running — abort it "
                    "first (stop_mining)"
                )
        _broadcast_bytes(bytes([_OP_SHUTDOWN]), _CTRL)

    # -- follower --------------------------------------------------------

    def follow(self) -> int:
        """Mirror the leader's searches until SHUTDOWN; returns how many
        searches were mirrored."""
        if self.is_leader:
            raise RuntimeError("the leader drives searches itself")
        mirrored = 0
        while True:
            if self.heartbeat is not None:
                self.heartbeat()
            frame = _broadcast_bytes(None, _CTRL)
            op = frame[0]
            if op == _OP_SHUTDOWN:
                return mirrored
            if op != _OP_START:
                raise ValueError(f"unexpected pod control op {op}")
            start_nonce = int.from_bytes(frame[8:16], "big")
            header = BlockHeader.deserialize(frame[16 : 16 + HEADER_SIZE])
            super().search_nonce(header, abort=None, start_nonce=start_nonce)
            mirrored += 1

    # -- lockstep chunk gate ---------------------------------------------

    def _chunk_sync(self, abort: threading.Event | None) -> bool:
        """One byte of leader truth per chunk: every process leaves the
        chunk loop at the same iteration."""
        if self.heartbeat is not None:
            self.heartbeat()
        if self.is_leader:
            stop = abort is not None and abort.is_set()
            return _broadcast_bytes(bytes([int(stop)]), 1)[0] != 0
        return _broadcast_bytes(None, 1)[0] != 0
