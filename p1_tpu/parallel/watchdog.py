"""Pod failure handling: the no-progress watchdog and leader failover.

Extracted from ``cli.py`` (which keeps only parsing + dispatch): the
process-level failsafes `p1 pod` arms around the lockstep mesh — a
follower that loses the pod exits 3 for its supervisor; the leader
re-execs itself into single-process mining on the same store so the
chain never goes dark (SURVEY §5 elastic recovery).
"""

from __future__ import annotations

import logging
import os
import sys
import time

#: The documented supervisor signal: a pod process that lost its mesh
#: exits with this code (``Restart=on-failure`` restarts it once the
#: coordinator is back).  ONE constant shared by the watchdog trip and
#: the follower's dead-collective exit so the two paths can never
#: drift (tests assert this exact code).
POD_LOST_EXIT = 3


class PodWatchdog:
    """No-progress failsafe: a vanished pod peer leaves the survivor
    blocked inside a collective forever (aborts can't unblock it, and
    interpreter exit would hang on the executor join), so if no lockstep
    point is reached for ``grace`` seconds the process fails over.
    ``grace`` covers the longest LEGITIMATE inter-beat gap — the first
    search's jit compile on a real mesh plus one chunk — independent of
    run length (progress-based, not an absolute deadline).  Override with
    ``P1_POD_GRACE_S`` (tests shrink it; operators can tune it).

    On trip the watchdog runs ``on_trip`` — the LEADER re-execs itself
    into a single-process ``p1 node`` against the same store and identity
    (SURVEY §5 elastic recovery: mining degrades instead of going dark;
    see ``cmd_pod``), while followers, whose chain state lives in the
    leader, still just exit ``POD_LOST_EXIT`` for their external
    supervisor to restart.

    ``beat()`` is a plain monotonic-timestamp store (the hot path runs it
    per chunk); one long-lived daemon thread polls, instead of spawning a
    Timer thread per beat.
    """

    _POLL_S = 1.0

    def __init__(self, role: str, on_trip=None):
        import threading

        self.role = role
        self.grace_s = float(os.environ.get("P1_POD_GRACE_S", "600"))
        self._on_trip = on_trip
        self._last = time.monotonic()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._poll, daemon=True)
        self._thread.start()

    def beat(self) -> None:
        self._last = time.monotonic()

    def cancel(self) -> None:
        self._stop.set()

    def _poll(self) -> None:
        while not self._stop.wait(self._POLL_S):
            if time.monotonic() - self._last > self.grace_s:
                logging.error(
                    "pod watchdog (%s): no lockstep progress for %.0fs "
                    "(peer lost?), failing over",
                    self.role,
                    self.grace_s,
                )
                if self._on_trip is not None:
                    try:
                        self._on_trip()
                    except Exception:
                        # A failed leader failover (os.execv can raise
                        # ENOMEM/E2BIG, or the interpreter path vanished)
                        # must still END the wedged process — the exit
                        # code is the supervisor's only signal.
                        logging.exception("pod failover failed")
                os._exit(POD_LOST_EXIT)  # followers, or a failed on_trip


def pod_leader_failover(args, deadline: float) -> None:
    """Degrade the pod leader to a single-process ``p1 node`` when a pod
    peer vanishes (VERDICT r3 item 8 / SURVEY §5 elastic recovery).

    ``os.execv`` replaces the wedged process image in place: the thread
    stuck inside the dead collective, the jax.distributed client, and the
    executor all go with it, while the pid (for the operator) and the
    environment (JAX platform pins, XLA flags) survive.  The store's
    writer flock is released automatically — Python opens files
    close-on-exec — so the SAME process re-acquires the SAME store and
    mining continues on the persisted chain with the same coinbase
    identity and peer list, for the remainder of the original window.
    Followers hold no chain state, so they still exit for their
    supervisor (cmd_pod docstring documents the recipe).  A leader
    configured with ``--port 0`` re-binds a fresh ephemeral port; pinned
    ports are re-bound exactly (the old socket died with the exec).
    """
    argv = [
        sys.executable, "-m", "p1_tpu", "node",
        "--difficulty", str(args.difficulty),
        "--backend", "sharded",  # local mesh only, no jax.distributed
        "--host", args.host,
        "--port", str(args.port),
        "--duration", f"{max(5.0, deadline - time.time()):.1f}",
    ]
    if args.peers:
        argv += ["--peers", *args.peers]
    if args.miner_id:
        argv += ["--miner-id", args.miner_id]
    if args.store:
        argv += ["--store", args.store]
    if args.chunk:
        argv += ["--chunk", str(args.chunk)]
    if args.batch:
        argv += ["--batch", str(args.batch)]
    if args.platform:
        argv += ["--platform", args.platform]
    logging.error("pod leader failing over to solo mining: %s", " ".join(argv))
    sys.stderr.flush()
    os.execv(sys.executable, argv)
