"""NumPy hash backend: batch-vectorized SHA-256d over the nonce lane.

Role in the framework: the host-side *vectorized oracle*.  It shares the
midstate formulation with the JAX/Pallas device kernels (one uint32 lane per
candidate nonce, chunk-2 + second-pass compression only), so kernel tests can
diff the two lane-by-lane.  It is an *oracle*, not a fast miner: NumPy's
per-op dispatch makes it measurably slower than the hashlib loop (~0.5 vs
~0.8 MH/s) — use ``cpu`` when you want host hashrate.

The layout mirrors what runs on the TPU VPU: every SHA-256 word is a vector
of ``count`` uint32 lanes; rotations are shift/or pairs; the 64 rounds are an
unrolled Python loop over vector ops (traced once — no per-nonce Python).
"""

from __future__ import annotations

import numpy as np

from p1_tpu.core.header import target_from_difficulty, target_to_words
from p1_tpu.hashx.backend import HashBackend, SearchResult, register
from p1_tpu.hashx.sha256_ref import IV, K, header_midstate, header_tail_words, sha256d

_K = np.array(K, dtype=np.uint32)
_IV = np.array(IV, dtype=np.uint32)


def _rotr(x: np.ndarray, n: int) -> np.ndarray:
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _schedule_extend(w: list[np.ndarray]) -> list[np.ndarray]:
    for i in range(16, 64):
        s0 = _rotr(w[i - 15], 7) ^ _rotr(w[i - 15], 18) ^ (w[i - 15] >> np.uint32(3))
        s1 = _rotr(w[i - 2], 17) ^ _rotr(w[i - 2], 19) ^ (w[i - 2] >> np.uint32(10))
        w.append(w[i - 16] + s0 + w[i - 7] + s1)
    return w


def _compress(state: list[np.ndarray], w: list[np.ndarray]) -> list[np.ndarray]:
    a, b, c, d, e, f, g, h = state
    for i in range(64):
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + _K[i] + w[i]
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        a, b, c, d, e, f, g, h = t1 + t2, a, b, c, d + t1, e, f, g
    return [x + y for x, y in zip(state, (a, b, c, d, e, f, g, h))]


def sha256d_lanes(
    midstate: np.ndarray, tail: np.ndarray, nonces: np.ndarray
) -> list[np.ndarray]:
    """SHA-256d digests (8 uint32 word-vectors) for a vector of nonces.

    ``midstate``: (8,) uint32 — chunk-1 state of the 80-byte header.
    ``tail``: (3,) uint32 — chunk-2 words 0..2 (header bytes 64..76).
    ``nonces``: (n,) uint32 — chunk-2 word 3 per lane.
    """
    n = nonces.shape[0]
    zeros = np.zeros(n, dtype=np.uint32)

    def bc(v: np.uint32) -> np.ndarray:
        return np.full(n, v, dtype=np.uint32)

    # Chunk 2 of pass 1: 16 header-tail bytes, 0x80 pad, bit length 640.
    w = [bc(tail[0]), bc(tail[1]), bc(tail[2]), nonces.astype(np.uint32)]
    w += [bc(np.uint32(0x80000000))] + [zeros] * 10 + [bc(np.uint32(640))]
    state1 = _compress([bc(v) for v in midstate], _schedule_extend(w))

    # Pass 2: the 32-byte digest as its own single padded block (length 256).
    w2 = list(state1) + [bc(np.uint32(0x80000000))] + [zeros] * 6 + [bc(np.uint32(256))]
    return _compress([bc(v) for v in _IV], _schedule_extend(w2))


def lanes_below_target(digest_words: list[np.ndarray], difficulty: int) -> np.ndarray:
    """Boolean mask of lanes whose big-endian digest is < the target."""
    t_words = target_to_words(target_from_difficulty(difficulty))
    n = digest_words[0].shape[0]
    lt = np.zeros(n, dtype=bool)
    eq = np.ones(n, dtype=bool)
    for dw, tw in zip(digest_words, t_words):
        tw = np.uint32(tw)
        lt |= eq & (dw < tw)
        eq &= dw == tw
    return lt


@register("numpy")
class NumpyBackend(HashBackend):
    """Vectorized CPU backend; also the ground truth for the device kernels."""

    def __init__(self, batch: int = 1 << 16):
        self.batch = batch

    def sha256d(self, data: bytes) -> bytes:
        return sha256d(data)  # single digests don't benefit from lanes

    def search(
        self, header_prefix: bytes, nonce_start: int, count: int, difficulty: int
    ) -> SearchResult:
        self._check_search_args(header_prefix, nonce_start, count, difficulty)
        midstate = np.array(header_midstate(header_prefix), dtype=np.uint32)
        tail = np.array(header_tail_words(header_prefix), dtype=np.uint32)
        done = 0
        while done < count:
            n = min(self.batch, count - done)
            nonces = (nonce_start + done + np.arange(n, dtype=np.uint64)).astype(
                np.uint32
            )
            hits = lanes_below_target(
                sha256d_lanes(midstate, tail, nonces), difficulty
            )
            idx = np.flatnonzero(hits)
            if idx.size:
                return SearchResult(int(nonces[idx[0]]), done + int(idx[0]) + 1)
            done += n
        return SearchResult(None, count)
