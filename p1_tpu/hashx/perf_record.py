"""Recorded healthy performance figures — ONE home for the magic numbers.

``bench.py``'s relay-degradation guard and ``docs/PERF.md``'s tables both
need "what this code measures on a healthy v5e"; duplicating the number in
each (as round 3 did) let them drift and hid regressions between 0.3× and
1.0× of the real rate (VERDICT r3 weak #6).  Update HERE when a kernel or
platform change moves the measurement, and the guard + docs follow.

These are *records of past measurements*, not targets: the bench always
reports what it actually measured.
"""

#: Pallas kernel ("tpu" backend), batch 2²⁷, one v5e chip via the axon
#: relay — the round-3/4 sweep plateau (docs/PERF.md).
RECORDED_V5E_PALLAS_HPS = 750e6

#: hashlib "cpu" backend, best-of-3 over ≥2 s windows at difficulty 20,
#: measured 2026-08-04 on THIS 1-vCPU bench host at 1-minute loadavg
#: 0.13 (effectively idle) — the healthiest measurement on record.  The
#: GRADED denominator pin (VERDICT r5 weak #2): the live
#: ``cpu_baseline_hps`` swung 842k → 359k → 773k → 298k H/s across
#: rounds 2-5 on co-tenant load alone, dragging the headline
#: ``vs_baseline`` ratio from 126× to 2481× while the kernel itself sat
#: still — so ``bench.py`` reports ``vs_recorded`` against this figure
#: next to the live ratio, plus the loadavg context that tells a reader
#: which one to trust (docs/PERF.md "Which ratio to trust").
RECORDED_CPU_BASELINE_HPS = 1_050_000.0

#: Fraction of the recorded rate below which a TPU measurement is treated
#: as the relay's known transient ~25× degradation (observed 2026-07-30)
#: rather than a real kernel change, and re-measured after a wait.
DEGRADED_FRACTION = 0.3

#: Host ingest plane (benchmarks/host_ingest.py, default config: 1000
#: blocks × 2 signed transfers, difficulty 1, signature memo warm) —
#: blocks/s through deserialize → check_block → add_block on the
#: zero-repack pipeline, measured 2026-08-04 on the 1-vCPU bench host
#: (docs/PERF.md "host ingest plane").  ``bench.py`` reports degradation
#: against it; update HERE when the host pipeline moves.
RECORDED_HOST_INGEST_BPS = 22_000.0

#: Same-session fraction below which ``bench.py`` flags the host ingest
#: measurement as a regression in its JSON output.  Looser than the TPU
#: guard: host rates on the shared 1-vCPU box wobble with co-tenants.
HOST_INGEST_DEGRADED_FRACTION = 0.5

#: Staged ingest (round 19, node/pipeline.py): blocks/s through the
#: staged pipeline driver (benchmarks/host_ingest.py ``--cores``,
#: default shape: 1000 blocks × 2 signed transfers, difficulty 1,
#: COLD signature cache — unlike the warmed serial figure above, the
#: validate lane pays real Ed25519 here) at the 1-worker rung.
#: Measured 2026-08-06 on the 1-vCPU bench host: with one core there
#: is no parallelism to sell, so this pin records the staging
#: ARCHITECTURE cost next to the unstaged control (the ≤5% overhead
#: acceptance) — the 2× multi-core claim is for hosts with cores to
#: spend, re-record there (docs/PERF.md "Staged node" has the ladder
#: and the honest 1-vCPU row).  ``bench.py`` emits
#: ``staged_ingest_vs_recorded`` against this figure.
RECORDED_STAGED_INGEST_BPS = 1_450.0

#: Same-session fraction below which the staged-ingest measurement is
#: flagged degraded (fsynced store appends + cold-cache verification:
#: the most IO/co-tenant-sensitive host figure).
STAGED_INGEST_DEGRADED_FRACTION = 0.4

#: Untrusted-path revalidation: blocks/s through
#: ``ChainStore.load_chain(trusted=False)`` on the bench shape (400
#: blocks × 2 signed transfers, difficulty 1) with the batched-signature
#: fast lane on the AUTO backend ladder.  Re-pinned 2026-08-05
#: (loadavg 0.43) for the round-15 native Ed25519 engine — the auto
#: ladder now resolves native on this toolchain-equipped wheel-less
#: host, so the prior 329 blocks/s pin (pure-Python batch, re-pinned
#: 2026-08-04 after the subgroup-gate consensus fix; the 1,100 pin
#: before THAT was the ungated consensus-divergent batch) describes a
#: rung this host no longer runs — ratios against it would misread the
#: backend ladder as a 13× speedup of the same code.  A host without a
#: C++ toolchain still lands on the fallback rung and should read its
#: numbers against 329, which keys.py's one-time warning names.
#: ``bench.py`` emits ``revalidate_vs_recorded`` against this figure —
#: the denominator-pinning convention of RECORDED_CPU_BASELINE_HPS.
RECORDED_REVALIDATE_BPS = 4376.0
#: The retired pure-Python-batch pin (see above), kept for wheel-less
#: toolchain-less hosts to read their fallback numbers against.
RECORDED_REVALIDATE_FALLBACK_BPS = 329.0

#: Same-session fraction below which the revalidation measurement is
#: flagged degraded in the bench JSON (same tolerance rationale as the
#: ingest guard).
REVALIDATE_DEGRADED_FRACTION = 0.5

#: Native C++ Ed25519 engine (round 15, native/ed25519.cpp):
#: milliseconds per signature through ``keys.verify_batch`` on the
#: native rung at the 1024-signature bench window, subgroup gate
#: included, measured 2026-08-05 on the 1-vCPU bench host
#: (benchmarks/sig_verify.py ``native_batch1024_us``).  The fallback
#: warning in core/keys.py names this figure so a wheel-less,
#: compiler-less operator knows what one `g++` buys.  ``bench.py``
#: emits ``sig_native_ms`` against it.
RECORDED_SIG_NATIVE_MS = 0.07

#: Device-sharded JAX MSM (round 15, hashx/ed25519_msm.py):
#: milliseconds per signature through ``verify_batch_device`` on the
#: 8-virtual-device CPU mesh at the 512-signature bench window,
#: subgroup gates included, measured 2026-08-05 on the 1-vCPU bench
#: host (loadavg ≤4.7 — the per-mesh XLA compiles themselves).
#: Context the number needs: on ONE CPU host the mesh is virtual, so
#: this records the ARCHITECTURE cost (dispatch + vectorized int32
#: field arithmetic sharing one core), not a speedup — ~13× slower
#: than the pure-Python MSM here, which is why the device rung is
#: opt-in.  The path exists for real multi-chip meshes; re-record
#: there.  ``bench.py`` emits ``sig_device_ms`` against it (behind
#: P1_BENCH_DEVICE).
RECORDED_SIG_DEVICE_MS = 18.7

#: Same-session factor over the recorded per-signature figures above
#: which a measurement is flagged degraded in the bench JSON (LOWER is
#: better for both; generous band for co-tenant noise).
SIG_DEGRADED_FACTOR = 2.0

#: Query serving plane (round 9): cached proofs/s through the proof
#: cache's steady state — LRU payload hit + 4-byte tip patch per serve
#: (benchmarks/query_plane.py ``bench_quick``: 60 blocks x 24 signed
#: transfers, difficulty 1).  Measured 2026-08-04 on the 1-vCPU bench
#: host at 1-minute loadavg 0.46; the same run measured the serial
#: per-proof baseline at ~29k/s and the cold batched path at ~136k/s —
#: the ROADMAP ≥50k/s bar is cleared by the batched path alone, before
#: the cache or any `p1 serve` process fan-out.  ``bench.py`` emits
#: ``query_vs_recorded`` against this figure — the denominator-pinning
#: convention of RECORDED_CPU_BASELINE_HPS.
RECORDED_QUERY_QPS = 980_000.0

#: Same-session fraction below which the query-plane measurement is
#: flagged degraded in the bench JSON (host-load tolerance, as above).
QUERY_DEGRADED_FRACTION = 0.5

#: Deterministic network simulator (round 10): node-seconds of
#: simulated mesh per wall second — nodes x virtual_s / wall_s on the
#: 200-node partition-heal scenario (benchmarks/netsim_scale.py;
#: node/netsim.py).  Measured 2026-08-04 on the 1-vCPU bench host at
#: low load: ~1,900 (and ~1,050 at the 1000-node acceptance scale —
#: the rate falls with mesh size as per-event Python cost dominates;
#: docs/PERF.md "Simulated mesh scale" has the ladder).  Context: real
#: sockets on this host topped out at ~7 nodes at 1x real time = ~7
#: node-seconds/second, so the pinned figure is a ~270x scale-up.
#: ``bench.py`` emits ``sim_vs_recorded`` against this figure — the
#: denominator-pinning convention of RECORDED_CPU_BASELINE_HPS.
RECORDED_SIM_RATE = 1_900.0

#: Same-session fraction below which the simulator measurement is
#: flagged degraded in the bench JSON.  Wider than the host-plane
#: guards: the figure is pure-Python event-loop throughput, the most
#: co-tenant-sensitive measurement in the file.
SIM_DEGRADED_FRACTION = 0.4

#: Sharded far-field plane (round 17, node/farfield.py): node-seconds
#: of simulated mesh per wall second on the bench probe shape (2,000
#: total nodes — a 16-full-node core + header-only far field — at 2
#: PROCESS shards over the pipe seam; benchmarks/netsim_scale.py
#: ``bench_far_field``).  Measured 2026-08-05 on the 1-vCPU bench host
#: at 1-minute loadavg 0.75.  Read it for what it is: header-only
#: node-seconds, ~50x the full-node sim rate because a far-field node
#: is ~50x less node (no mempool/ledger/store/supervision —
#: docs/PERF.md "Sharded far field" has the model's omissions and the
#: 10k ladder, where 1 shard beats 2 and 4 on this host: one vCPU has
#: no parallelism to sell, so process shards only add pipe+spawn cost;
#: the split exists for multi-core hosts and the determinism proof).
#: ``bench.py`` emits ``sim_sharded_vs_recorded`` against this figure.
RECORDED_SIM_SHARDED_RATE = 52_000.0

#: Same-session degraded threshold; same substrate sensitivity as the
#: full-node sim figure.
SIM_SHARDED_DEGRADED_FRACTION = 0.4

#: Chaos plane (round 11): combined-fault schedules per wall second on
#: the default 5-node/10-event configuration (benchmarks/chaos_rate.py;
#: node/chaos.py) — each schedule a full mesh life cycle: formation,
#: warmup, the fault events (crashes with torn appends, disk errors,
#: partitions, adversaries), heal epilogue, settle, and the invariant
#: suite.  Measured 2026-08-04 on the 1-vCPU bench host at load 0.07:
#: ~9.9 schedules/s at a ~320x virtual-per-wall ratio (a schedule
#: spans ~33 virtual seconds of production-deadline supervision and
#: recovery backoff).  ``bench.py`` emits ``chaos_vs_recorded``
#: against this figure — the denominator-pinning convention of
#: RECORDED_CPU_BASELINE_HPS.
RECORDED_CHAOS_RATE = 9.9

#: Same-session degraded threshold; as co-tenant-sensitive as the sim
#: figure (same pure-Python event-loop substrate).
CHAOS_DEGRADED_FRACTION = 0.4

#: Untrusted snapshot sync (round 12): seconds from a cold snapshot
#: file to SERVING queries — load + CRC/digest/state-root verification
#: + ``Chain.from_snapshot`` + the first balance/header/proof answers —
#: on the bench probe shape (benchmarks/snapshot_boot.py
#: ``bench_quick``: 2,000 blocks, ~1k accounts; the figure is
#: O(accounts), chain length barely moves it: the full 100k-block run
#: measured the SAME 0.004 s against a 17.4 s batched revalidation in
#: the same session — docs/PERF.md "Snapshot boot").  Measured
#: 2026-08-04 on the 1-vCPU bench host at 1-minute loadavg 0.44.
#: LOWER is better — ``bench.py`` emits ``snapshot_vs_recorded`` =
#: measured / recorded, flagged degraded above the factor below.
RECORDED_SNAPSHOT_BOOT_S = 0.004

#: Factor over the recorded boot time above which the measurement is
#: flagged degraded (generous: the figure is milliseconds, so absolute
#: jitter is a large relative band).
SNAPSHOT_DEGRADED_FACTOR = 5.0

#: Archive scale (round 18, chain/segstore.py + chain/headerplane.py):
#: the 100k-block synthetic segmented archive probe
#: (benchmarks/archive_scale.py ``bench_quick`` — same code path as
#: the 10M acceptance run behind ``P1_BENCH_ARCHIVE=1``).
#: ``RECORDED_ARCHIVE_RESUME_BPS`` is the whole-archive packed-header
#: extraction rate (records/s through the per-segment scan — what a
#: header-plane rebuild or full PoW replay pays);
#: ``RECORDED_ARCHIVE_BOOT_RSS_MB`` is the peak RSS (VmHWM, fresh
#: process) of booting ``ArchiveChain`` and serving
#: header/balance/proof queries at 100k blocks.  The RSS figure is
#: dominated by the ACTIVE segment's hdrx rebuild (segment-bounded,
#: ~95 MB transient regardless of chain length) — the measured 10M
#: figure on this host was 166 MB, ~6x under the 1 GB acceptance
#: bar (docs/PERF.md "Archive scale" has the 100k/1M/10M ladder and
#: the two structures it took: a blocked bloom per segment so txid
#: negatives cost one 64-byte read, and pread — NOT mmap — probing,
#: because fault-around residented ~1 GB of neighbor pages at 10M).
#: Measured 2026-08-05 on the 1-vCPU bench host.
RECORDED_ARCHIVE_RESUME_BPS = 683_000
RECORDED_ARCHIVE_BOOT_RSS_MB = 170.0

#: Degraded thresholds: resume is CPU-bound (co-tenant-sensitive);
#: RSS is an allocator property and should barely move — flag at 2x.
ARCHIVE_RESUME_DEGRADED_FRACTION = 0.4
ARCHIVE_BOOT_RSS_DEGRADED_FACTOR = 2.0

#: Always-on maintenance plane (round 20, chain/snapshot.py
#: ``build_records_incremental`` + chain/chain.py ``rebase``): the
#: bench.py quick probe (benchmarks/maintenance_cadence.py
#: ``bench_quick`` — 20k accounts, 64 dirty per build, 96-block chain;
#: the 100k/1M acceptance ladder lives in docs/PERF.md "Maintenance
#: cadence").  ``RECORDED_SNAPSHOT_CADENCE_BPS`` is incremental
#: snapshot rebuilds/sec on the warm O(delta·log n) path — the
#: continuous-publication cadence a serving node can sustain (the
#: full O(accounts) rebuild it replaces measured 9.4/s on the same
#: shape, a ~56x spread the speedup field reports live).
#: ``RECORDED_REBASE_MS`` is the in-RAM half of `p1 maintain rebase`
#: — the event-loop stall the command costs a serving node (the
#: durable store half runs off-loop; archive bench territory).
#: Measured 2026-08-06 on the 1-vCPU bench host at idle.
RECORDED_SNAPSHOT_CADENCE_BPS = 523.0
RECORDED_REBASE_MS = 0.08

#: Degraded thresholds: the cadence is hash-bound (co-tenant
#: sensitive, same band as the other CPU rates); the rebase figure is
#: sub-100µs, so absolute jitter is a huge relative band — only a
#: 10x+ move says the dict-surgery cost model changed.
SNAPSHOT_CADENCE_DEGRADED_FRACTION = 0.4
REBASE_DEGRADED_FACTOR = 10.0

#: Wallet push plane (round 21, node/subscriptions.py): the bench.py
#: quick probe (benchmarks/wallet_plane.py ``bench_quick`` — 20k live
#: subscriptions, 8 measured block connects; the 100k acceptance run
#: is ``python benchmarks/wallet_plane.py --subs 100000`` and its row
#: lives in docs/PERF.md "Wallet push plane").
#: ``RECORDED_WALLET_SUBS`` is the live-subscription count the quick
#: probe holds while measuring; ``RECORDED_NOTIFY_P95_MS`` is the p95
#: per-block notify latency at that scale — decode the block's filter
#: ONCE, probe every session's watch set against the decoded value
#: set, share one pre-encoded frame across all non-matched sessions
#: (the O(filter + subs·items) shape that makes 100k sessions per
#: process feasible, vs the naive O(subs·filter-decode)).  Measured
#: 2026-08-07 on the 1-vCPU bench host.  LOWER is better for the p95
#: — ``bench.py`` emits
#: ``notify_vs_recorded`` = measured / recorded, flagged degraded
#: above the factor below.
RECORDED_WALLET_SUBS = 20_000
RECORDED_NOTIFY_P95_MS = 97.0

#: Factor over the recorded notify p95 above which the measurement is
#: flagged degraded (pure-Python hot loop on the shared box — wide
#: band, same rationale as the sim figures).
NOTIFY_DEGRADED_FACTOR = 3.0

#: Fleet provisioning (round 22, node/provision.py): the bench.py
#: quick probe (benchmarks/wallet_plane.py ``bench_fleet_quick`` — 3
#: replicas x 24 ReplicaSet-spread sessions on one store, the
#: most-loaded replica killed mid-push, plus one snapshot cold start).
#: ``RECORDED_FLEET_COLD_START_S`` is decide-to-serving-ready wall
#: seconds for ``p1 serve --bootstrap`` against a loopback node with a
#: snapshot 12 blocks below tip — headers skeleton + verified snapshot
#: chunks + filter-header cross-check + body fill; the cost is bounded
#: by blocks ABOVE the snapshot base, not chain length, which is the
#: whole point.  ``RECORDED_FLEET_NOTIFY_P95_MS`` is the per-event
#: notify p95 across every session and every block of the
#: kill-one-replica run — it includes the failover window (cursor
#: replay over a fresh replica), so it sits above the single-node p95
#: but must stay the same order of magnitude.  Measured 2026-08-07 on
#: the 1-vCPU bench host; LOWER is better for both.  ``bench.py``
#: emits ``fleet_cold_start_vs_recorded`` and
#: ``fleet_notify_vs_recorded`` = measured / recorded, flagged
#: degraded above the factor below; ``fleet_missed`` must be 0
#: unconditionally (a missed confirmation is a correctness bug, not a
#: perf regression).
RECORDED_FLEET_COLD_START_S = 0.03
RECORDED_FLEET_NOTIFY_P95_MS = 25.0

#: Factor over the recorded fleet figures above which the measurement
#: is flagged degraded.  Wider than the single-node notify band: the
#: cold start is dominated by process-local fsync+mmap at this scale
#: and the fleet p95 rides three event loops on one box.
FLEET_DEGRADED_FACTOR = 5.0

#: Relay bandwidth budget (round 23, node/reconcile.py + the RECONCILE
#: wire exchange): the bench.py quick probe
#: (benchmarks/netsim_scale.py ``bench_relay`` — 10-node shaped mesh,
#: 64 kbps per-host uplinks, 4 senders x 24 txs over 10 virtual
#: seconds, flood arm vs reconciliation arm over the SAME storm).
#: ``RECORDED_RELAY_BYTES_PER_TX`` is the recon arm's tx-plane bytes
#: (TX + REQRECON/SKETCH/RECONCILDIFF/GETTX families) per delivered
#: tx-node pair; ``RECORDED_TX_PROP_P95_MS`` is the recon arm's
#: submit-to-everywhere p95 in VIRTUAL ms.  Both figures are
#: deterministic functions of the seed (virtual time, seeded sim), so
#: drift means the PROTOCOL changed, not the host — the degraded band
#: below absorbs deliberate re-tuning inside a round, and a figure
#: outside it means re-measure and re-record with the change that
#: moved it.  Measured 2026-08-07 (quick probe: flood arm 13662
#: bytes/tx at p95 5351 ms — a 5.07x byte reduction at 2.9x better
#: p95; the full 16-node acceptance run measured 5.97x at 5.8x better
#: p95).  LOWER is better for both.  ``bench.py`` emits
#: ``relay_bytes_vs_recorded`` and ``tx_prop_vs_recorded`` = measured
#: / recorded.
RECORDED_RELAY_BYTES_PER_TX = 2697.1
RECORDED_TX_PROP_P95_MS = 1868.7

#: Factor over the recorded relay figures above which the measurement
#: is flagged degraded.  Tighter than the wall-clock bands — the probe
#: is virtual-time deterministic, so anything past 1.5x is a real
#: protocol regression (duplicate serves, capacity under-estimates,
#: stall-demotion floods), not host noise.
RELAY_DEGRADED_FACTOR = 1.5
