"""Pure-Python SHA-256: the framework's ground-truth implementation.

Three jobs:

1. ``sha256``/``sha256d`` — convenience digests (cross-checked against
   ``hashlib`` in tests; used for txids/merkle where speed is irrelevant).
2. ``compress`` — the raw compression function, exposed so the miner can
   compute the **midstate**: with an 80-byte header only the second 64-byte
   chunk depends on the nonce, so the first chunk is compressed once on the
   host and the resulting 8-word state shipped to the device
   (the classic miner optimization; see p1_tpu/hashx/jax_backend.py).
3. The round constants / IV shared by every backend.

Implements FIPS 180-4.  All word arithmetic is mod 2**32.
"""

from __future__ import annotations

import struct

MASK32 = 0xFFFFFFFF

# fmt: off
K = (
    0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
    0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
    0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
    0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
    0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
    0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
    0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
    0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
    0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
    0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
    0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
)
# fmt: on

IV = (
    0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
    0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
)  # fmt: skip


def _rotr(x: int, n: int) -> int:
    return ((x >> n) | (x << (32 - n))) & MASK32


def compress(state: tuple[int, ...], chunk: bytes) -> tuple[int, ...]:
    """One SHA-256 compression: 64-byte chunk folded into an 8-word state."""
    if len(chunk) != 64:
        raise ValueError("chunk must be 64 bytes")
    w = list(struct.unpack(">16I", chunk))
    for i in range(16, 64):
        s0 = _rotr(w[i - 15], 7) ^ _rotr(w[i - 15], 18) ^ (w[i - 15] >> 3)
        s1 = _rotr(w[i - 2], 17) ^ _rotr(w[i - 2], 19) ^ (w[i - 2] >> 10)
        w.append((w[i - 16] + s0 + w[i - 7] + s1) & MASK32)

    a, b, c, d, e, f, g, h = state
    for i in range(64):
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = (h + s1 + ch + K[i] + w[i]) & MASK32
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = (s0 + maj) & MASK32
        a, b, c, d, e, f, g, h = (t1 + t2) & MASK32, a, b, c, (d + t1) & MASK32, e, f, g
    return tuple((x + y) & MASK32 for x, y in zip(state, (a, b, c, d, e, f, g, h)))


def padding(message_len: int) -> bytes:
    """FIPS 180-4 padding for a message of ``message_len`` bytes."""
    pad = b"\x80" + b"\x00" * ((55 - message_len) % 64)
    return pad + struct.pack(">Q", message_len * 8)


def sha256(data: bytes) -> bytes:
    padded = data + padding(len(data))
    state = IV
    for off in range(0, len(padded), 64):
        state = compress(state, padded[off : off + 64])
    return struct.pack(">8I", *state)


def sha256d(data: bytes) -> bytes:
    return sha256(sha256(data))


def header_midstate(header_prefix: bytes) -> tuple[int, ...]:
    """Compress the nonce-independent first chunk of an 80-byte header.

    ``header_prefix`` is the first 76 bytes (everything but the nonce); only
    its first 64 bytes enter the midstate.  Returns the 8-word state from
    which the device continues with chunk 2 (bytes 64..80 + padding).
    """
    if len(header_prefix) < 64:
        raise ValueError("header prefix must be at least 64 bytes")
    return compress(IV, header_prefix[:64])


def header_tail_words(header_prefix: bytes) -> tuple[int, int, int]:
    """Words 0..2 of the second chunk (bytes 64..76); word 3 is the nonce."""
    if len(header_prefix) != 76:
        raise ValueError("header prefix must be exactly 76 bytes")
    return struct.unpack(">3I", header_prefix[64:76])
