"""SHA-256d nonce search as pure JAX uint32 math.

The device-side heart of the framework (BASELINE.json:5 — the miner's inner
loop "becomes a vmapped Pallas SHA-256 kernel that evaluates millions of
candidate nonces per device step").  This module is the XLA formulation: one
uint32 lane per candidate nonce, all 64 rounds unrolled at trace time into
straight-line vector ops that XLA tiles onto the TPU VPU (8x128 vregs).  The
Pallas kernel (pallas_backend.py) reuses exactly this math inside a kernel
body; on CPU the same functions run under the virtual-device test mesh.

Design choices for TPU:

- **Midstate**: the first 64 header bytes are nonce-independent, so the host
  compresses chunk 1 once (sha256_ref.header_midstate) and the device only
  runs chunk 2 + the full second pass — 2 compressions instead of 3.
- **Static shapes**: the batch size is a trace-time constant; the host loop
  re-invokes the jitted step with a new ``nonce_base`` scalar, so nothing
  recompiles between steps.
- **First-hit reduce**: each step returns ``min(lane index where hit, else
  batch)`` — a single uint32 — keeping device->host traffic at 4 bytes per
  step and making the multi-chip ``pmin`` reduction trivial.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp

from p1_tpu.hashx.sha256_ref import IV, K

_U32 = jnp.uint32


def _rotr(x: jax.Array, n: int) -> jax.Array:
    return (x >> _U32(n)) | (x << _U32(32 - n))


def _extend_schedule(w: list[jax.Array]) -> list[jax.Array]:
    """Message-schedule expansion W16..W63 (in-place append, trace-time loop)."""
    for i in range(16, 64):
        s0 = _rotr(w[i - 15], 7) ^ _rotr(w[i - 15], 18) ^ (w[i - 15] >> _U32(3))
        s1 = _rotr(w[i - 2], 17) ^ _rotr(w[i - 2], 19) ^ (w[i - 2] >> _U32(10))
        w.append(w[i - 16] + s0 + w[i - 7] + s1)
    return w


def _compress(state: Sequence[jax.Array], w: list[jax.Array]) -> list[jax.Array]:
    """64 SHA-256 rounds, unrolled; returns state + compressed."""
    a, b, c, d, e, f, g, h = state
    for i in range(64):
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + _U32(K[i]) + w[i]
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        a, b, c, d, e, f, g, h = t1 + t2, a, b, c, d + t1, e, f, g
    return [x + y for x, y in zip(state, (a, b, c, d, e, f, g, h))]


def sha256d_words(
    midstate: jax.Array, tail: jax.Array, nonces: jax.Array
) -> list[jax.Array]:
    """SHA-256d digest words for a lane-vector of nonces.

    midstate: (8,) uint32 chunk-1 state; tail: (3,) uint32 chunk-2 words 0..2;
    nonces: (...,) uint32.  Returns 8 arrays shaped like ``nonces``.
    """
    zero = jnp.zeros_like(nonces)

    def bc(word: jax.Array) -> jax.Array:
        return jnp.broadcast_to(word.astype(_U32), nonces.shape)

    # Pass 1, chunk 2: 16 tail bytes + nonce word + pad(0x80) + bitlen 640.
    w = [bc(tail[0]), bc(tail[1]), bc(tail[2]), nonces]
    w += [zero + _U32(0x80000000)] + [zero] * 10 + [zero + _U32(640)]
    state1 = _compress([bc(m) for m in midstate], _extend_schedule(w))

    # Pass 2: the 32-byte digest as one padded block (bitlen 256).
    w2 = list(state1) + [zero + _U32(0x80000000)] + [zero] * 6 + [zero + _U32(256)]
    iv = [jnp.full(nonces.shape, v, dtype=_U32) for v in IV]
    return _compress(iv, _extend_schedule(w2))


def below_target(digest_words: list[jax.Array], target_words: jax.Array) -> jax.Array:
    """Lanes whose 256-bit big-endian digest is < the 8-word target."""
    lt = jnp.zeros(digest_words[0].shape, dtype=jnp.bool_)
    eq = jnp.ones(digest_words[0].shape, dtype=jnp.bool_)
    for i in range(8):
        tw = target_words[i]
        lt = lt | (eq & (digest_words[i] < tw))
        eq = eq & (digest_words[i] == tw)
    return lt


def first_hit_index(hits: jax.Array, batch: int) -> jax.Array:
    """min(flat lane index where hit) or ``batch`` if no lane hit (uint32)."""
    lanes = jnp.arange(batch, dtype=_U32).reshape(hits.shape)
    return jnp.min(jnp.where(hits, lanes, _U32(batch)))


def search_step(
    midstate: jax.Array,
    tail: jax.Array,
    target_words: jax.Array,
    nonce_base: jax.Array,
    batch: int,
) -> jax.Array:
    """One device step: scan [nonce_base, nonce_base+batch) lanes, return
    the first hit's offset from nonce_base, or ``batch`` if none."""
    nonces = nonce_base + jnp.arange(batch, dtype=_U32)
    hits = below_target(sha256d_words(midstate, tail, nonces), target_words)
    return first_hit_index(hits, batch)


@functools.cache
def jit_search_step(batch: int, platform: str | None = None):
    """Jitted ``search_step`` closed over a static batch size."""
    fn = functools.partial(search_step, batch=batch)
    device = jax.devices(platform)[0] if platform else None
    return jax.jit(fn, device=device)
