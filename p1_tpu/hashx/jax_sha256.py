"""SHA-256d nonce search as pure JAX uint32 math.

The device-side heart of the framework (BASELINE.json:5 — the miner's inner
loop "becomes a vmapped Pallas SHA-256 kernel that evaluates millions of
candidate nonces per device step").  This module is the XLA formulation: one
uint32 lane per candidate nonce, vector ops that XLA tiles onto the TPU VPU
(8x128 vregs).  The Pallas kernel (pallas_backend.py) reuses exactly this
round math inside a kernel body; on CPU the same functions run under the
virtual-device test mesh.

Design choices for TPU:

- **Midstate**: the first 64 header bytes are nonce-independent, so the host
  compresses chunk 1 once (sha256_ref.header_midstate) and the device only
  runs chunk 2 + the full second pass — 2 compressions instead of 3.
- **Rolled rounds with an unroll knob**: the 64 SHA-256 rounds (with the
  message-schedule extension fused in) run under ``lax.fori_loop`` carrying a
  16-word rolling window — the whole double hash traces as ~2 round bodies
  instead of 2x(48+64) unrolled steps, so XLA:CPU compiles in seconds rather
  than tens of minutes (a 1-vCPU box never finished the unrolled trace).
  ``unroll=`` re-expands the loop body for TPU throughput; with the window
  carried as 16 separate arrays the rotation is pure re-binding, so an
  unrolled body has static register assignments and no roll/concat traffic.
- **Static shapes**: the batch size is a trace-time constant; the host loop
  re-invokes the jitted step with a new ``nonce_base`` scalar, so nothing
  recompiles between steps.
- **First-hit reduce**: each step returns ``min(flat lane index where hit,
  else batch)`` — a single uint32 — keeping device->host traffic at 4 bytes
  per step and making the multi-chip ``pmin`` reduction trivial.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from p1_tpu.hashx.sha256_ref import IV, K

_U32 = jnp.uint32

#: fori_loop unroll factor by platform.  CPU wants a tiny trace (compile
#: time dominates on the 1-vCPU test box); TPU amortizes one compile over
#: the whole mining session, so re-expanding the round body buys VPU
#: throughput back.  16 rounds per body keeps the trace ~8x smaller than
#: full unroll while giving XLA long straight-line stretches to fuse.
_PLATFORM_UNROLL = {"cpu": 1, "tpu": 16, "axon": 16}


def default_unroll(platform: str | None = None) -> int:
    p = platform or jax.default_backend()
    return _PLATFORM_UNROLL.get(p, 8)


def _rotr(x: jax.Array, n: int) -> jax.Array:
    return (x >> _U32(n)) | (x << _U32(32 - n))


# A host-side constant (NOT a jnp array: creating one inside a trace and
# caching it would leak a tracer); jnp indexes it as an implicit constant.
_K_NP = np.asarray(K, dtype=np.uint32)


def _compress(
    state: tuple[jax.Array, ...],
    w16: tuple[jax.Array, ...],
    unroll: int,
    ks=None,
) -> tuple[jax.Array, ...]:
    """One SHA-256 compression over a 16-word chunk, rounds+extension fused.

    The carry holds the sliding window ``w[i..i+15]`` as 16 separate arrays;
    round ``i`` consumes ``w[i]`` (= window[0]) and appends
    ``w[i+16] = w[i] + σ0(w[i+1]) + w[i+9] + σ1(w[i+14])`` — so rounds
    16..63 see exactly the words the schedule extension would have produced,
    without ever materializing a (64, batch) array in HBM.  The 16 extension
    steps computed for rounds 48..63 feed nothing; that waste is ~12% of the
    σ work and buys a single uniform round body.
    """
    if ks is None:
        ks = jnp.asarray(_K_NP)
    # ``ks`` may also be a Pallas SMEM ref of the K table: inside a kernel
    # a captured jnp constant is disallowed, so the kernel passes the table
    # in as a scalar-memory input and round ``i`` reads ``ks[i]``.

    def body(i, carry):
        w, s = carry
        a, b, c, d, e, f, g, h = s
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + ks[i] + w[0]
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        sig0 = _rotr(w[1], 7) ^ _rotr(w[1], 18) ^ (w[1] >> _U32(3))
        sig1 = _rotr(w[14], 17) ^ _rotr(w[14], 19) ^ (w[14] >> _U32(10))
        w_next = w[0] + sig0 + w[9] + sig1
        return (
            w[1:] + (w_next,),
            (t1 + s0 + maj, a, b, c, d + t1, e, f, g),
        )

    _, out = lax.fori_loop(0, 64, body, (w16, tuple(state)), unroll=unroll)
    return tuple(x + y for x, y in zip(state, out))


def sha256d_words(
    midstate: jax.Array,
    tail: jax.Array,
    nonces: jax.Array,
    unroll: int | None = None,
) -> list[jax.Array]:
    """SHA-256d digest words for a lane-vector of nonces.

    midstate: (8,) uint32 chunk-1 state; tail: (3,) uint32 chunk-2 words 0..2;
    nonces: (...,) uint32.  Returns 8 arrays shaped like ``nonces``.
    """
    if unroll is None:
        unroll = default_unroll()
    zero = jnp.zeros_like(nonces)

    def bc(word: jax.Array) -> jax.Array:
        return jnp.broadcast_to(word.astype(_U32), nonces.shape)

    # Pass 1, chunk 2: 16 tail bytes + nonce word + pad(0x80) + bitlen 640.
    w = (bc(tail[0]), bc(tail[1]), bc(tail[2]), nonces)
    w += (zero + _U32(0x80000000),) + (zero,) * 10 + (zero + _U32(640),)
    state1 = _compress(tuple(bc(m) for m in midstate), w, unroll)

    # Pass 2: the 32-byte digest as one padded block (bitlen 256).
    w2 = state1 + (zero + _U32(0x80000000),) + (zero,) * 6 + (zero + _U32(256),)
    # Derive the IV lanes from ``zero`` (not jnp.full) so they inherit the
    # nonces' varying-manual-axes under shard_map and the fori_loop carry
    # types line up on multi-chip meshes.
    iv = tuple(zero + _U32(v) for v in IV)
    return list(_compress(iv, w2, unroll))


def sha256d_headers(
    header_words: jax.Array, unroll: int | None = None
) -> list[jax.Array]:
    """SHA-256d digests for a batch of full 80-byte headers.

    Unlike the nonce search (fixed prefix, varying nonce), every header
    differs in all 20 words, so all 3 compressions run on device: chunk 1
    (words 0..15), chunk 2 (words 16..19 + padding, bitlen 640), then the
    second pass over the 32-byte digest.  This is the chain-replay hot loop
    (BASELINE.json:9 — "verify 10k-block header chain, hash-only") as one
    batched device computation: ``header_words`` is (N, 20) uint32, returns
    8 arrays of shape (N,).
    """
    if unroll is None:
        unroll = default_unroll()
    n = header_words.shape[0]
    zero = jnp.zeros((n,), dtype=_U32)

    w1 = tuple(header_words[:, i] for i in range(16))
    iv = tuple(zero + _U32(v) for v in IV)
    state1 = _compress(iv, w1, unroll)

    w2 = tuple(header_words[:, i] for i in range(16, 20))
    w2 += (zero + _U32(0x80000000),) + (zero,) * 10 + (zero + _U32(640),)
    state2 = _compress(state1, w2, unroll)

    w3 = state2 + (zero + _U32(0x80000000),) + (zero,) * 6 + (zero + _U32(256),)
    return list(_compress(iv, w3, unroll))


def _verify_segment(
    header_words: jax.Array,
    target_words: jax.Array,
    prev_digest: jax.Array,
    genesis_first: jax.Array,
    difficulty: jax.Array,
    unroll: int | None,
) -> tuple[jax.Array, jax.Array]:
    """(first-invalid index or N, last header's digest words (8,))."""
    digests = sha256d_headers(header_words, unroll)
    n = header_words.shape[0]
    pow_ok = below_target(digests, target_words)
    pow_ok = pow_ok.at[0].set(pow_ok[0] | genesis_first)
    # The difficulty field itself is consensus data: a header claiming a
    # different difficulty than the chain's must be flagged even if its
    # hash happens to meet the real target (word 18 = difficulty, see
    # p1_tpu/core/header.py layout).
    pow_ok = pow_ok & (header_words[:, 18] == difficulty)

    link_ok = jnp.ones((n,), dtype=jnp.bool_)
    for w in range(8):
        claimed = header_words[:, 1 + w]
        actual = jnp.concatenate(
            [prev_digest[w][None], digests[w][:-1]]
        )
        link_ok = link_ok & (claimed == actual)

    ok = pow_ok & link_ok
    idx = jnp.arange(n, dtype=_U32)
    first_bad = jnp.min(jnp.where(ok, _U32(n), idx))
    last_digest = jnp.stack([d[-1] for d in digests])
    return first_bad, last_digest


def verify_header_chain(
    header_words: jax.Array,
    target_words: jax.Array,
    prev_digest: jax.Array,
    genesis_first: jax.Array,
    difficulty: jax.Array,
    unroll: int | None = None,
) -> jax.Array:
    """Index of the first invalid header in a linked batch, or N if all pass.

    ``header_words``: (N, 20) uint32 — consecutive headers of one chain
    segment.  A header is valid iff its declared difficulty field (word 18)
    equals ``difficulty``, its SHA-256d meets ``target_words`` AND its
    prev-hash field (words 1..8) equals the previous header's digest.
    ``prev_digest``: (8,) digest of the header before the segment (for i=0).
    ``genesis_first``: scalar bool — when true, header 0 is a genesis block:
    linkage (zero prev-hash) is still enforced via ``prev_digest`` but the
    PoW check is waived (genesis anchors by identity, not work).
    """
    idx, _ = _verify_segment(
        header_words, target_words, prev_digest, genesis_first, difficulty, unroll
    )
    return idx


def verify_header_chain_segments(
    words3: jax.Array,
    target_words: jax.Array,
    difficulty: jax.Array,
    unroll: int | None = None,
) -> jax.Array:
    """Whole-chain verification as ONE device program: ``lax.scan`` over
    (S, segment, 20) header words, carrying the cross-segment digest on
    device.  Returns (S,) local first-invalid indices (= segment when the
    segment is clean).

    This exists because per-segment host round-trips dominate replay through
    the axon relay (~125 ms per dispatch, docs/PERF.md): the scan costs one
    dispatch and one bulk transfer for the entire chain, with no host
    re-hashing between segments.  Header 0 of segment 0 is treated as
    genesis (PoW waived, zero prev-hash enforced).
    """
    s = words3.shape[0]
    first_flags = jnp.arange(s) == 0

    def body(prev_digest, inp):
        seg_words, is_first = inp
        idx, last_digest = _verify_segment(
            seg_words, target_words, prev_digest, is_first, difficulty, unroll
        )
        return last_digest, idx

    _, idxs = lax.scan(
        body, jnp.zeros((8,), _U32), (words3, first_flags)
    )
    return idxs


@functools.cache
def jit_verify_chain_scan(
    n_segments: int,
    segment: int,
    platform: str | None = None,
    unroll: int | None = None,
):
    """Jitted ``verify_header_chain_segments`` for an (S, segment) layout."""
    if unroll is None:
        unroll = default_unroll(platform)
    fn = functools.partial(verify_header_chain_segments, unroll=unroll)
    device = jax.devices(platform)[0] if platform else None
    return jax.jit(fn, device=device)


def below_target(digest_words: list[jax.Array], target_words: jax.Array) -> jax.Array:
    """Lanes whose 256-bit big-endian digest is < the 8-word target."""
    lt = jnp.zeros(digest_words[0].shape, dtype=jnp.bool_)
    eq = jnp.ones(digest_words[0].shape, dtype=jnp.bool_)
    for i in range(8):
        tw = target_words[i]
        lt = lt | (eq & (digest_words[i] < tw))
        eq = eq & (digest_words[i] == tw)
    return lt


def first_hit_index(hits: jax.Array, batch: int) -> jax.Array:
    """min(flat lane index where hit) or ``batch`` if no lane hit (uint32)."""
    lanes = jnp.arange(batch, dtype=_U32).reshape(hits.shape)
    return jnp.min(jnp.where(hits, lanes, _U32(batch)))


def search_step(
    midstate: jax.Array,
    tail: jax.Array,
    target_words: jax.Array,
    nonce_base: jax.Array,
    batch: int,
    unroll: int | None = None,
) -> jax.Array:
    """One device step: scan [nonce_base, nonce_base+batch) lanes, return
    the first hit's offset from nonce_base, or ``batch`` if none."""
    nonces = nonce_base + jnp.arange(batch, dtype=_U32)
    hits = below_target(sha256d_words(midstate, tail, nonces, unroll), target_words)
    return first_hit_index(hits, batch)


@functools.cache
def jit_search_step(batch: int, platform: str | None = None, unroll: int | None = None):
    """Jitted ``search_step`` closed over a static batch size.

    ``unroll=None`` resolves per platform (see ``default_unroll``) before
    the trace is cut, so CPU tests get the second-scale compile and TPU
    keeps its throughput body.
    """
    if unroll is None:
        unroll = default_unroll(platform)
    fn = functools.partial(search_step, batch=batch, unroll=unroll)
    device = jax.devices(platform)[0] if platform else None
    return jax.jit(fn, device=device)
