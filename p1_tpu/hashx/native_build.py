"""Build the native SHA-256d core (p1_tpu/native/sha256d.cpp) on demand.

The .so is machine-local (it carries a runtime SHA-NI dispatch but is built
with the local toolchain), so it is compiled lazily into a content-addressed
cache — first `get_backend("native")` pays one g++ invocation, everything
after that is an mmap.  No setuptools, no pybind11: the C ABI + ctypes is
the whole binding layer (this environment ships no pybind11; the CPython
API would be overkill for four functions).
"""

from __future__ import annotations

import hashlib
import os
import pathlib
import subprocess

SOURCE = pathlib.Path(__file__).resolve().parent.parent / "native" / "sha256d.cpp"


class NativeBuildError(RuntimeError):
    """The native core could not be compiled (missing toolchain, bad env)."""


def cache_dir() -> pathlib.Path:
    root = os.environ.get("P1_NATIVE_CACHE")
    if root:
        return pathlib.Path(root)
    return pathlib.Path.home() / ".cache" / "p1_tpu"


def build_lib(force: bool = False) -> pathlib.Path:
    """Compile (if needed) and return the shared library path.

    Content-addressed by source hash: editing the .cpp invalidates the
    cache automatically; concurrent builders race benignly via an atomic
    rename of a per-pid temp file.
    """
    tag = hashlib.sha256(SOURCE.read_bytes()).hexdigest()[:16]
    out = cache_dir() / f"sha256d_{tag}.so"
    if out.exists() and not force:
        return out
    out.parent.mkdir(parents=True, exist_ok=True)
    cxx = os.environ.get("CXX", "g++")
    tmp = out.with_suffix(f".tmp.{os.getpid()}")
    cmd = [
        cxx,
        "-O3",
        "-std=c++17",
        "-fPIC",
        "-shared",
        "-fno-exceptions",
        str(SOURCE),
        "-o",
        str(tmp),
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        raise NativeBuildError(f"cannot run {cxx}: {e}") from e
    if proc.returncode != 0:
        raise NativeBuildError(
            f"native build failed ({' '.join(cmd)}):\n{proc.stderr[-2000:]}"
        )
    os.replace(tmp, out)
    return out
