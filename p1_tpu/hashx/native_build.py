"""Build the native crypto core (p1_tpu/native/*.cpp) on demand.

One shared object carries both native engines — the SHA-256d miner/
verifier core (sha256d.cpp, runtime SHA-NI dispatch) and the Ed25519
batch verifier (ed25519.cpp, portable __int128 radix-51 field
arithmetic).  The .so is machine-local (local toolchain), so it is
compiled lazily into a content-addressed cache — the first consumer
(`get_backend("native")` or the first signature-backend resolution in
core/keys.py) pays one g++ invocation, everything after that is an
mmap.  No setuptools, no pybind11: the C ABI + ctypes is the whole
binding layer (this environment ships no pybind11; the CPython API
would be overkill for a dozen functions).
"""

from __future__ import annotations

import hashlib
import os
import pathlib
import subprocess

_NATIVE_DIR = pathlib.Path(__file__).resolve().parent.parent / "native"
SOURCES = (
    _NATIVE_DIR / "sha256d.cpp",
    _NATIVE_DIR / "ed25519.cpp",
)
#: Kept for older callers/tests that referenced the single-source name.
SOURCE = SOURCES[0]


class NativeBuildError(RuntimeError):
    """The native core could not be compiled (missing toolchain, bad env)."""


def cache_dir() -> pathlib.Path:
    root = os.environ.get("P1_NATIVE_CACHE")
    if root:
        return pathlib.Path(root)
    return pathlib.Path.home() / ".cache" / "p1_tpu"


def build_lib(force: bool = False) -> pathlib.Path:
    """Compile (if needed) and return the shared library path.

    Content-addressed by the hash of every source: editing any .cpp
    invalidates the cache automatically; concurrent builders race
    benignly via an atomic rename of a per-pid temp file.
    """
    h = hashlib.sha256()
    for src in SOURCES:
        h.update(src.read_bytes())
    tag = h.hexdigest()[:16]
    out = cache_dir() / f"p1native_{tag}.so"
    if out.exists() and not force:
        return out
    out.parent.mkdir(parents=True, exist_ok=True)
    cxx = os.environ.get("CXX", "g++")
    tmp = out.with_suffix(f".tmp.{os.getpid()}")
    cmd = [
        cxx,
        "-O3",
        "-std=c++17",
        "-fPIC",
        "-shared",
        "-fno-exceptions",
        *[str(src) for src in SOURCES],
        "-o",
        str(tmp),
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        raise NativeBuildError(f"cannot run {cxx}: {e}") from e
    if proc.returncode != 0:
        raise NativeBuildError(
            f"native build failed ({' '.join(cmd)}):\n{proc.stderr[-2000:]}"
        )
    os.replace(tmp, out)
    return out
