"""Device-sharded Ed25519 batch verification: fe25519 in JAX limbs.

ROADMAP item 1, route (b): the TPU as a *validation* accelerator, not
just a miner.  This module evaluates the same subgroup-gated batch
equation as ``core/_ed25519.py::verify_batch`` — exact prime-subgroup
gates ([q]·P == identity) on every point plus one random-linear-
combination multi-scalar multiplication — as vectorized field
arithmetic over a device mesh:

- **fe25519 limbs**: field elements are 20 × 13-bit limbs in uint32
  (``FE_LIMBS``/``LIMB_BITS``).  13 bits is the TPU-honest radix: a
  limb product fits 26 bits and a 20-term column sum stays under 2³¹,
  so the whole pipeline runs in native int32/uint32 vector lanes — no
  64-bit integers, which TPUs do not carry.  ``fe_add``/``fe_sub``/
  ``fe_mul``/``fe_sq`` keep a limbs-≤-``LIMB_TOL`` invariant via
  parallel carry passes (carries ripple at most a few limbs per pass;
  three passes settle any product).
- **Point arithmetic**: the extended-coordinate add/double of
  ``core/_ed25519.py`` translated limb-wise and batched over a leading
  axis, so one `lax.scan` step advances EVERY point in the window.
- **Subgroup gate**: all points share the scalar q, so the gate is a
  scan over q's 64 fixed 4-bit windows — per step four batched doubles
  plus one table add (per-point 16-entry tables, the windowed form of
  ``_in_prime_subgroup``).
- **MSM**: windowed Pippenger in its SIMD shape — the per-point
  16-entry table IS the bucket set, indexed by each scalar's digit;
  per window one gather + one tree-reduction of batched point adds +
  four doubles of the accumulator (Horner over windows).  Work is
  ~(bits/4)·(N + N) point-additions for the whole batch, against
  ~770·N for serial ladders.
- **Sharding**: `shard_map` over the 1-D chip mesh
  (``hashx.sharded.make_mesh`` — the same seam the miner uses,
  SNIPPETS.md [1]/[3]): the point/scalar arrays split along the batch
  axis, every chip gates its shard and folds its partial MSM sum, and
  D partial points come back for a host-side combine (point addition
  is the reduction, so the cross-chip fold is D−1 cheap host adds, not
  a ``psum``).

Division of labor with the host (mirrors ``core/_ed25519_native.py``):
decompression (two ~255-bit field exponentiations — CPython's ``pow``
is C-speed), SHA-512 challenges, mod-q scalar products, and the random
coefficients stay on the host; the device does the O(bits·N) point
arithmetic, which is all the pure-Python path is slow at.

Semantics are the fallback batch's exactly — ``verify_batch_device``
accepts iff ``_ed25519.verify_batch`` would (2⁻¹²⁸ coefficients aside),
pinned by the torsion/corruption matrix in tests/test_ed25519_device.py
— so ``core/keys.py`` can route batches here (``--sig-backend device``)
with ``first_invalid``'s serial settlement unchanged.

Honest scope note (docs/ROUND15.md): on a single CPU host the mesh is
virtual and this path measures architecture cost, not speedup — the
native C++ engine is the host fast lane.  The figure that matters here
is the devices-vs-throughput scaling row in docs/PERF.md.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from p1_tpu.core import _ed25519 as _py
from p1_tpu.hashx.sharded import AXIS, _SHARD_MAP_KW, _shard_map, make_mesh

_U32 = jnp.uint32

#: Field-element shape: 20 limbs × 13 bits = 260 ≥ 255 bits.
FE_LIMBS = 20
LIMB_BITS = 13
LIMB_MASK = (1 << LIMB_BITS) - 1
#: 2^260 ≡ 19·2^5 (mod p): the fold factor for limb-19 overflow.
FOLD = 19 << (FE_LIMBS * LIMB_BITS - 255)
#: Carried limbs stay ≤ this (LIMB_MASK + a bounded fold residue); the
#: fe_mul column bound 20·LIMB_TOL² + fold terms < 2³¹ is what makes
#: uint32 accumulation safe.
LIMB_TOL = LIMB_MASK + 641

_SCALAR_WINDOWS = 64  # 256-bit scalars in 4-bit windows


def fe_from_int(x: int) -> np.ndarray:
    return np.array(
        [(x >> (LIMB_BITS * i)) & LIMB_MASK for i in range(FE_LIMBS)],
        dtype=np.uint32,
    )


def fe_to_int(limbs) -> int:
    total = 0
    for i, limb in enumerate(np.asarray(limbs, dtype=np.uint64)):
        total += int(limb) << (LIMB_BITS * i)
    return total % _py._P


def _carry_pass(x):
    """One parallel carry pass: every limb sheds its overflow to its
    neighbor (limb 19's overflow folds to limb 0 ×FOLD).  Carries can
    re-overflow a limb by a bounded amount; three passes settle any
    fe_mul column vector (bounds audited in the module docstring)."""
    c = x >> LIMB_BITS
    x = x & LIMB_MASK
    fold = c[..., FE_LIMBS - 1 :] * _U32(FOLD)
    return x + jnp.concatenate([fold, c[..., : FE_LIMBS - 1]], axis=-1)


def _carry(x, passes: int = 3):
    for _ in range(passes):
        x = _carry_pass(x)
    return x


def fe_add(a, b):
    return _carry(a + b, passes=2)


#: A multiple of p whose EVERY limb exceeds a carried operand limb
#: (≤ LIMB_TOL), so a−b+pad never underflows in uint32 while the
#: represented value shifts by 0 mod p.  Canonical p limbs will not do
#: (the top limb is only 255 — smaller than a reduced operand limb), so
#: the pad is 2·(p≪13) with the shifted-out top limb folded back via
#: 2^260 ≡ FOLD: limbs [2·255·FOLD, 2·p₀, 2·p₁, …, 2·p₁₈] ≥ 16346.
_P_LIMBS = [int(v) for v in fe_from_int(_py._P)]
_SUBPAD = tuple(2 * v for v in ([_P_LIMBS[-1] * FOLD] + _P_LIMBS[:-1]))
assert min(_SUBPAD) > LIMB_TOL


def fe_sub(a, b):
    pad = jnp.array(_SUBPAD, dtype=_U32)
    return _carry((a + pad) - b, passes=2)


def _fold_columns(cols):
    """39 convolution columns -> 20 limbs: high columns fold back by
    2^260 ≡ FOLD, split lo/hi so every product stays < 2³¹."""
    low = cols[..., :FE_LIMBS]
    high = cols[..., FE_LIMBS:]  # 19 columns
    hi_lo = high & LIMB_MASK
    hi_hi = high >> LIMB_BITS
    low = low.at[..., : FE_LIMBS - 1].add(hi_lo * _U32(FOLD))
    low = low.at[..., 1:FE_LIMBS].add(hi_hi * _U32(FOLD))
    # Four passes: product columns reach ~2^31, and the limb-0 fold can
    # re-inflate limb 0 to ~2^24 twice before the ripple dies out.
    return _carry(low, passes=4)


def fe_mul(a, b):
    """Schoolbook 20×20 limb product as a padded-shift convolution —
    20 batched multiplies + a tree sum, fully vectorized over the
    leading axes."""
    terms = []
    for i in range(FE_LIMBS):
        prod = a[..., i : i + 1] * b
        terms.append(
            jnp.pad(prod, [(0, 0)] * (prod.ndim - 1) + [(i, FE_LIMBS - 1 - i)])
        )
    return _fold_columns(sum(terms))


def fe_sq(a):
    """Square via the symmetric half: cross terms i<j counted once and
    doubled — ~half the multiplies of fe_mul."""
    terms = []
    for i in range(FE_LIMBS):
        diag = a[..., i : i + 1] * a[..., i : i + 1]
        terms.append(
            jnp.pad(
                diag,
                [(0, 0)] * (diag.ndim - 1)
                + [(2 * i, 2 * (FE_LIMBS - 1 - i))],
            )
        )
        if i + 1 < FE_LIMBS:
            cross = _U32(2) * a[..., i : i + 1] * a[..., i + 1 :]
            terms.append(
                jnp.pad(
                    cross,
                    [(0, 0)] * (cross.ndim - 1) + [(2 * i + 1, FE_LIMBS - 1 - i)],
                )
            )
    return _fold_columns(sum(terms))


#: Bits of the top limb below 2^255 (13·19 = 247 bits underneath).
_TOP_BITS = 255 - LIMB_BITS * (FE_LIMBS - 1)
_TOP_MASK = (1 << _TOP_BITS) - 1


def fe_canon(x):
    """Full reduction to the canonical representative (< p).

    The 260-bit limb capacity means a merely-carried value can still be
    ~32p (the top limb holds 13 bits where p uses 8), so: (1) settle
    the limbs exactly and fold the top limb's bits ≥ 2²⁵⁵ back as ×19 —
    twice, because the first fold can ripple — leaving the value < 2p;
    then (2) the +19 trick: x ≥ p iff x+19 crosses 2²⁵⁵, in which case
    adding 19 and dropping bit 255 IS the subtraction of p.  Sequential
    exact carries are fine here: canon runs on verdicts and final
    equalities, not inside the per-window arithmetic."""
    x = _carry(x, passes=4)
    for _ in range(2):
        limbs = [x[..., i] for i in range(FE_LIMBS)]
        c = jnp.zeros_like(limbs[0])
        for i in range(FE_LIMBS):
            t = limbs[i] + c
            limbs[i] = t & LIMB_MASK
            c = t >> LIMB_BITS
        limbs[0] = limbs[0] + c * _U32(FOLD)  # beyond-2^260 overflow
        hi = limbs[FE_LIMBS - 1] >> _TOP_BITS  # bits >= 2^255
        limbs[FE_LIMBS - 1] = limbs[FE_LIMBS - 1] & _TOP_MASK
        limbs[0] = limbs[0] + hi * _U32(19)
        x = jnp.stack(limbs, axis=-1)
    probe = x.at[..., 0].add(_U32(19))
    limbs = [probe[..., i] for i in range(FE_LIMBS)]
    c = jnp.zeros_like(limbs[0])
    for i in range(FE_LIMBS):
        t = limbs[i] + c
        limbs[i] = t & LIMB_MASK
        c = t >> LIMB_BITS
    q = (limbs[FE_LIMBS - 1] >> _TOP_BITS) & 1
    x = x.at[..., 0].add(_U32(19) * q)
    limbs = [x[..., i] for i in range(FE_LIMBS)]
    c = jnp.zeros_like(limbs[0])
    for i in range(FE_LIMBS):
        t = limbs[i] + c
        limbs[i] = t & LIMB_MASK
        c = t >> LIMB_BITS
    out = jnp.stack(limbs, axis=-1)
    return out.at[..., FE_LIMBS - 1].set(out[..., FE_LIMBS - 1] & _TOP_MASK)


def fe_eq(a, b):
    return jnp.all(fe_canon(a) == fe_canon(b), axis=-1)


def fe_is_zero(a):
    return jnp.all(fe_canon(a) == 0, axis=-1)


# ---------------------------------------------------------------- points --
# A batch of points is a (..., 4, FE_LIMBS) uint32 array — extended
# homogeneous (X, Y, Z, T), the exact formulas of core/_ed25519.py.

_D2 = tuple(int(v) for v in fe_from_int((2 * _py._D) % _py._P))


def ge_identity(shape=()):
    out = np.zeros(shape + (4, FE_LIMBS), dtype=np.uint32)
    out[..., 1, 0] = 1  # y = 1
    out[..., 2, 0] = 1  # z = 1
    return jnp.asarray(out)


def ge_add(p, q):
    px, py_, pz, pt = (p[..., i, :] for i in range(4))
    qx, qy, qz, qt = (q[..., i, :] for i in range(4))
    d2 = jnp.array(_D2, dtype=_U32)
    aa = fe_mul(fe_sub(py_, px), fe_sub(qy, qx))
    bb = fe_mul(fe_add(py_, px), fe_add(qy, qx))
    cc = fe_mul(fe_mul(pt, qt), d2)
    zz = fe_mul(pz, qz)
    dd = fe_add(zz, zz)
    e = fe_sub(bb, aa)
    f = fe_sub(dd, cc)
    g = fe_add(dd, cc)
    h = fe_add(bb, aa)
    return jnp.stack(
        [fe_mul(e, f), fe_mul(g, h), fe_mul(f, g), fe_mul(e, h)], axis=-2
    )


def ge_double(p):
    px, py_, pz, _ = (p[..., i, :] for i in range(4))
    aa = fe_sq(px)
    bb = fe_sq(py_)
    cc_ = fe_sq(pz)
    cc = fe_add(cc_, cc_)
    h = fe_add(aa, bb)
    e = fe_sub(h, fe_sq(fe_add(px, py_)))
    g = fe_sub(aa, bb)
    f = fe_add(cc, g)
    return jnp.stack(
        [fe_mul(e, f), fe_mul(g, h), fe_mul(f, g), fe_mul(e, h)], axis=-2
    )


def ge_is_identity(p):
    return fe_is_zero(p[..., 0, :]) & fe_eq(p[..., 1, :], p[..., 2, :])


def _point_table(points):
    """Per-point windowed table [0]P..[15]P: (16, N, 4, FE_LIMBS).
    Built with a scan (one ge_add body) rather than 14 unrolled adds —
    the unrolled form multiplied the traced graph by ~15× and XLA
    compile time on a small host with it."""

    def step(prev, _):
        nxt = ge_add(prev, points)
        return nxt, nxt

    _, rows = lax.scan(step, points, None, length=14)
    return jnp.concatenate(
        [ge_identity(points.shape[:-2])[None], points[None], rows], axis=0
    )


#: q in 4-bit windows, most significant first (shared gate scalar).
_Q_DIGITS = np.array(
    [(_py._Q >> (4 * i)) & 15 for i in reversed(range(_SCALAR_WINDOWS))],
    dtype=np.uint32,
)


def _gate_all(points):
    """[q]·P for every point in the batch — identity iff torsion-free.
    One scan over q's 64 windows; the per-step digit indexes every
    point's table at once (the digits are shared, so the lookup is a
    single dynamic slice, not a gather)."""
    table = _point_table(points)

    def step(acc, digit):
        for _ in range(4):
            acc = ge_double(acc)
        term = lax.dynamic_index_in_dim(table, digit, axis=0, keepdims=False)
        return ge_add(acc, term), ()

    acc0 = ge_identity(points.shape[:-2])
    acc, _ = lax.scan(step, acc0, jnp.asarray(_Q_DIGITS))
    return ge_is_identity(acc)


def _msm_tree(points, digit_rows):
    """Σ sᵢ·Pᵢ over the batch: windowed Pippenger in SIMD shape.

    ``digit_rows`` is (64, N) — each scalar's 4-bit windows, msb first.
    Per window: gather each point's bucket (its table row for its own
    digit), tree-reduce the batch to one point, Horner-accumulate.
    The batch size must be a power of two (callers pad with identity
    points and zero scalars, which add nothing)."""
    table = jnp.moveaxis(_point_table(points), 0, 1)  # (N, 16, 4, L)

    def step(acc, digits):
        for _ in range(4):
            acc = ge_double(acc)
        idx = digits.reshape(digits.shape + (1, 1, 1)).astype(jnp.int32)
        terms = jnp.take_along_axis(table, idx, axis=1)[:, 0]
        while terms.shape[0] > 1:
            half = terms.shape[0] // 2
            terms = ge_add(terms[:half], terms[half:])
        return ge_add(acc, terms[0]), ()

    acc, _ = lax.scan(step, ge_identity(), digit_rows)
    return acc


@functools.lru_cache(maxsize=8)
def _jit_gate_msm(mesh, per_device: int):
    """The fused device program: gate every point exactly, fold the
    shard's partial MSM — one `shard_map` over the chip mesh, arrays
    split on the batch axis.  Outputs stack per device: (D,) gate
    verdicts and (D, 4, L) partial sums the host combines (point
    addition is the cross-chip reduction, so it rides home as D tiny
    arrays rather than a collective)."""

    @jax.jit
    @functools.partial(
        _shard_map,
        mesh=mesh,
        in_specs=(P(AXIS), P(AXIS)),
        out_specs=(P(AXIS), P(AXIS)),
        **{_SHARD_MAP_KW: False},
        # check_vma off: the scan carries mix replicated constants
        # (q digits, curve constants) into varying shard data and the
        # varying-manual-axes checker wants per-op pcasts through the
        # whole fe pipeline — pure noise for an embarrassingly parallel
        # map with no collectives (the pallas miner body makes the same
        # call, hashx/sharded.py).
    )
    def program(points, digit_cols):
        ok = jnp.all(_gate_all(points))
        partial = _msm_tree(points, jnp.transpose(digit_cols))
        return ok[None], partial[None]

    del per_device  # part of the cache key: shapes bake into the jit
    return program


def _digits_of(scalar: int) -> np.ndarray:
    return np.array(
        [(scalar >> (4 * i)) & 15 for i in reversed(range(_SCALAR_WINDOWS))],
        dtype=np.uint32,
    )


def _encode_point(pt) -> np.ndarray:
    x, y, z, t = pt
    return np.stack(
        [fe_from_int(x), fe_from_int(y), fe_from_int(z), fe_from_int(t)]
    )


def _decode_point(arr):
    return tuple(fe_to_int(np.asarray(arr)[i]) for i in range(4))


class DeviceUnavailable(RuntimeError):
    """No usable mesh (jax missing devices) — callers degrade to host."""


@functools.lru_cache(maxsize=4)
def _default_mesh(n_devices: int | None = None):
    try:
        return make_mesh(n_devices)
    except Exception as exc:  # no devices / misconfigured platform
        raise DeviceUnavailable(str(exc)) from exc


def verify_batch_device(triples, mesh=None, n_devices: int | None = None) -> bool:
    """``_ed25519.verify_batch`` evaluated on the device mesh.

    Host side: parse + range-check, decompress (CPython pow is C-speed),
    draw the 128-bit coefficients, dedup pubkeys (one gate and ONE
    combined MSM term Σzᵢkᵢ·A per unique key — same point, scalars
    merge).  Device side: exact gates + partial MSMs per shard.  Host
    closes: D−1 partial adds, the base-point term, identity check.

    Accepts iff the fallback batch would (same gate, same combination,
    independent randomness) — False is NOT a serial verdict, exactly
    the ``verify_batch`` contract everywhere else.
    """
    import secrets

    triples = list(triples)
    if not triples:
        return True
    if mesh is None:
        mesh = _default_mesh(n_devices)
    points = []  # decompressed (x, y, z, t) int tuples
    scalars = []  # matching MSM coefficients
    a_slots: dict[bytes, int] = {}  # pubkey -> index into points
    s_total = 0
    for pubkey, sig, message in triples:
        if len(pubkey) != 32 or len(sig) != 64:
            return False
        s = int.from_bytes(sig[32:], "little")
        if s >= _py._Q:
            return False
        pubkey = bytes(pubkey)
        slot = a_slots.get(pubkey)
        if slot is None:
            a_pt = _py._pt_decompress(pubkey)
            if a_pt is None:
                return False
            slot = len(points)
            a_slots[pubkey] = slot
            points.append(a_pt)
            scalars.append(0)
        r_pt = _py._pt_decompress(sig[:32])
        if r_pt is None:
            return False
        k = int.from_bytes(_py._sha512(sig[:32] + pubkey + message), "little")
        k %= _py._Q
        z = secrets.randbits(128) | 1
        s_total = (s_total + z * s) % _py._Q
        # The mod-q merges are exact only because the device gate PROVES
        # every point has order q before the sum is trusted (the same
        # gate-first contract as every other backend).
        scalars[slot] = (scalars[slot] + z * k) % _py._Q
        points.append(r_pt)
        scalars.append(z)
    n_dev = mesh.devices.size
    per_device = max(1, -(-len(points) // n_dev))
    # power-of-two tiles keep the in-shard tree reduction exact
    per_device = 1 << (per_device - 1).bit_length()
    total = per_device * n_dev
    ident = (0, 1, 1, 0)
    while len(points) < total:
        points.append(ident)  # identity + zero scalar: contributes nothing
        scalars.append(0)
    pts = jnp.asarray(np.stack([_encode_point(p) for p in points]))
    digs = jnp.asarray(np.stack([_digits_of(s) for s in scalars]))
    program = _jit_gate_msm(mesh, per_device)
    # digits travel shard-major on axis 0 => pass as (N, 64) columns
    ok, partials = program(pts, digs)
    if not bool(jnp.all(ok)):
        return False
    acc = _py._IDENT
    for d in range(n_dev):
        acc = _py._pt_add(acc, _decode_point(partials[d]))
    if s_total:
        acc = _py._pt_add(acc, _py._pt_mul(_py._Q - s_total, _py._B))
    return _py._pt_equal(acc, _py._IDENT)
