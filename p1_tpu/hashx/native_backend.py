"""Native hash backend: the C++ SHA-256d core via ctypes.

Capability parity: the host-side mining tier done the way a native
reference would do it (SURVEY.md §2's rule — native components get C++
equivalents).  One C call scans a whole nonce range with the midstate
trick; on CPUs with the SHA-NI extension (runtime-dispatched inside the
.so) this measures ~10x the hashlib loop (docs/PERF.md) from hardware
rounds plus Python-overhead elimination.  Deterministic earliest-hit — same
contract as every
other backend, so it slots into the Miner/chain/node unchanged.
"""

from __future__ import annotations

import ctypes

from p1_tpu.hashx.backend import HashBackend, SearchResult, register
from p1_tpu.hashx.native_build import build_lib


def _load():
    lib = ctypes.CDLL(str(build_lib()))
    lib.p1_sha256d.argtypes = [
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.c_char_p,
    ]
    lib.p1_sha256d.restype = None
    lib.p1_search.argtypes = [
        ctypes.c_char_p,
        ctypes.c_uint32,
        ctypes.c_uint64,
        ctypes.c_uint32,
    ]
    lib.p1_search.restype = ctypes.c_longlong
    lib.p1_has_shani.argtypes = []
    lib.p1_has_shani.restype = ctypes.c_int
    lib.p1_force_scalar.argtypes = [ctypes.c_int]
    lib.p1_force_scalar.restype = None
    lib.p1_verify_chain.argtypes = [
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.c_uint32,
        ctypes.c_int,
    ]
    lib.p1_verify_chain.restype = ctypes.c_longlong
    lib.p1_verify_chain_retarget.argtypes = [
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.c_uint32,
        ctypes.c_uint32,
        ctypes.c_uint32,
        ctypes.c_uint32,
    ]
    lib.p1_verify_chain_retarget.restype = ctypes.c_longlong
    return lib


def verify_header_chain(
    raw: bytes, n: int, difficulty: int, genesis_exempt: bool = True
) -> int | None:
    """Native engine for chain replay (config 3): verify ``n`` contiguous
    80-byte headers in one C call — PoW, difficulty field, prev-hash
    linkage, exactly ``chain.replay.replay_host``'s rules.  Returns the
    first invalid index, or None when the whole chain is valid."""
    if len(raw) != 80 * n:
        raise ValueError(f"expected {80 * n} header bytes, got {len(raw)}")
    idx = _lib().p1_verify_chain(raw, n, difficulty, int(genesis_exempt))
    return None if idx < 0 else int(idx)


def verify_header_chain_retarget(raw: bytes, n: int, retarget) -> int | None:
    """Retargeting form of ``verify_header_chain``: the C engine
    recomputes the contextual difficulty schedule and enforces the
    timestamp rules (strict increase + forward cap, height-1 anchor
    exempt) — rule-for-rule ``replay_host(retarget=...)``.  The caller
    validates header 0 against the chain's genesis identity, exactly as
    the host path's callers do."""
    if len(raw) != 80 * n:
        raise ValueError(f"expected {80 * n} header bytes, got {len(raw)}")
    idx = _lib().p1_verify_chain_retarget(
        raw, n, retarget.window, retarget.spacing,
        retarget.max_adjust, retarget.max_step,
    )
    return None if idx < 0 else int(idx)


_LIB = None


def _lib():
    global _LIB
    if _LIB is None:
        _LIB = _load()
    return _LIB


@register("native")
class NativeBackend(HashBackend):
    """C++ SHA-256d search (SHA-NI when the CPU has it)."""

    def __init__(self):
        self._lib = _lib()
        self.has_shani = bool(self._lib.p1_has_shani())

    def force_scalar(self, enable: bool) -> None:
        """Test hook: pin the portable scalar compression on/off."""
        self._lib.p1_force_scalar(int(enable))
        self.has_shani = bool(self._lib.p1_has_shani())

    def sha256d(self, data: bytes) -> bytes:
        out = ctypes.create_string_buffer(32)
        self._lib.p1_sha256d(data, len(data), out)
        return out.raw

    def search(
        self, header_prefix: bytes, nonce_start: int, count: int, difficulty: int
    ) -> SearchResult:
        self._check_search_args(header_prefix, nonce_start, count, difficulty)
        hit = self._lib.p1_search(header_prefix, nonce_start, count, difficulty)
        if hit < 0:
            return SearchResult(None, count)
        return SearchResult(int(hit), int(hit) - nonce_start + 1)
