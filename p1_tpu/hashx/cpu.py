"""CPU hash backend: ``hashlib`` SHA-256d.

Capability parity: the reference's baseline "CPU backend" that the TPU
backend must beat by >=10x (BASELINE.json:5).  The search loop reuses a
pre-absorbed ``hashlib`` context for the 76-byte prefix (``copy()`` per
nonce), which is the fastest pure-stdlib formulation.
"""

from __future__ import annotations

import hashlib
import struct

from p1_tpu.core.header import target_from_difficulty
from p1_tpu.hashx.backend import HashBackend, SearchResult, register


@register("cpu")
class CpuBackend(HashBackend):
    def sha256d(self, data: bytes) -> bytes:
        return hashlib.sha256(hashlib.sha256(data).digest()).digest()

    def search(
        self, header_prefix: bytes, nonce_start: int, count: int, difficulty: int
    ) -> SearchResult:
        self._check_search_args(header_prefix, nonce_start, count, difficulty)
        target = target_from_difficulty(difficulty)
        base = hashlib.sha256(header_prefix)
        pack = struct.Struct(">I").pack
        outer = hashlib.sha256
        for nonce in range(nonce_start, nonce_start + count):
            h = base.copy()
            h.update(pack(nonce))
            digest = outer(h.digest()).digest()
            if int.from_bytes(digest, "big") < target:
                return SearchResult(nonce, nonce - nonce_start + 1)
        return SearchResult(None, count)
