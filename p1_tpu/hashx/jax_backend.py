"""JAX hash backend: the XLA-compiled nonce search (CPU or TPU).

Capability parity: the ``JaxTPUBackend`` registry entry of the north star
(BASELINE.json:5), in its pure-XLA form — the Pallas-kernel variant is the
``tpu`` backend (pallas_backend.py) and the multi-chip variant is the
``sharded`` backend (sharded.py), both of which reuse this module's
pipelined host loop.  ``search`` runs a host loop of jitted device steps
with **async double-buffering**: step k+1 is dispatched before step k's
4-byte result is read back, so the device never idles on the host (JAX's
async dispatch gives this for free as long as we delay ``int()``-ing a
result until the next step is enqueued).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from p1_tpu.core.header import target_from_difficulty, target_to_words
from p1_tpu.hashx.backend import HashBackend, SearchResult, register
from p1_tpu.hashx.jax_sha256 import jit_search_step
from p1_tpu.hashx.sha256_ref import header_midstate, header_tail_words, sha256d

_U32 = jnp.uint32

#: A step function: (midstate, tail, target, nonce_base) -> uint32 offset of
#: the earliest hit in [nonce_base, nonce_base + step_span), or step_span.
StepFn = Callable[..., jax.Array]

#: Default device-step batch by platform.  The TPU sweep peaked near 2**24
#: per step (106 MH/s pipelined; 2**26 gains little and slows aborts); on
#: CPU the fori_loop carry holds ~25 live uint32 arrays per lane, so 2**24
#: would mean a ~2 GB working set and minutes between abort checks on a
#: 1-vCPU box — 2**18 keeps both in the tens-of-MB / sub-second range.
_PLATFORM_BATCH = {"cpu": 1 << 18, "tpu": 1 << 24, "axon": 1 << 24}


def default_batch(platform: str | None = None) -> int:
    p = platform or jax.default_backend()
    return _PLATFORM_BATCH.get(p, 1 << 20)


def is_tpu_platform(platform: str | None = None) -> bool:
    """True when ``platform`` is a real TPU (directly or via the axon
    relay) — the single source of truth for compiled-Mosaic / kernel
    defaults, so the platform list can't drift between call sites."""
    p = platform or jax.default_backend()
    return p in ("tpu", "axon")


#: Opening-ramp parameters (see ``PipelinedSearchMixin.search``).  The floor
#: is sized so a difficulty-20 hit (expected at ~2²⁰ nonces) lands in the
#: first step with ~98% probability; through the axon relay one dispatch
#: costs ~125 ms regardless of span, so nothing is gained by starting lower.
_RAMP_FLOOR = 1 << 22
_RAMP_FACTOR = 8
#: Ramp only when a hit inside the floor span is plausible: at difficulty d
#: the expected hit is at 2^d nonces, so for d > 26 the opening steps almost
#: never hit and would only add dispatch latency to a long scan.
_RAMP_MAX_DIFFICULTY = 26


class PipelinedSearchMixin:
    """The host loop shared by every device-stepped backend.

    Subclasses provide ``step_span`` (nonces evaluated per full device step)
    and ``_make_step(span)`` (a jitted step function for a given span).
    ``search`` then scans an arbitrary range with a one-step pipeline and
    host-side masking of the partial final step.

    **Adaptive opening ramp**: a fresh scan (nonce_start == 0) at a
    difficulty where an early hit is plausible starts with a small step
    (``ramp_floor``) and grows geometrically to ``step_span``, so
    time-to-block is one dispatch latency instead of a full-batch step —
    at difficulty 20 a 2²⁷-batch backend would otherwise spend ~10× the
    expected search time on granularity alone.  Throughput scans
    (high difficulty, or resumed ranges) skip the ramp entirely.
    """

    step_span: int
    #: Smallest opening step; None disables the ramp (sharded backend: the
    #: per-device batch is baked into the mesh program).
    ramp_floor: int | None = _RAMP_FLOOR

    def _make_step(self, span: int) -> StepFn:
        raise NotImplementedError

    def sha256d(self, data: bytes) -> bytes:
        return sha256d(data)  # single digests stay on host

    def _search_arrays(self, header_prefix: bytes, difficulty: int):
        midstate = jnp.array(header_midstate(header_prefix), dtype=_U32)
        tail = jnp.array(header_tail_words(header_prefix), dtype=_U32)
        target = jnp.array(
            target_to_words(target_from_difficulty(difficulty)), dtype=_U32
        )
        return midstate, tail, target

    def search(
        self, header_prefix: bytes, nonce_start: int, count: int, difficulty: int
    ) -> SearchResult:
        self._check_search_args(header_prefix, nonce_start, count, difficulty)
        midstate, tail, target = self._search_arrays(header_prefix, difficulty)

        ramping = (
            self.ramp_floor is not None
            and nonce_start == 0
            and difficulty <= _RAMP_MAX_DIFFICULTY
            and self.step_span > self.ramp_floor
        )
        span = self.ramp_floor if ramping else self.step_span

        # Batched scan with a one-step pipeline.  Each step covers
        # [base, base+span); a partial final step is masked on the host
        # by re-checking the hit offset against the remaining count.
        pending: list[tuple[int, int, object]] = []  # (base, valid, device idx)
        done = 0
        result: SearchResult | None = None
        while done < count and result is None:
            base = nonce_start + done
            valid = min(span, count - done)
            idx = self._make_step(span)(midstate, tail, target, _U32(base))
            pending.append((base, valid, idx))
            done += valid
            span = min(span * _RAMP_FACTOR, self.step_span)
            if len(pending) > 1:
                result = self._drain_one(pending, nonce_start)
        while result is None and pending:
            result = self._drain_one(pending, nonce_start)
        if result is not None:
            return result
        return SearchResult(None, count)

    def _drain_one(self, pending: list, nonce_start: int) -> SearchResult | None:
        base, valid, idx = pending.pop(0)
        offset = int(np.asarray(idx))  # blocks until this step is done
        if offset < valid:
            nonce = base + offset
            return SearchResult(nonce, nonce - nonce_start + 1)
        return None


@register("jax")
class JaxBackend(PipelinedSearchMixin, HashBackend):
    """XLA-compiled SHA-256d search on a single JAX device."""

    def __init__(self, batch: int | None = None, platform: str | None = None):
        if batch is None:
            batch = default_batch(platform)
        if batch <= 0 or batch & (batch - 1):
            raise ValueError(f"batch must be a power of two, got {batch}")
        self.batch = batch
        self.step_span = batch
        self.platform = platform

    def _make_step(self, span: int) -> StepFn:
        return jit_search_step(span, self.platform)
