"""JAX hash backend: the XLA-compiled nonce search (CPU or TPU).

Capability parity: the ``JaxTPUBackend`` registry entry of the north star
(BASELINE.json:5), in its pure-XLA form — the Pallas-kernel variant is the
``tpu`` backend (pallas_backend.py).  ``search`` runs a host loop of jitted
device steps with **async double-buffering**: step k+1 is dispatched before
step k's 4-byte result is read back, so the device never idles on the host
(JAX's async dispatch gives this for free as long as we delay
``int()``-ing a result until the next step is enqueued).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from p1_tpu.core.header import target_from_difficulty, target_to_words
from p1_tpu.hashx.backend import HashBackend, SearchResult, register
from p1_tpu.hashx.jax_sha256 import jit_search_step
from p1_tpu.hashx.sha256_ref import header_midstate, header_tail_words, sha256d

_U32 = jnp.uint32


@register("jax")
class JaxBackend(HashBackend):
    """XLA-compiled SHA-256d search on the default JAX device."""

    def __init__(self, batch: int = 1 << 24, platform: str | None = None):
        if batch <= 0 or batch & (batch - 1):
            raise ValueError(f"batch must be a power of two, got {batch}")
        self.batch = batch
        self.platform = platform

    def sha256d(self, data: bytes) -> bytes:
        return sha256d(data)  # single digests stay on host

    def _search_arrays(self, header_prefix: bytes, difficulty: int):
        midstate = jnp.array(header_midstate(header_prefix), dtype=_U32)
        tail = jnp.array(header_tail_words(header_prefix), dtype=_U32)
        target = jnp.array(
            target_to_words(target_from_difficulty(difficulty)), dtype=_U32
        )
        return midstate, tail, target

    def search(
        self, header_prefix: bytes, nonce_start: int, count: int, difficulty: int
    ) -> SearchResult:
        self._check_search_args(header_prefix, nonce_start, count, difficulty)
        midstate, tail, target = self._search_arrays(header_prefix, difficulty)
        step = jit_search_step(self.batch, self.platform)

        # Batched scan with a one-step pipeline.  Each step covers
        # [base, base+batch); a partial final step is masked on the host by
        # re-checking the hit offset against the remaining count.
        pending: list[tuple[int, int, object]] = []  # (base, valid, device idx)
        done = 0
        result: SearchResult | None = None
        while done < count and result is None:
            base = nonce_start + done
            valid = min(self.batch, count - done)
            idx = step(midstate, tail, target, _U32(base))
            pending.append((base, valid, idx))
            done += valid
            if len(pending) > 1:
                result = self._drain_one(pending, nonce_start)
        while result is None and pending:
            result = self._drain_one(pending, nonce_start)
        if result is not None:
            return result
        return SearchResult(None, count)

    def _drain_one(self, pending: list, nonce_start: int) -> SearchResult | None:
        base, valid, idx = pending.pop(0)
        offset = int(np.asarray(idx))  # blocks until this step is done
        if offset < valid:
            nonce = base + offset
            return SearchResult(nonce, nonce - nonce_start + 1)
        return None
