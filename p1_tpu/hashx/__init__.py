from p1_tpu.hashx.backend import (
    HashBackend,
    SearchResult,
    available_backends,
    get_backend,
    register,
)

# Import for registration side effects.
from p1_tpu.hashx import cpu as _cpu  # noqa: F401
from p1_tpu.hashx import numpy_backend as _numpy  # noqa: F401

# Backends with heavy imports (JAX) or build steps (native .so) load lazily.
from p1_tpu.hashx.backend import register_lazy as _register_lazy


def _load_jax():
    from p1_tpu.hashx import jax_backend

    return jax_backend.JaxBackend


def _load_sharded():
    from p1_tpu.hashx import sharded

    return sharded.ShardedBackend


def _load_pallas():
    from p1_tpu.hashx import pallas_backend

    return pallas_backend.PallasTPUBackend


def _load_native():
    from p1_tpu.hashx import native_backend

    return native_backend.NativeBackend


_register_lazy("jax", _load_jax)
_register_lazy("sharded", _load_sharded)
_register_lazy("tpu", _load_pallas)
_register_lazy("native", _load_native)

__all__ = [
    "HashBackend",
    "SearchResult",
    "available_backends",
    "get_backend",
    "register",
]
