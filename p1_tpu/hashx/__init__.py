from p1_tpu.hashx.backend import (
    HashBackend,
    SearchResult,
    available_backends,
    get_backend,
    register,
)

# Import for registration side effects.
from p1_tpu.hashx import cpu as _cpu  # noqa: F401
from p1_tpu.hashx import numpy_backend as _numpy  # noqa: F401

# Backends with heavy imports (JAX) or build steps (native .so) load lazily.
from p1_tpu.hashx.backend import register_lazy as _register_lazy


def _load_jax():
    enable_persistent_compilation_cache()
    from p1_tpu.hashx import jax_backend

    return jax_backend.JaxBackend


def _load_sharded():
    enable_persistent_compilation_cache()
    from p1_tpu.hashx import sharded

    return sharded.ShardedBackend


def _load_pallas():
    enable_persistent_compilation_cache()
    from p1_tpu.hashx import pallas_backend

    return pallas_backend.PallasTPUBackend


def _load_native():
    from p1_tpu.hashx import native_backend

    return native_backend.NativeBackend


_register_lazy("jax", _load_jax)
_register_lazy("sharded", _load_sharded)
_register_lazy("tpu", _load_pallas)
_register_lazy("native", _load_native)


def enable_persistent_compilation_cache() -> None:
    """Point JAX's persistent compilation cache at ~/.cache/p1_tpu/jax
    (override the location with ``P1_CACHE_HOME``; disable by exporting
    the standard ``JAX_COMPILATION_CACHE_DIR``, which always wins).

    Cross-process win measured on the v5e relay: the first search step
    drops from ~4.7 s to ~1.9 s in a fresh process.  Runs automatically
    when a JAX-backed hash backend is lazily loaded — never on pure-host
    paths, which must not pay the jax import.  Best-effort: unsupported
    JAX versions or read-only homes just skip.
    """
    import os

    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        return  # the user configured JAX's own mechanism; don't clobber it
    try:
        import jax

        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(
                os.path.expanduser(os.environ.get("P1_CACHE_HOME", "~/.cache")),
                "p1_tpu",
                "jax",
            ),
        )
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # pragma: no cover - version/permission dependent
        pass

__all__ = [
    "HashBackend",
    "SearchResult",
    "available_backends",
    "enable_persistent_compilation_cache",
    "get_backend",
    "register",
]
