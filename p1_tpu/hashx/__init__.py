from p1_tpu.hashx.backend import (
    HashBackend,
    SearchResult,
    available_backends,
    get_backend,
    register,
)

# Import for registration side effects.
from p1_tpu.hashx import cpu as _cpu  # noqa: F401
from p1_tpu.hashx import numpy_backend as _numpy  # noqa: F401

# Backends with heavy imports (JAX) or build steps (native .so) load lazily.
from p1_tpu.hashx.backend import register_lazy as _register_lazy


def _load_jax():
    from p1_tpu.hashx import jax_backend

    return jax_backend.JaxBackend


_register_lazy("jax", _load_jax)
# "tpu" (Pallas kernel) and "native" (C++ core) register here when their
# modules land; advertising names whose modules don't exist yet would turn
# get_backend into a ModuleNotFoundError trap.

__all__ = [
    "HashBackend",
    "SearchResult",
    "available_backends",
    "get_backend",
    "register",
]
