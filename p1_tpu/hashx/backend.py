"""``HashBackend`` ABC and plugin registry.

Capability parity: the reference selects hash implementations through a
``HashBackend`` plugin registry keyed by name, so that adding a TPU backend
touches nothing outside ``miner/`` and ``hash/`` (BASELINE.json:5 — "The
existing ``HashBackend`` plugin registry gains a ``JaxTPUBackend`` entry").
Here the registry is the framework's own design: ``@register`` decorator,
``get_backend(name)`` factory, plus a lazy table for backends whose imports
are heavy (JAX) or optional (native .so), so ``import p1_tpu`` stays cheap.

The two operations every backend provides:

- ``sha256d(data)`` — one double-SHA-256 (validation path).
- ``search(prefix, nonce_start, count, difficulty)`` — scan candidate nonces
  ``[nonce_start, nonce_start+count)`` over a 76-byte header prefix and
  return the **earliest** nonce whose SHA-256d meets the difficulty target,
  or None.  This is the miner's hot loop (BASELINE.json:5).
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Callable, Iterator

from p1_tpu.core.header import NONCE_OFFSET


@dataclasses.dataclass(frozen=True)
class SearchResult:
    """Outcome of scanning a nonce range."""

    nonce: int | None  # earliest hit, or None
    hashes_done: int  # how many candidates were evaluated


class HashBackend(abc.ABC):
    """A pluggable SHA-256d implementation."""

    #: Registry key; set by @register.
    name: str = "?"

    @abc.abstractmethod
    def sha256d(self, data: bytes) -> bytes:
        """Double SHA-256 of ``data`` (32 raw bytes out)."""

    @abc.abstractmethod
    def search(
        self, header_prefix: bytes, nonce_start: int, count: int, difficulty: int
    ) -> SearchResult:
        """Find the earliest nonce in [nonce_start, nonce_start+count) whose
        header hash meets ``difficulty`` leading zero bits.

        ``header_prefix`` is the first ``NONCE_OFFSET`` (76) bytes of the
        serialized header.  The scanned range must stay within uint32 space.
        """

    def _check_search_args(
        self, header_prefix: bytes, nonce_start: int, count: int, difficulty: int
    ) -> None:
        if len(header_prefix) != NONCE_OFFSET:
            raise ValueError(
                f"header prefix must be {NONCE_OFFSET} bytes, got {len(header_prefix)}"
            )
        if not 0 <= nonce_start <= 0xFFFFFFFF:
            raise ValueError(f"nonce_start={nonce_start} out of uint32 range")
        if count < 0 or nonce_start + count > 1 << 32:
            raise ValueError("nonce range exceeds uint32 space")
        if not 0 <= difficulty <= 255:
            raise ValueError(f"difficulty={difficulty} out of range")


_REGISTRY: dict[str, type[HashBackend]] = {}
_LAZY_BACKENDS: dict[str, Callable[[], type[HashBackend]]] = {}
_INSTANCES: dict[tuple, HashBackend] = {}


def register(name: str) -> Callable[[type[HashBackend]], type[HashBackend]]:
    """Class decorator: ``@register("cpu")`` adds the backend to the registry."""

    def deco(cls: type[HashBackend]) -> type[HashBackend]:
        if name in _REGISTRY:
            raise ValueError(f"hash backend {name!r} already registered")
        cls.name = name
        _REGISTRY[name] = cls
        # A direct `import p1_tpu.hashx.<module>` fulfills the lazy entry
        # without going through _resolve; drop it so the name isn't listed
        # twice and _resolve never re-imports a loaded module.
        _LAZY_BACKENDS.pop(name, None)
        return cls

    return deco


def register_lazy(name: str, loader: Callable[[], type[HashBackend]]) -> None:
    """Register a backend whose module should only import on first use."""
    if name in _REGISTRY or name in _LAZY_BACKENDS:
        raise ValueError(f"hash backend {name!r} already registered")
    _LAZY_BACKENDS[name] = loader


def _resolve(name: str) -> type[HashBackend]:
    if name in _REGISTRY:
        return _REGISTRY[name]
    if name in _LAZY_BACKENDS:
        # The loader's module is expected to @register(name) on import,
        # which also removes the lazy entry.  A failed import leaves the
        # entry in place so the error surfaces again on retry.
        _LAZY_BACKENDS[name]()
        if name not in _REGISTRY:
            raise RuntimeError(f"lazy loader for {name!r} did not register it")
        return _REGISTRY[name]
    raise KeyError(
        f"unknown hash backend {name!r}; available: {sorted(available_backends())}"
    )


def get_backend(name: str, **kwargs) -> HashBackend:
    """Instantiate (and memoize) a backend by registry name."""
    key = (name, tuple(sorted(kwargs.items())))
    if key not in _INSTANCES:
        _INSTANCES[key] = _resolve(name)(**kwargs)
    return _INSTANCES[key]


def available_backends() -> Iterator[str]:
    yield from _REGISTRY
    yield from _LAZY_BACKENDS
