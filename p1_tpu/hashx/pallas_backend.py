"""Pallas-TPU hash backend: the SHA-256d nonce search as a Mosaic kernel.

This is the literal north-star artifact (BASELINE.json:5 — the miner's inner
loop "becomes a vmapped Pallas SHA-256 kernel that evaluates millions of
candidate nonces per device step"), and it exists for a measured reason, not
ceremony: the pure-XLA formulation (jax_backend.py) is **HBM-bound**.  XLA
compiles the 64-round ``fori_loop`` with its ~25-array uint32 carry spilled
to HBM between (unrolled) round bodies, so at batch 2²⁴ every round group
streams gigabytes through HBM and the VPU idles.  The Pallas kernel instead
works one ``(sub, 128)`` uint32 tile of nonces per grid step with the entire
rolling window held in VMEM/vector registers — HBM traffic is ~zero (a few
SMEM scalars in, 4 bytes out) and the search becomes compute-bound on the
VPU, which is the best a hash search can do on this hardware.

Layout (SURVEY.md §7 step 3):

- Nonces across VPU lanes: grid step ``i`` evaluates flat lane indices
  ``[i·sub·128, (i+1)·sub·128)`` as a ``(sub, 128)`` uint32 tile — the
  native vreg shape for 32-bit data.
- Same round math as jax_sha256 (``_compress`` is reused verbatim inside
  the kernel body: midstate chunk-2 + second pass, schedule extension fused
  into the round loop), so the Pallas/XLA/NumPy formulations stay
  lane-exact by construction.
- Scalar plumbing in SMEM: midstate (8), chunk-2 tail words (3), target
  (8), nonce base (1).  Output is a single SMEM uint32 — ``min`` over the
  grid of the earliest hit's flat index (or ``batch``) — accumulated across
  sequential grid steps, exactly the contract of jax_sha256.search_step, so
  the pipelined host loop and the sharded pmin reduction compose unchanged.
- ``interpret=True`` runs the identical kernel on CPU (tests; Mosaic needs
  real TPU hardware otherwise).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from p1_tpu.hashx.backend import HashBackend, register
from p1_tpu.hashx.jax_backend import _RAMP_FLOOR, PipelinedSearchMixin, StepFn
from p1_tpu.hashx.jax_sha256 import _compress, below_target
from p1_tpu.hashx.sha256_ref import IV, K

_U32 = jnp.uint32
# Round constants / IV enter the kernel as SMEM inputs: a Pallas kernel may
# not capture array-valued constants from its closure.
_K_WORDS = np.asarray(K, dtype=np.uint32)
_IV_WORDS = np.asarray(IV, dtype=np.uint32)

#: Rows of 128 lanes per grid step.  The v5e sweep (docs/PERF.md) put
#: sub=16 on top: 2048 nonces/step keeps the full compression window
#: (~30 live tile-arrays ≈ 0.25 MB) in VMEM with the best Mosaic schedule;
#: larger tiles spill, smaller ones starve the VPU of independent work.
_DEFAULT_SUB = 16

#: Device-step batch for compiled runs.  Unlike the XLA backend, the kernel
#: materializes nothing per nonce in HBM, so a huge batch costs only abort
#: granularity — and through the axon relay each dispatch carries ~40-125 ms
#: of RPC overhead, so big steps are what amortize it (the sweep saturated
#: at 2²⁷: ~750 MH/s vs 195 MH/s at 2²⁴).
_DEFAULT_BATCH = 1 << 27


def _search_kernel(
    mid_ref,
    tail_ref,
    target_ref,
    base_ref,
    k_ref,
    iv_ref,
    out_ref,
    *,
    sub: int,
    batch: int,
    unroll: int,
):
    """One grid step: hash a (sub, 128) tile of nonces, fold in its first hit.

    TPU grid steps run sequentially on the core, so the min-accumulation
    into the single SMEM output cell is race-free by construction — and
    that same sequencing powers the **early exit**: once any step has
    recorded a hit, every later step sees it in SMEM and skips its whole
    tile (one scalar read + branch instead of 2·64 compression rounds).
    Exactness is free — grid steps ascend in flat nonce index, so a later
    step can never hold an earlier hit than one already recorded.  This is
    what closes the d28 abort-granularity gap (VERDICT r3 item 4): the
    step containing the hit used to grind out its remaining ~2²⁷ nonces
    (~0.12 s wasted per block at the north-star difficulty); now the
    remainder of the batch costs microseconds.
    """
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[0] = jnp.int32(batch)

    @pl.when(out_ref[0] == jnp.int32(batch))  # no hit recorded yet
    def _():
        rows = jax.lax.broadcasted_iota(_U32, (sub, 128), 0)
        cols = jax.lax.broadcasted_iota(_U32, (sub, 128), 1)
        flat = i.astype(_U32) * _U32(sub * 128) + rows * _U32(128) + cols
        nonces = base_ref[0] + flat

        def bc(scalar):
            return jnp.full((sub, 128), scalar, dtype=_U32)

        zero = jnp.zeros((sub, 128), dtype=_U32)
        # Pass 1, chunk 2: tail words + nonce + pad(0x80) + bitlen 640.
        w = (bc(tail_ref[0]), bc(tail_ref[1]), bc(tail_ref[2]), nonces)
        w += (zero + _U32(0x80000000),) + (zero,) * 10 + (zero + _U32(640),)
        state1 = _compress(
            tuple(bc(mid_ref[k]) for k in range(8)), w, unroll=unroll, ks=k_ref
        )
        # Pass 2 over the 32-byte digest (bitlen 256).
        w2 = (
            state1 + (zero + _U32(0x80000000),) + (zero,) * 6 + (zero + _U32(256),)
        )
        iv = tuple(bc(iv_ref[k]) for k in range(8))
        digest = list(_compress(iv, w2, unroll=unroll, ks=k_ref))

        hits = below_target(digest, tuple(target_ref[k] for k in range(8)))
        # Mosaic has no unsigned-int reductions; flat indices are < 2³¹, so
        # the first-hit min runs in int32 and the wrapper casts back to
        # uint32.
        local = jnp.min(
            jnp.where(hits, flat.astype(jnp.int32), jnp.int32(batch))
        )
        out_ref[0] = jnp.minimum(out_ref[0], local)


def pallas_search_fn(
    batch: int,
    sub: int = _DEFAULT_SUB,
    interpret: bool = False,
    unroll: int | None = None,
):
    """The UNJITTED Pallas search step: (midstate(8,), tail(3,), target(8,),
    nonce_base) -> uint32 first-hit offset in [0, batch] (``batch`` = miss).

    Composable into larger traced programs — the ``sharded`` backend calls
    it inside ``shard_map`` so each chip of a mesh runs the kernel on its
    own nonce block; ``jit_pallas_search_step`` is the single-device jitted
    form.
    """
    block = sub * 128
    if batch % block:
        raise ValueError(f"batch {batch} not a multiple of the {block} tile")
    if batch >= 1 << 31:
        # The kernel's first-hit min runs in int32 (Mosaic has no unsigned
        # reductions): a 2³¹ batch wraps the miss sentinel negative and
        # silently masks every hit.  Guard at the layer that owns the
        # constraint so every composer (backends, shard_map) inherits it.
        raise ValueError(f"batch {batch} must be < 2**31")
    if unroll is None:
        # Interpret mode lowers through XLA:CPU, where a fully-unrolled
        # 128-round trace compiles for minutes (the trap jax_sha256's
        # rolled loop exists to avoid); Mosaic on real TPU wants the
        # straight-line body.
        unroll = 1 if interpret else 64

    try:
        from jax.experimental.pallas import tpu as pltpu

        smem = pltpu.SMEM
    except ImportError:  # pragma: no cover - pallas tpu backend always ships
        smem = None

    kernel = functools.partial(
        _search_kernel, sub=sub, batch=batch, unroll=unroll
    )
    scalar_spec = pl.BlockSpec(memory_space=smem)
    call = pl.pallas_call(
        kernel,
        grid=(batch // block,),
        in_specs=[scalar_spec] * 6,
        out_specs=pl.BlockSpec(
            (1,), lambda i: (0,), memory_space=smem
        ),
        # NOTE: composing this into shard_map requires check_vma=False on
        # the shard_map (the sharded backend does this): the pallas
        # machinery emits unvarying internal operands (grid indexing) that
        # the varying-manual-axes checker rejects.
        out_shape=jax.ShapeDtypeStruct((1,), jnp.int32),
        interpret=interpret,
    )

    def step(midstate, tail, target, nonce_base):
        return call(
            midstate,
            tail,
            target,
            jnp.reshape(nonce_base, (1,)),
            jnp.asarray(_K_WORDS),
            jnp.asarray(_IV_WORDS),
        )[0].astype(_U32)

    return step


@functools.cache
def jit_pallas_search_step(
    batch: int,
    sub: int = _DEFAULT_SUB,
    platform: str | None = None,
    interpret: bool = False,
    unroll: int | None = None,
) -> StepFn:
    """Jitted single-device ``pallas_search_fn`` (jit_search_step's exact
    signature)."""
    step = pallas_search_fn(batch, sub, interpret, unroll)
    device = jax.devices(platform)[0] if platform else None
    return jax.jit(step, device=device)


@register("tpu")
class PallasTPUBackend(PipelinedSearchMixin, HashBackend):
    """SHA-256d nonce search as a Pallas TPU kernel (north star's ``tpu``).

    ``interpret=None`` auto-detects: compiled Mosaic on a real TPU,
    interpreter mode elsewhere (CPU tests run the identical kernel).
    """

    def __init__(
        self,
        batch: int | None = None,
        sub: int = _DEFAULT_SUB,
        platform: str | None = None,
        interpret: bool | None = None,
    ):
        from p1_tpu.hashx.jax_backend import is_tpu_platform

        resolved = platform or jax.default_backend()
        if interpret is None:
            interpret = not is_tpu_platform(resolved)
        if batch is None:
            # Interpreted runs are for parity tests: keep steps small.
            batch = 1 << 12 if interpret else _DEFAULT_BATCH
        block = sub * 128
        if batch % block:
            raise ValueError(f"batch {batch} must be a multiple of {block}")
        if batch >= 1 << 31:
            # Same int32-sentinel bound pallas_search_fn enforces; checked
            # here too so misconfiguration fails at construction, not at
            # the first search's trace.
            raise ValueError(f"batch {batch} must be < 2**31")
        if _RAMP_FLOOR % block:
            # Ramp spans are powers of two; a tile that doesn't divide them
            # can't take part in the opening ramp.
            self.ramp_floor = None
        self.batch = batch
        self.sub = sub
        self.step_span = batch
        self.platform = platform
        self.interpret = interpret

    def _make_step(self, span: int) -> StepFn:
        return jit_pallas_search_step(
            span, self.sub, self.platform, self.interpret
        )
