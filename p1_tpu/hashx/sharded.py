"""Multi-chip sharded nonce search: ``shard_map`` + ``pmin`` over a Mesh.

Capability parity: the north star's pod-scale mode — "Nonce ranges shard
across chips with a ``pmin``-based first-hit reduction so a v5e-8 pod
presents as a single miner on the gossip network" (BASELINE.json:5; config 5
at BASELINE.json:11).  TPU-first design: the mesh is a 1-D
``jax.sharding.Mesh`` over all chips, each device scans a **contiguous,
disjoint** block of the step's nonce range, and one ``lax.pmin`` over the
per-device first-hit offsets (sentinel = whole span) rides the ICI to give
the deterministic global earliest nonce — 4 bytes cross the ICI per step,
nothing crosses per candidate.

Contiguous blocks (device d owns ``[base + d*batch, base + (d+1)*batch)``)
rather than interleaved strides keep the global offset a pure affine map of
the local one, so the ``pmin`` argument *is* the earliest-nonce order and
the result is bit-identical to a single-device scan of the same range —
the mesh-parity tests assert exactly that.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from p1_tpu.hashx.backend import HashBackend, register
from p1_tpu.hashx.jax_backend import (
    PipelinedSearchMixin,
    StepFn,
    default_batch,
    is_tpu_platform,
)
from p1_tpu.hashx.jax_sha256 import default_unroll, search_step

_U32 = jnp.uint32
AXIS = "chips"

# shard_map moved to the jax top level (and check_rep became check_vma,
# with lax.pcast the promotion API) in newer JAX; resolve whichever this
# environment carries so the mesh backend runs on both sides of the move.
if hasattr(jax, "shard_map"):
    _SHARD_MAP_KW = "check_vma"
    _shard_map = jax.shard_map
else:  # pre-move JAX: experimental module, check_rep, no pcast
    from jax.experimental.shard_map import shard_map as _shard_map

    _SHARD_MAP_KW = "check_rep"


def _pcast_varying(x, axis):
    """``lax.pcast(x, axis, to="varying")`` where it exists, identity
    where the old check_rep machinery infers replication itself."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axis, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, axis)
    return x


def make_mesh(
    n_devices: int | None = None, platform: str | None = None
) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` devices (default: all)."""
    devices = jax.devices(platform) if platform else jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} available"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (AXIS,))


@functools.cache
def jit_sharded_step(
    mesh: Mesh,
    batch_per_device: int,
    unroll: int | None = None,
    kernel: str = "xla",
) -> StepFn:
    """Jitted sharded step closed over mesh + per-device batch.

    Signature matches ``jit_search_step``: (midstate, tail, target,
    nonce_base) -> uint32 offset of the earliest hit in
    [nonce_base, nonce_base + n_devices*batch_per_device), or the span.
    All inputs are replicated (``P()``); the output is replicated too —
    ``pmin`` makes it device-invariant, so any shard can be read back.

    ``kernel`` picks the per-device search body: ``"xla"`` (the jax_sha256
    formulation — also what CPU validation meshes run) or ``"pallas"``
    (the Mosaic kernel of pallas_backend inside shard_map, so every chip
    of a real TPU mesh mines at the single-chip kernel rate, docs/PERF.md).
    """
    n = mesh.devices.size
    span = n * batch_per_device
    if span >= 1 << 32:
        raise ValueError("step span must stay below uint32 nonce space")
    platform = mesh.devices.flat[0].platform
    if kernel == "pallas":
        from p1_tpu.hashx.pallas_backend import pallas_search_fn

        device_search = pallas_search_fn(
            batch_per_device,
            interpret=not is_tpu_platform(platform),
            unroll=unroll,
        )
    elif kernel == "xla":
        if unroll is None:
            # Resolve against the mesh's platform, not the ambient default
            # backend: a CPU validation mesh on a TPU host must get the
            # trace-tiny body, and vice versa.
            unroll = default_unroll(platform)
        device_search = functools.partial(
            search_step, batch=batch_per_device, unroll=unroll
        )
    else:
        raise ValueError(f"unknown sharded kernel {kernel!r}")

    # The pallas body needs check_vma off: pallas' internal grid indexing
    # emits unvarying operands the varying-manual-axes checker rejects
    # (JAX's own suggested workaround).  The XLA body keeps the check and
    # the explicit pcast promotion it requires.
    check_vma = kernel != "pallas"

    @jax.jit
    @functools.partial(
        _shard_map,
        mesh=mesh,
        in_specs=(P(), P(), P(), P()),
        out_specs=P(),
        **{_SHARD_MAP_KW: check_vma},
    )
    def step(midstate, tail, target, nonce_base):
        d = lax.axis_index(AXIS).astype(_U32)
        base = nonce_base + d * _U32(batch_per_device)
        if check_vma:
            # ``base`` varies per device, so the whole hash dataflow is
            # varying over the mesh axis; promote the replicated inputs to
            # match, or the fori_loop carry in the compression rejects the
            # mixed types.
            midstate, tail, target = (
                _pcast_varying(x, AXIS) for x in (midstate, tail, target)
            )
        off = device_search(midstate, tail, target, base)
        hit = off < _U32(batch_per_device)
        global_off = jnp.where(hit, d * _U32(batch_per_device) + off, _U32(span))
        return lax.pmin(global_off, AXIS)

    return step


@register("sharded")
class ShardedBackend(PipelinedSearchMixin, HashBackend):
    """SHA-256d search sharded over every chip of a device mesh.

    ``batch`` is the per-device batch; one step evaluates
    ``n_devices * batch`` nonces.  With one device this degrades gracefully
    to the single-chip search (the ``pmin`` is a no-op), so the same backend
    name works from a laptop CPU to a pod slice.
    """

    def __init__(
        self,
        batch: int | None = None,
        n_devices: int | None = None,
        platform: str | None = None,
        unroll: int | None = None,
        kernel: str | None = None,
    ):
        self.mesh = make_mesh(n_devices, platform)
        mesh_platform = self.mesh.devices.flat[0].platform
        if kernel is None:
            # Real TPU chips run the Mosaic kernel (7x the XLA formulation,
            # docs/PERF.md); CPU validation meshes keep the XLA body — the
            # interpreted Pallas kernel is a correctness tool, too slow to
            # be the default 8-virtual-device path.
            kernel = "pallas" if is_tpu_platform(mesh_platform) else "xla"
        if batch is None:
            if kernel == "pallas" and is_tpu_platform(mesh_platform):
                # The kernel's rate comes from big dispatch-amortizing
                # steps (docs/PERF.md), not the XLA-carry-sized default.
                from p1_tpu.hashx.pallas_backend import _DEFAULT_BATCH

                batch = _DEFAULT_BATCH
            else:
                batch = default_batch(mesh_platform)
        if batch <= 0 or batch & (batch - 1):
            raise ValueError(f"batch must be a power of two, got {batch}")
        if kernel == "pallas":
            # Mirror PallasTPUBackend's construction-time guards: the
            # kernel's first-hit min runs in int32 and nonces tile as
            # (sub, 128) blocks — fail here, not at the first search.
            from p1_tpu.hashx.pallas_backend import _DEFAULT_SUB

            block = _DEFAULT_SUB * 128
            if batch % block:
                raise ValueError(
                    f"per-device batch {batch} must be a multiple of {block} "
                    "for the pallas kernel"
                )
            if batch >= 1 << 31:
                raise ValueError(f"per-device batch {batch} must be < 2**31")
        self.n_devices = self.mesh.devices.size
        self.batch = batch
        self.kernel = kernel
        self.step_span = self.n_devices * batch
        if self.step_span >= 1 << 32:
            # jit_sharded_step would reject this at first search; fail at
            # construction instead (reachable: 32 devices x the 2**27
            # pallas default).
            raise ValueError(
                f"step span {self.step_span} (= {self.n_devices} devices x "
                f"batch {batch}) must stay below uint32 nonce space"
            )
        self.unroll = unroll
        # No opening ramp: the per-device batch is baked into the mesh
        # program, and a v5e-8 step is already granular enough per chip.
        self.ramp_floor = None

    def _make_step(self, span: int) -> StepFn:
        assert span == self.step_span, "sharded step span is fixed"
        return jit_sharded_step(self.mesh, self.batch, self.unroll, self.kernel)
