"""Multi-chip sharded nonce search: ``shard_map`` + ``pmin`` over a Mesh.

Capability parity: the north star's pod-scale mode — "Nonce ranges shard
across chips with a ``pmin``-based first-hit reduction so a v5e-8 pod
presents as a single miner on the gossip network" (BASELINE.json:5; config 5
at BASELINE.json:11).  TPU-first design: the mesh is a 1-D
``jax.sharding.Mesh`` over all chips, each device scans a **contiguous,
disjoint** block of the step's nonce range, and one ``lax.pmin`` over the
per-device first-hit offsets (sentinel = whole span) rides the ICI to give
the deterministic global earliest nonce — 4 bytes cross the ICI per step,
nothing crosses per candidate.

Contiguous blocks (device d owns ``[base + d*batch, base + (d+1)*batch)``)
rather than interleaved strides keep the global offset a pure affine map of
the local one, so the ``pmin`` argument *is* the earliest-nonce order and
the result is bit-identical to a single-device scan of the same range —
the mesh-parity tests assert exactly that.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from p1_tpu.hashx.backend import HashBackend, register
from p1_tpu.hashx.jax_backend import PipelinedSearchMixin, StepFn, default_batch
from p1_tpu.hashx.jax_sha256 import default_unroll, search_step

_U32 = jnp.uint32
AXIS = "chips"


def make_mesh(
    n_devices: int | None = None, platform: str | None = None
) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` devices (default: all)."""
    devices = jax.devices(platform) if platform else jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} available"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (AXIS,))


@functools.cache
def jit_sharded_step(
    mesh: Mesh, batch_per_device: int, unroll: int | None = None
) -> StepFn:
    """Jitted sharded step closed over mesh + per-device batch.

    Signature matches ``jit_search_step``: (midstate, tail, target,
    nonce_base) -> uint32 offset of the earliest hit in
    [nonce_base, nonce_base + n_devices*batch_per_device), or the span.
    All inputs are replicated (``P()``); the output is replicated too —
    ``pmin`` makes it device-invariant, so any shard can be read back.
    """
    n = mesh.devices.size
    span = n * batch_per_device
    if span >= 1 << 32:
        raise ValueError("step span must stay below uint32 nonce space")
    if unroll is None:
        # Resolve against the mesh's platform, not the ambient default
        # backend: a CPU validation mesh on a TPU host must get the
        # trace-tiny body, and vice versa.
        unroll = default_unroll(mesh.devices.flat[0].platform)

    @jax.jit
    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), P(), P(), P()),
        out_specs=P(),
    )
    def step(midstate, tail, target, nonce_base):
        d = lax.axis_index(AXIS).astype(_U32)
        base = nonce_base + d * _U32(batch_per_device)
        # ``base`` varies per device, so the whole hash dataflow is varying
        # over the mesh axis; promote the replicated inputs to match, or the
        # fori_loop carry in the compression rejects the mixed types.
        midstate, tail, target = (
            lax.pcast(x, AXIS, to="varying") for x in (midstate, tail, target)
        )
        off = search_step(midstate, tail, target, base, batch_per_device, unroll)
        hit = off < _U32(batch_per_device)
        global_off = jnp.where(hit, d * _U32(batch_per_device) + off, _U32(span))
        return lax.pmin(global_off, AXIS)

    return step


@register("sharded")
class ShardedBackend(PipelinedSearchMixin, HashBackend):
    """SHA-256d search sharded over every chip of a device mesh.

    ``batch`` is the per-device batch; one step evaluates
    ``n_devices * batch`` nonces.  With one device this degrades gracefully
    to the single-chip search (the ``pmin`` is a no-op), so the same backend
    name works from a laptop CPU to a pod slice.
    """

    def __init__(
        self,
        batch: int | None = None,
        n_devices: int | None = None,
        platform: str | None = None,
        unroll: int | None = None,
    ):
        self.mesh = make_mesh(n_devices, platform)
        if batch is None:
            batch = default_batch(self.mesh.devices.flat[0].platform)
        if batch <= 0 or batch & (batch - 1):
            raise ValueError(f"batch must be a power of two, got {batch}")
        self.n_devices = self.mesh.devices.size
        self.batch = batch
        self.step_span = self.n_devices * batch
        self.unroll = unroll
        # No opening ramp: the per-device batch is baked into the mesh
        # program, and a v5e-8 step is already granular enough per chip.
        self.ramp_floor = None

    def _make_step(self, span: int) -> StepFn:
        assert span == self.step_span, "sharded step span is fixed"
        return jit_sharded_step(self.mesh, self.batch, self.unroll)
