import sys

from p1_tpu.cli import main

# The guard matters beyond hygiene: the far-field shard workers
# (node/farfield.py) use the multiprocessing spawn context, whose
# children re-import the parent's __main__ module — without it, a
# `p1 sim --shards N` run would recursively re-enter the CLI in every
# worker.
if __name__ == "__main__":
    sys.exit(main())
