import sys

from p1_tpu.cli import main

sys.exit(main())
