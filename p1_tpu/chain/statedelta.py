"""Per-segment ledger-state deltas: the ``.sdx`` sidecar plane.

Round 20 (the always-on node), leg (b) of the zero-downtime operations
plane: when a segment seals (chain/segstore.py ``_roll``), the net
ledger effect of every record inside it — per-account balance shifts
and nonce increments, in canonical account order — is written to a
``segNNNNN.sdx`` sidecar next to the segment, with the same durability
framing as everything else in the store family (magic + CRC-framed
JSON, tmp + rename + dir-fsync).

What that buys:

- **Incremental state derivation.**  The ledger state at a segment
  boundary is the previous boundary's state plus one delta — O(delta)
  accounts touched, never O(accounts) — so continuous snapshot
  publication (chain/snapshot.py ``build_records_incremental``) and
  offline state audits can advance checkpoint state without replaying
  a single block body.
- **Prune survival.**  Like the ``.hdrx`` header sidecar, the delta
  outlives its segment's bodies: a pruned archive still knows *what
  the discarded records did to the state*, which is exactly the part a
  boot snapshot needs to extend.

Trust + failure model, identical to the header plane: the sidecar is
**derivable cache**, never the only copy — the segment's records are
the data, and a failed or missing sidecar costs a rebuild
(``write_segment_delta`` over the segment bytes), never data.  The
store tolerates sidecar write failures (``healed["sdx_failures"]``)
exactly as it tolerates ``hdrx_failures``.

Determinism: the delta is a pure function of the segment bytes —
accounts serialize sorted by utf-8 key, JSON with sorted keys, no
floats — so two nodes sealing byte-identical segments write
byte-identical sidecars (pinned in tests/test_maintenance.py).
"""

from __future__ import annotations

import dataclasses
import json
import os
import struct
import zlib
from pathlib import Path

# NOTE: chain.store is imported lazily inside the functions that scan
# segment bytes — chain/chain.py imports ``block_accounts`` from here,
# and chain/store.py imports chain/chain.py, so a module-level store
# import would close an import cycle.
from p1_tpu.core.block import Block

__all__ = [
    "SDX_MAGIC",
    "SegmentDelta",
    "block_accounts",
    "load_segment_delta",
    "segment_delta",
    "write_segment_delta",
]

#: Sidecar format tag, versioned like every other on-disk magic here.
SDX_MAGIC = b"P1TPUSD1"

_LEN = struct.Struct(">I")
_CRC = struct.Struct(">I")


def block_accounts(block: Block) -> set[str]:
    """Every account whose balance or nonce ``block`` touches — the
    coinbase recipient plus each transfer's sender and recipient.  The
    chain's dirty-account tracking (incremental snapshot creation) and
    the segment delta below share this one definition."""
    accounts: set[str] = set()
    for i, tx in enumerate(block.txs):
        if i == 0 and tx.is_coinbase:
            accounts.add(tx.recipient)
            continue
        accounts.add(tx.sender)
        accounts.add(tx.recipient)
    return accounts


@dataclasses.dataclass(frozen=True)
class SegmentDelta:
    """The net ledger effect of one segment's records.

    ``balances``/``nonces`` map account → signed shift (nonces only
    ever shift up within one segment, but the type stays signed so the
    arithmetic composes).  ``records`` counts the blocks summed;
    ``first_hash``/``last_hash`` pin which records, so a delta can be
    cross-checked against the segment it claims to describe."""

    records: int
    balances: dict[str, int]
    nonces: dict[str, int]
    first_hash: bytes | None
    last_hash: bytes | None

    def apply(
        self, balances: dict[str, int], nonces: dict[str, int]
    ) -> tuple[dict[str, int], dict[str, int]]:
        """State after this delta, from copies (inputs untouched).
        Zero entries drop on the way out — the same invariant the live
        ``Ledger`` keeps, so derived state compares clean against it."""
        out_b = dict(balances)
        out_n = dict(nonces)
        for account, d in self.balances.items():
            v = out_b.get(account, 0) + d
            if v:
                out_b[account] = v
            else:
                out_b.pop(account, None)
        for account, d in self.nonces.items():
            v = out_n.get(account, 0) + d
            if v:
                out_n[account] = v
            else:
                out_n.pop(account, None)
        return out_b, out_n


def segment_delta(segment_data: bytes) -> SegmentDelta:
    """Sum the ledger effect of every record in a segment's raw bytes.

    Frames are walked with the store's own scanner (torn tails and bad
    spans are simply not part of the sum — the sidecar describes what
    the segment durably holds).  The per-block delta rule is the
    ledger's (``Ledger._block_delta`` with ``check=False``): this
    module must never invent a second definition of what a block does
    to the state."""
    from p1_tpu.chain.ledger import Ledger
    from p1_tpu.chain.store import ChainStore

    ledger = Ledger()
    balances: dict[str, int] = {}
    nonces: dict[str, int] = {}
    records = 0
    first_hash: bytes | None = None
    last_hash: bytes | None = None
    for off, n in ChainStore.scan(segment_data).spans:
        block = Block.deserialize(segment_data[off : off + n])
        delta = ledger._block_delta(block, check=False)
        for account, d in delta.balances.items():
            balances[account] = balances.get(account, 0) + d
        for account, d in delta.nonces.items():
            nonces[account] = nonces.get(account, 0) + d
        bhash = block.block_hash()
        if first_hash is None:
            first_hash = bhash
        last_hash = bhash
        records += 1
    return SegmentDelta(
        records=records,
        balances={a: d for a, d in balances.items() if d},
        nonces={a: d for a, d in nonces.items() if d},
        first_hash=first_hash,
        last_hash=last_hash,
    )


def _encode(delta: SegmentDelta) -> bytes:
    payload = json.dumps(
        {
            "version": 1,
            "records": delta.records,
            "balances": delta.balances,
            "nonces": delta.nonces,
            "first_hash": delta.first_hash.hex() if delta.first_hash else None,
            "last_hash": delta.last_hash.hex() if delta.last_hash else None,
        },
        sort_keys=True,
        separators=(",", ":"),
    ).encode("utf-8")
    body = _LEN.pack(len(payload)) + payload
    return SDX_MAGIC + body + _CRC.pack(zlib.crc32(body))


def write_segment_delta(segment_data: bytes, out_path) -> SegmentDelta:
    """Derive + durably write the sidecar for a segment's bytes (tmp +
    fsync + rename + dir-fsync — the store family's discipline; a crash
    leaves either the old sidecar or the new one, both derivable).
    Returns the delta it wrote."""
    from p1_tpu.chain.store import fsync_dir

    delta = segment_delta(segment_data)
    out_path = Path(out_path)
    tmp = out_path.with_name(f"{out_path.name}.tmp.{os.getpid()}")
    with open(tmp, "wb") as fh:
        fh.write(_encode(delta))
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, out_path)
    fsync_dir(out_path.parent)
    return delta


def load_segment_delta(path) -> SegmentDelta | None:
    """Parse a sidecar file; None when missing/corrupt — like the
    manifest and the header plane, a bad sidecar is a cache miss (the
    caller rebuilds from the segment), never an error to propagate."""
    try:
        data = Path(path).read_bytes()
    except OSError:
        return None
    if not data.startswith(SDX_MAGIC):
        return None
    off = len(SDX_MAGIC)
    if off + _LEN.size + _CRC.size > len(data):
        return None
    (n,) = _LEN.unpack_from(data, off)
    end = off + _LEN.size + n
    if end + _CRC.size > len(data):
        return None
    body = data[off:end]
    if zlib.crc32(body) != _CRC.unpack_from(data, end)[0]:
        return None
    try:
        d = json.loads(data[off + _LEN.size : end].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError):
        return None
    if not isinstance(d, dict) or d.get("version") != 1:
        return None
    try:
        return SegmentDelta(
            records=int(d["records"]),
            balances={a: int(v) for a, v in d["balances"].items()},
            nonces={a: int(v) for a, v in d["nonces"].items()},
            first_hash=bytes.fromhex(d["first_hash"]) if d["first_hash"] else None,
            last_hash=bytes.fromhex(d["last_hash"]) if d["last_hash"] else None,
        )
    except (KeyError, TypeError, ValueError, AttributeError):
        return None
