"""Chain persistence: append-only block log + resume from tip.

The blockchain analog of checkpoint/resume (SURVEY.md §5): every block the
node accepts is appended to a length-prefixed record log; on restart the
log replays through ``Chain.add_block`` — full validation, fork choice,
and orphan handling included — so a corrupt or truncated tail degrades to
"resume from the last good block" rather than a poisoned index.  Records
keep insertion order, which preserves first-seen tie-breaks and means
side branches survive restarts too.
"""

from __future__ import annotations

import fcntl
import io
import os
import struct
from pathlib import Path
from typing import Iterator

from p1_tpu.chain.chain import AddStatus, Chain
from p1_tpu.core.block import Block
from p1_tpu.core.header import HEADER_SIZE

_LEN = struct.Struct(">I")
#: Format tag, versioned with the RECORD layout, not just the framing:
#: round 4 extended the transaction wire format (Ed25519 pubkey + sig
#: fields), so "2" refuses round-3 stores with a clean message instead of
#: crashing mid-parse with a raw "truncated transaction".
MAGIC = b"P1TPUCH2"
_OLD_MAGICS = (b"P1TPUCHN",)


class ChainStore:
    """Append-only block log backing one node's chain.

    Durability contract: with ``fsync=True`` (the default) every
    ``append`` returns only after ``os.fsync`` — an acknowledged block
    survives OS crash / power loss, not just process death.  At benchmark
    block rates the cost is noise next to the PoW (measured ~1.9 ms/append
    on this VM's fs vs ≥120 ms blocks; see docs/PERF.md).  ``fsync=False`` keeps only the
    process-crash guarantee (the flush + torn-tail truncation story) for
    workloads that prefer raw append throughput, e.g. bulk ``save_chain``
    snapshots, which are re-derivable."""

    def __init__(self, path: str | os.PathLike, fsync: bool = True):
        self.path = Path(path)
        self.fsync = fsync
        self._fh: io.BufferedWriter | None = None

    def acquire(self) -> None:
        """Open + exclusively lock the store for this writer's lifetime
        (idempotent; released by ``close``).  Raises RuntimeError when
        another process holds the lock — two nodes appending to one store,
        or a compaction racing a live node, would corrupt or silently
        orphan records.

        Lock ordering matters: the torn-tail truncation runs strictly
        UNDER the lock, so a refused second writer can never truncate a
        live writer's in-flight record on its way to the refusal.
        """
        if self._fh is not None:
            return
        self.path.parent.mkdir(parents=True, exist_ok=True)
        fh = open(self.path, "a+b")  # "a": every write appends
        try:
            fcntl.flock(fh, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError as e:
            fh.close()
            raise RuntimeError(
                f"{self.path} is locked by another process (a running node?)"
            ) from e
        try:
            if self.path.stat().st_size == 0:
                fh.write(MAGIC)
                fh.flush()
            else:
                # Drop any truncated tail record (crash mid-append) before
                # writing behind it, or its stale length prefix would point
                # into the new records and corrupt the whole log.
                good_end = self._scan_good_end(self.path.read_bytes())
                if good_end < self.path.stat().st_size:
                    os.truncate(self.path, good_end)
        except ValueError as e:
            # e.g. "not a chain store": release the lock + handle instead
            # of leaking an exclusively-flocked fd, and surface the same
            # clean error type as the lock conflict.
            fh.close()
            raise RuntimeError(str(e)) from e
        self._fh = fh

    def append(self, block: Block) -> None:
        self.acquire()
        # ``serialize`` is memoized on the block: for a block that arrived
        # off the wire these are the exact gossip bytes — ingest appends
        # with zero re-packing (docs/PERF.md "host ingest plane").
        raw = block.serialize()
        self._fh.write(_LEN.pack(len(raw)))
        self._fh.write(raw)
        self._fh.flush()
        if self.fsync:
            os.fsync(self._fh.fileno())

    def sync(self) -> None:
        """Flush + fsync now — the batch closer for callers that toggle
        ``fsync`` off around a bulk append run (e.g. a node persisting a
        whole BLOCKS resync batch pays one fsync per frame, not per
        block; every batched block is re-fetchable from peers if the OS
        eats the window)."""
        if self._fh is not None:
            self._fh.flush()
            os.fsync(self._fh.fileno())

    @staticmethod
    def _check_magic(data: bytes, label: str = "") -> None:
        prefix = f"{label} " if label else ""
        if not data.startswith(MAGIC):
            if any(data.startswith(m) for m in _OLD_MAGICS):
                raise ValueError(
                    f"{prefix}written by an older p1-tpu version "
                    "(incompatible transaction format); re-mine or discard it"
                )
            raise ValueError(f"{prefix}not a chain store")

    @staticmethod
    def _record_spans(data: bytes) -> Iterator[tuple[int, int]]:
        """(offset, length) of every whole record's block bytes — the ONE
        walk of the framing, shared by the tail scan, the batch parse,
        and the packed-header extraction, so the three can't drift.
        Stops cleanly at a truncated tail."""
        off = len(MAGIC)
        while off + _LEN.size <= len(data):
            (n,) = _LEN.unpack_from(data, off)
            if off + _LEN.size + n > len(data):
                break
            yield off + _LEN.size, n
            off += _LEN.size + n

    @classmethod
    def _scan_good_end(cls, data: bytes) -> int:
        """Byte offset just past the last whole record."""
        cls._check_magic(data)
        end = len(MAGIC)
        for off, n in cls._record_spans(data):
            end = off + n
        return end

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def _read_checked(self) -> bytes:
        data = self.path.read_bytes()
        self._check_magic(data, str(self.path))
        return data

    def load_blocks(self) -> list[Block]:
        """All decodable records, stopping cleanly at a truncated tail.

        Batch parse on the packed-bytes plane: each ``Block.deserialize``
        seeds the block's (and its header's and transactions') encoding
        caches with the record's exact bytes, so resume never re-packs —
        ``add_block``'s hashing, the ledger's txids, and any later relay
        all reuse the disk bytes (docs/PERF.md "Restart at scale")."""
        if not self.path.exists():
            return []
        data = self._read_checked()
        return [
            Block.deserialize(data[off : off + n])
            for off, n in self._record_spans(data)
        ]

    def packed_headers(self) -> tuple[bytes, int]:
        """(buffer, count): every record's 80-byte header, contiguous, cut
        straight from the record framing with NO object parse — the exact
        shape ``replay_packed``/the native verifier take in one ctypes
        call.  For a linear store (a ``save_chain`` snapshot or compacted
        log — main branch only, append order = height order) this is the
        whole-chain PoW + linkage check at the raw-bytes rate; stores
        carrying side branches fail linkage at the first out-of-line
        record, by construction."""
        if not self.path.exists():
            return b"", 0
        data = self._read_checked()
        parts = [
            data[off : off + HEADER_SIZE] for off, _ in self._record_spans(data)
        ]
        return b"".join(parts), len(parts)

    def load_chain(
        self,
        difficulty: int,
        blocks: list[Block] | None = None,
        retarget=None,
        trusted: bool = False,
    ) -> Chain:
        """Rebuild a validated chain from the log (skipping the genesis
        record, which the Chain constructor provides).  Pass ``blocks``
        when the caller already ran ``load_blocks`` (avoids a second full
        read+parse of the log), and the store's ``RetargetRule`` if the
        chain was mined with one (the rule is part of chain identity).

        ``trusted=True`` is the fast-resume path for a node reloading its
        OWN store: every record was fully validated by this node before
        it was appended (and the store is exclusively flocked, so nothing
        else wrote it), so the stateless checks — Ed25519 signatures
        above all — are skipped while the contextual rules and the
        connect-time ledger still rebuild identical state (measured ~3x
        end-to-end at 100k blocks — 4.6 s vs 14.0 s, docs/PERF.md;
        equivalence is tested).  The cost:
        on-disk bit-rot inside a record body goes undetected until it
        disagrees with the network — ``p1 node --revalidate-store`` is
        the remedy when disk integrity is in question (header-only
        tools like ``p1 replay`` check PoW/linkage, not bodies).

        Raises ValueError when records exist but NONE connect — that is a
        store from a chain with different parameters (wrong difficulty /
        retarget flags), and proceeding would be catastrophic for some
        callers (``p1 compact`` would rewrite the store as a genesis-only
        snapshot of the wrong chain).  The guard lives here, once, so no
        call site can forget it; a partially-connecting store (corrupt
        tail) still loads what it can.

        Resume operates on the packed-bytes plane end to end: the batch
        parse (``load_blocks``) seeds every block's encoding caches from
        the record bytes, so the per-block hashing that ``add_block`` and
        the ledger need digests the disk bytes directly — no
        re-serialization anywhere in the resume loop (measured in
        benchmarks/host_ingest.py, recorded in docs/PERF.md)."""
        chain = Chain(difficulty, retarget=retarget)
        ghash = chain.genesis.block_hash()
        saw_record = False
        for block in self.load_blocks() if blocks is None else blocks:
            if block.block_hash() == ghash:
                continue
            saw_record = True
            chain.add_block(block, trusted=trusted)
        if saw_record and not chain.height:
            raise ValueError(
                f"{self.path}: records do not connect to this chain's "
                "genesis — wrong --difficulty or "
                "--retarget-window/--target-spacing for this store?"
            )
        return chain


def save_chain(chain: Chain, path: str | os.PathLike) -> None:
    """Snapshot a chain's main branch to a fresh store (tooling aid; nodes
    normally append incrementally as blocks arrive).  The snapshot is
    LINEAR by construction — genesis-first main branch — so its
    ``packed_headers`` buffer verifies in one native call
    (``replay_packed``), which is how ``p1 compact`` proves a snapshot
    before replacing the original log."""
    p = Path(path)
    if p.exists():
        p.unlink()
    # Bulk snapshot: one fsync at the end (via close -> OS) is enough; the
    # source chain still exists in memory if the write is lost.
    store = ChainStore(p, fsync=False)
    try:
        for block in chain.main_chain():
            store.append(block)
        os.fsync(store._fh.fileno())
    finally:
        store.close()
