"""Chain persistence: append-only block log + resume from tip.

The blockchain analog of checkpoint/resume (SURVEY.md §5): every block the
node accepts is appended to a length-prefixed record log; on restart the
log replays through ``Chain.add_block`` — full validation, fork choice,
and orphan handling included — so a corrupt or truncated tail degrades to
"resume from the last good block" rather than a poisoned index.  Records
keep insertion order, which preserves first-seen tie-breaks and means
side branches survive restarts too.

Round 7 — the durability layer.  v3 framing carries a CRC32 trailer per
record (over the length prefix AND the payload), which splits on-disk
damage into two cases the recovery paths treat differently:

- **torn tail** — the file ends inside a record (crash mid-append).  The
  expected crash artifact: the partial record is silently truncated
  under the writer lock, exactly as before.
- **mid-log corruption** — a record whose bytes are all present but fail
  their checksum (bit-rot, a flipped length prefix, a bad sector).
  Pre-v3 framing could not tell this from a torn tail, so one flipped
  bit in a mid-log length prefix silently truncated every good record
  behind it.  v3 *resyncs*: scan forward for the next checksum-valid
  record boundary, quarantine the bad span to a ``.quarantine`` sidecar,
  keep everything else, and surface counts (``ChainStore.healed``).

v2 stores (``P1TPUCH2``) stay readable — every read path accepts both
framings — but a writer refuses them with an upgrade hint (``p1 fsck``
or ``p1 compact`` rewrite the log as v3).
"""

from __future__ import annotations

import dataclasses
import fcntl
import io
import os
import struct
import threading
import zlib
from pathlib import Path
from typing import Iterator

from p1_tpu.chain.chain import AddStatus, Chain
from p1_tpu.core.block import Block
from p1_tpu.core.header import HEADER_SIZE, BlockHeader

_LEN = struct.Struct(">I")
_CRC = struct.Struct(">I")
#: Quarantine sidecar entry header: original byte offset (u64) + span
#: length (u32), followed by the raw quarantined bytes.  Append-only, so
#: repeated heals accumulate evidence instead of overwriting it.
_QREC = struct.Struct(">QI")
#: Format tag, versioned with the RECORD layout, not just the framing:
#: "3" adds the per-record CRC32 trailer (corruption containment); "2"
#: extended the transaction wire format (Ed25519 fields) over round 3's
#: original layout.  Older tags are refused with a clean message instead
#: of crashing mid-parse with a raw "truncated transaction".
MAGIC = b"P1TPUCH3"
V2_MAGIC = b"P1TPUCH2"
_OLD_MAGICS = (b"P1TPUCHN",)
#: Largest length prefix a record may carry — same bound as the wire's
#: ``protocol.MAX_FRAME`` (every stored block arrived in, or must fit
#: into, one gossip frame).  Scanning rejects bigger length fields
#: before checksumming, which bounds the resync walk's per-candidate
#: cost: a random 32-bit length passes this gate ~0.8% of the time, so
#: recovering framing past a corrupt span stays near-linear instead of
#: O(file_size x record_size).
_MAX_RECORD = 32 << 20

#: Body spans are packed ``(offset << _SPAN_SHIFT) | length`` into ONE
#: int per block: the span index is an O(chain) RAM structure, and a
#: small int (~36 B) beats a tuple of two (~116 B) by ~8 MB at 100k
#: blocks.  26 bits holds any length ≤ _MAX_RECORD (= 2**25) inclusive.
_SPAN_SHIFT = 26
assert _MAX_RECORD < (1 << _SPAN_SHIFT)


def fsync_dir(path: str | os.PathLike) -> None:
    """fsync a DIRECTORY, making a just-created or just-renamed entry
    durable: on journaling filesystems the rename/create lives in the
    directory's metadata, and a crash after the data fsync but before
    the metadata journal commits can lose the entry — the file's bytes
    were safe, the *name* wasn't."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


@dataclasses.dataclass
class StoreScan:
    """One framing walk's verdict over a store's raw bytes."""

    #: Record-layout version the magic declared (2 = pre-checksum).
    version: int
    #: (payload offset, payload length) of every checksum-valid record
    #: (v2: every whole record — no checksums to check), in file order.
    spans: list[tuple[int, int]]
    #: [start, end) byte ranges that fail their checksum but are fully
    #: present — mid-log corruption, quarantinable.  Always empty for v2
    #: (undetectable without checksums: pre-v3 behavior was truncation).
    bad_spans: list[tuple[int, int]]
    #: Offset where an INCOMPLETE trailing record starts (crash
    #: mid-append), or None.  Truncated by the writer, never quarantined.
    torn_tail: int | None
    #: Total file size scanned.
    size: int

    @property
    def clean(self) -> bool:
        return not self.bad_spans and self.torn_tail is None

    @property
    def quarantined_bytes(self) -> int:
        return sum(e - s for s, e in self.bad_spans)


class ChainStore:
    """Append-only block log backing one node's chain.

    Durability contract: with ``fsync=True`` (the default) every
    ``append`` returns only after ``os.fsync`` — an acknowledged block
    survives OS crash / power loss, not just process death.  At benchmark
    block rates the cost is noise next to the PoW (measured ~1.9 ms/append
    on this VM's fs vs ≥120 ms blocks; see docs/PERF.md).  ``fsync=False`` keeps only the
    process-crash guarantee (the flush + torn-tail truncation story) for
    workloads that prefer raw append throughput, e.g. bulk ``save_chain``
    snapshots, which are re-derivable.

    The file layer is routed through four overridable seams
    (``_open_fh``/``_fsync_file``/``_fsync_dir``/``_read_bytes``) so the
    fault-injection harness (``chain/testing.py`` ``FaultStore``) can
    script disk pathologies without monkeypatching."""

    def __init__(self, path: str | os.PathLike, fsync: bool = True):
        self.path = Path(path)
        self.fsync = fsync
        self._fh: io.BufferedWriter | None = None
        #: The pre-heal scan ``acquire`` ran (None until then) and what
        #: the heal did about it — surfaced by ``Node.status()["storage"]``
        #: and ``p1 fsck``.
        self.last_scan: StoreScan | None = None
        self.healed = {
            "quarantined_records": 0,
            "quarantined_bytes": 0,
            "truncated_bytes": 0,
        }
        #: block hash -> (payload offset, length), populated by the read
        #: paths and by ``append`` — the offset index behind on-demand
        #: body refetch (``read_body``), which is what lets the chain
        #: evict block bodies from RAM (memory-bounded operation).
        self._body_spans: dict[bytes, int] = {}
        #: File offset the NEXT append lands at, maintained so appends can
        #: register their span without a stat per record.  None = unknown
        #: (not yet acquired, or a failed write left the tail unknowable —
        #: spans stop being registered until re-acquire, which only costs
        #: evictability of post-incident blocks, never correctness).
        self._append_off: int | None = None
        self._read_fd: int | None = None
        #: Read-fd lifecycle guard for the staged node (node/pipeline.py):
        #: ``read_body`` preads can run on the event-loop thread while the
        #: store-writer lane rewrites/compacts/prunes on its worker — the
        #: pread itself is seek-free and page-cache-safe, but open/close of
        #: the shared read fd must not race a read in flight (a close
        #: between the ``is None`` check and the pread would pread a dead —
        #: or worse, recycled — descriptor).
        self._fd_lock = threading.Lock()

    # -- file-layer seams (FaultStore overrides these) --------------------
    #
    # The no-arg seams are the historical single-file surface; each
    # routes through a ``*_path`` seam taking an explicit path so the
    # segmented store (chain/segstore.py), whose appends land in
    # per-segment files, injects through the SAME fault plane — one
    # FaultStore shim covers both layouts.

    def _open_fh(self):
        return self._open_fh_path(self.path)

    def _open_fh_path(self, path):
        return open(path, "a+b")  # "a": every write appends

    def _fsync_file(self, fh) -> None:
        os.fsync(fh.fileno())

    def _fsync_dir(self) -> None:
        self._fsync_dir_path(self.path.parent)

    def _fsync_dir_path(self, path) -> None:
        fsync_dir(path)

    def _read_bytes(self) -> bytes:
        return self._read_bytes_path(self.path)

    def _read_bytes_path(self, path) -> bytes:
        return Path(path).read_bytes()

    def _pread(self, fd: int, n: int, off: int) -> bytes:
        """The body-refetch read seam (``read_body``/``iter_blocks``):
        per-call so the fault harness can model a sector going EIO
        under a live serve — the segmented store's per-segment
        degradation case."""
        return os.pread(fd, n, off)

    # -- writer lifecycle -------------------------------------------------

    def acquire(self, allow_v2: bool = False, heal: bool = True) -> None:
        """Open + exclusively lock the store for this writer's lifetime
        (idempotent; released by ``close``).  Raises RuntimeError when
        another process holds the lock — two nodes appending to one store,
        or a compaction racing a live node, would corrupt or silently
        orphan records.

        Lock ordering matters: the torn-tail truncation and the
        corruption heal run strictly UNDER the lock, so a refused second
        writer can never mutate a live writer's in-flight record on its
        way to the refusal.

        ``allow_v2`` admits a pre-checksum v2 store (read/maintenance
        tooling: ``p1 compact`` / ``p1 fsck`` lock before rewriting);
        plain writers refuse v2 with an upgrade hint — appending
        unchecksummed records forever would defeat the containment.
        ``heal=False`` locks and scans without mutating (``p1 fsck``'s
        report pass owns its own salvage decisions)."""
        if self._fh is not None:
            return
        self.path.parent.mkdir(parents=True, exist_ok=True)
        # At most one rebuild round: the first pass may replace the file
        # (quarantine heal), after which the fresh inode is re-locked and
        # re-verified; a clean store locks on the first pass.
        for attempt in (0, 1):
            fh = self._open_fh()
            try:
                fcntl.flock(fh, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError as e:
                fh.close()
                raise RuntimeError(
                    f"{self.path} is locked by another process (a running node?)"
                ) from e
            try:
                if self.path.stat().st_size == 0:
                    fh.write(MAGIC)
                    fh.flush()
                    break
                data = self._read_bytes()
                scan = self.scan(data)
                self.last_scan = scan
                if scan.version == 2 and not allow_v2:
                    raise ValueError(
                        f"{self.path}: v2 chain store (records carry no "
                        "checksums) — run `p1 fsck` or `p1 compact` to "
                        "upgrade before writing"
                    )
                if not heal or scan.clean:
                    break
                if scan.bad_spans:
                    if attempt == 0:
                        # Mid-log corruption: quarantine + rebuild
                        # replaces the inode, so loop to re-lock and
                        # re-verify it.
                        self._heal_rebuild(data, scan)
                        fh.close()
                        continue
                    # The rebuild wrote only checksum-valid records, so
                    # corruption surviving the re-verify means the medium
                    # itself is lying (persistent read fault, bytes
                    # re-corrupting under us).  Refuse the writer rather
                    # than silently append behind unhealed damage.
                    raise ValueError(
                        f"{self.path}: {len(scan.bad_spans)} corrupt "
                        "span(s) persist after heal — refusing writer; "
                        "run `p1 fsck`"
                    )
                if scan.torn_tail is not None:
                    # Drop the truncated tail record (crash mid-append)
                    # before writing behind it, or its stale length
                    # prefix would point into the new records and corrupt
                    # the whole log.
                    self.healed["truncated_bytes"] += len(data) - scan.torn_tail
                    os.truncate(self.path, scan.torn_tail)
                break
            except ValueError as e:
                # e.g. "not a chain store": release the lock + handle
                # instead of leaking an exclusively-flocked fd, and
                # surface the same clean error type as the lock conflict.
                fh.close()
                raise RuntimeError(str(e)) from e
        self._fh = fh
        try:
            self._append_off = self.path.stat().st_size
        except OSError:
            self._append_off = None

    def _heal_rebuild(self, data: bytes, scan: StoreScan) -> None:
        """Quarantine ``scan.bad_spans`` to the sidecar, then atomically
        rewrite the store as magic + every valid record (and NO torn
        tail).  Sidecar first, fsynced: the evidence must be durable
        before the original bytes stop existing.  The rebuild goes
        through tmp + rename + directory fsync, so a crash at any point
        leaves either the old corrupt file (re-healed next start) or the
        complete new one — never a half-rebuilt log."""
        qpath = self.quarantine_path()
        with open(qpath, "ab") as qf:
            for s, e in scan.bad_spans:
                qf.write(_QREC.pack(s, e - s))
                qf.write(data[s:e])
            qf.flush()
            os.fsync(qf.fileno())
        tmp = self.path.with_name(f"{self.path.name}.heal.{os.getpid()}")
        with open(tmp, "wb") as out:
            out.write(MAGIC)
            for off, n in scan.spans:
                out.write(data[off - _LEN.size : off + n + _CRC.size])
            out.flush()
            os.fsync(out.fileno())
        os.replace(tmp, self.path)
        self._fsync_dir()
        self.healed["quarantined_records"] += len(scan.bad_spans)
        self.healed["quarantined_bytes"] += scan.quarantined_bytes
        if scan.torn_tail is not None:
            self.healed["truncated_bytes"] += scan.size - scan.torn_tail

    def quarantine_path(self) -> Path:
        return self.path.with_name(self.path.name + ".quarantine")

    def append(self, block: Block, height: int | None = None) -> None:
        """Append one record.  ``height`` is an optional hint for
        layouts that track height spans (the segmented store's
        manifest); the single-file log ignores it."""
        self.acquire()
        if self.last_scan is not None and self.last_scan.version == 2:
            # allow_v2 admits readers and rewriters, never appenders: a
            # v3 CRC-trailed record in a v2-magic file reads back with
            # the trailer as the NEXT record's length prefix, silently
            # desyncing the whole log's framing.
            raise ValueError(
                f"{self.path}: cannot append to a v2 chain store — "
                "rewrite it as v3 first (`p1 fsck` or `p1 compact`)"
            )
        # ``serialize`` is memoized on the block: for a block that arrived
        # off the wire these are the exact gossip bytes — ingest appends
        # with zero re-packing (docs/PERF.md "host ingest plane").
        raw = block.serialize()
        if len(raw) > _MAX_RECORD:
            # The scan rejects bigger length fields as corruption, so a
            # record this size would be unreadable the moment it landed.
            raise ValueError(
                f"block serializes to {len(raw)} bytes, over the "
                f"{_MAX_RECORD}-byte record limit"
            )
        prefix = _LEN.pack(len(raw))
        crc = zlib.crc32(raw, zlib.crc32(prefix))
        # One write per record: a torn append (crash, ENOSPC mid-write)
        # can tear at most THIS record, never desync an earlier one.
        try:
            self._fh.write(prefix + raw + _CRC.pack(crc))
            self._fh.flush()
        except OSError:
            # The tail may now hold a partial record, so the next append's
            # offset is unknowable without a rescan: stop registering
            # spans (post-incident blocks just stay unevictable until the
            # next acquire re-derives clean framing).
            self._append_off = None
            raise
        if self._append_off is not None:
            self._body_spans[block.block_hash()] = (
                (self._append_off + _LEN.size) << _SPAN_SHIFT
            ) | len(raw)
            self._append_off += _LEN.size + len(raw) + _CRC.size
        if self.fsync:
            self._fsync_file(self._fh)

    def sync(self) -> None:
        """Flush + fsync now — the batch closer for callers that toggle
        ``fsync`` off around a bulk append run (e.g. a node persisting a
        whole BLOCKS resync batch pays one fsync per frame, not per
        block; every batched block is re-fetchable from peers if the OS
        eats the window)."""
        if self._fh is not None:
            self._fh.flush()
            self._fsync_file(self._fh)

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        self._append_off = None
        with self._fd_lock:
            if self._read_fd is not None:
                os.close(self._read_fd)
                self._read_fd = None

    # -- the framing walk -------------------------------------------------

    @staticmethod
    def _check_magic(data: bytes, label: str = "") -> None:
        prefix = f"{label} " if label else ""
        if not data.startswith(MAGIC) and not data.startswith(V2_MAGIC):
            if any(data.startswith(m) for m in _OLD_MAGICS):
                raise ValueError(
                    f"{prefix}written by an older p1-tpu version "
                    "(incompatible transaction format); re-mine or discard it"
                )
            raise ValueError(f"{prefix}not a chain store")

    @staticmethod
    def _v3_record_at(data: bytes, off: int) -> int | None:
        """End offset of a checksum-valid v3 record starting at ``off``,
        or None (incomplete frame / checksum mismatch)."""
        if off + _LEN.size + _CRC.size > len(data):
            return None
        (n,) = _LEN.unpack_from(data, off)
        if n > _MAX_RECORD:
            return None
        end = off + _LEN.size + n + _CRC.size
        if end > len(data):
            return None
        body_end = end - _CRC.size
        if zlib.crc32(data[off:body_end]) != _CRC.unpack_from(data, body_end)[0]:
            return None
        return end

    @classmethod
    def _resync(cls, data: bytes, start: int) -> int | None:
        """First offset >= ``start`` where a checksum-valid record begins
        — how the scan recovers framing past a corrupt span.  A false
        positive needs a 32-bit CRC collision at a byte offset whose
        length field also happens to land exactly inside the file
        (~2^-32 per candidate): negligible against whole-log loss.
        Candidates whose length field exceeds ``_MAX_RECORD`` (or whose
        frame overruns the file) are rejected before any checksumming,
        so the walk's cost is dominated by the cheap 4-byte reads, not
        by CRCs over garbage."""
        for cand in range(start, len(data) - (_LEN.size + _CRC.size) + 1):
            if cls._v3_record_at(data, cand) is not None:
                return cand
        return None

    @classmethod
    def scan(cls, data: bytes) -> StoreScan:
        """The ONE walk of the framing, shared by the writer's heal, the
        batch parse, the packed-header extraction, and ``p1 fsck`` — so
        none of them can drift."""
        cls._check_magic(data)
        if data.startswith(V2_MAGIC):
            # Pre-checksum framing: whole records up to the first one the
            # file ends inside.  Corruption is UNDETECTABLE here (that is
            # what v3 fixes); a bad length prefix reads as a torn tail.
            spans: list[tuple[int, int]] = []
            off = len(V2_MAGIC)
            while off + _LEN.size <= len(data):
                (n,) = _LEN.unpack_from(data, off)
                if off + _LEN.size + n > len(data):
                    break
                spans.append((off + _LEN.size, n))
                off += _LEN.size + n
            return StoreScan(
                version=2,
                spans=spans,
                bad_spans=[],
                torn_tail=off if off < len(data) else None,
                size=len(data),
            )
        spans = []
        bad: list[tuple[int, int]] = []
        torn: int | None = None
        off = len(MAGIC)
        while off < len(data):
            end = cls._v3_record_at(data, off)
            if end is not None:
                spans.append((off + _LEN.size, end - off - _LEN.size - _CRC.size))
                off = end
                continue
            nxt = cls._resync(data, off + 1)
            if nxt is not None:
                bad.append((off, nxt))
                off = nxt
                continue
            # Nothing checksum-valid ahead.  A fully-present frame that
            # failed its CRC is trailing corruption (quarantinable);
            # anything the file ends inside is a torn tail.
            if off + _LEN.size <= len(data):
                (n,) = _LEN.unpack_from(data, off)
                end = off + _LEN.size + n + _CRC.size
                if end <= len(data):
                    bad.append((off, end))
                    if end < len(data):
                        torn = end
                    break
            torn = off
            break
        return StoreScan(
            version=3, spans=spans, bad_spans=bad, torn_tail=torn, size=len(data)
        )

    @classmethod
    def _record_spans(cls, data: bytes) -> Iterator[tuple[int, int]]:
        """(offset, length) of every checksum-valid record's block bytes.
        Skips quarantinable spans and stops cleanly at a torn tail."""
        yield from cls.scan(data).spans

    # -- readers ----------------------------------------------------------

    def _read_checked(self) -> bytes:
        data = self._read_bytes()
        self._check_magic(data, str(self.path))
        return data

    def load_blocks(self) -> list[Block]:
        """All decodable records: checksum-valid (v3), stopping cleanly at
        a truncated tail, SKIPPING — not trusting — corrupt spans.

        Batch parse on the packed-bytes plane: each ``Block.deserialize``
        seeds the block's (and its header's and transactions') encoding
        caches with the record's exact bytes, so resume never re-packs —
        ``add_block``'s hashing, the ledger's txids, and any later relay
        all reuse the disk bytes (docs/PERF.md "Restart at scale")."""
        return list(self.iter_blocks())

    def iter_blocks(self):
        """Streaming form of ``load_blocks``: one block at a time, never
        the whole object list at once — what memory-bounded resume
        iterates (``load_chain(..., body_cache=N)`` evicts as it goes, so
        peak RSS is bounded by the keep window, not the chain length).
        Registers each record's span in the body index as a side effect.

        The whole-file buffer is needed for the checksum walk but is
        RELEASED before the object build: records are re-read per span
        (pread against the page cache the scan just warmed), so the
        build phase — where the per-block index objects accumulate —
        never also carries an O(chain) byte buffer.  At 100k blocks that
        is ~24 MB off the resume's peak RSS (docs/PERF.md
        "Memory-bounded operation")."""
        if not self.path.exists():
            return
        data = self._read_checked()
        spans = list(self._record_spans(data))
        del data
        with self._fd_lock:
            if self._read_fd is None:
                self._read_fd = os.open(self.path, os.O_RDONLY)
        for off, n in spans:
            raw = self._pread(self._read_fd, n, off)
            if len(raw) != n:
                raise OSError(f"{self.path}: short record read at {off}")
            block = Block.deserialize(raw)
            self._body_spans[block.block_hash()] = (off << _SPAN_SHIFT) | n
            yield block

    def first_difficulty(self) -> int | None:
        """The difficulty the first stored record declares (every block
        carries the chain difficulty), or None for an empty store —
        the streaming-resume path's pre-check, which must not
        materialize the block list just to read one header field."""
        header = self.first_header()
        return None if header is None else header.difficulty

    def first_header(self) -> BlockHeader | None:
        """The first stored record's header (None for an empty store).
        The snapshot-resume path's linkage probe: a store whose first
        record is (or extends) genesis resumes normally, one whose
        records hang off a snapshot anchor needs the sidecar
        (node/node.py ``_try_snapshot_resume``)."""
        if not self.path.exists():
            return None
        data = self._read_checked()
        for off, _ in self._record_spans(data):
            return BlockHeader.deserialize(data[off : off + HEADER_SIZE])
        return None

    def reindex_spans(self) -> int:
        """Rebuild the body-span index from the CURRENT file contents —
        required after an in-place rewrite replaced the inode under the
        held writer lock (the snapshot plane's flip transition): the old
        spans point into a dead inode, and serving a refetch from them
        would be an offset lottery.  Block hashes come straight from the
        80-byte header slices (block id = header SHA-256d), so the
        rebuild costs no full-record parses."""
        from p1_tpu.core.hashutil import sha256d

        self._body_spans.clear()
        with self._fd_lock:
            if self._read_fd is not None:
                os.close(self._read_fd)  # points at the replaced inode
                self._read_fd = None
        if not self.path.exists():
            return 0
        data = self._read_checked()
        for off, n in self._record_spans(data):
            bhash = sha256d(data[off : off + HEADER_SIZE])
            self._body_spans[bhash] = (off << _SPAN_SHIFT) | n
        return len(self._body_spans)

    # -- body refetch (memory-bounded operation) ---------------------------

    def has_body(self, block_hash: bytes) -> bool:
        """True when ``read_body`` can re-serve this block — the chain's
        eviction gate: only durably refetchable bodies leave RAM."""
        return block_hash in self._body_spans

    def read_body(self, block_hash: bytes) -> Block:
        """Re-read one block straight from its record span (pread — no
        shared seek state with the appender; the writer flushes every
        record, so the bytes are page-cache-visible the moment the span
        exists).  The deserialize seeds the block's encoding caches with
        the disk bytes, so a refetched body re-serves/re-hashes at the
        zero-repack rate; the hash check pins the span map itself —
        a mismatch is a store-layer bug, not peer input, so it raises."""
        span = self._body_spans[block_hash]
        off, n = span >> _SPAN_SHIFT, span & ((1 << _SPAN_SHIFT) - 1)
        with self._fd_lock:
            if self._read_fd is None:
                self._read_fd = os.open(self.path, os.O_RDONLY)
            raw = self._pread(self._read_fd, n, off)
        if len(raw) != n:
            raise OSError(f"{self.path}: short body read at {off}")
        block = Block.deserialize(raw)
        if block.block_hash() != block_hash:
            raise ValueError(
                f"{self.path}: body span for {block_hash.hex()[:16]} "
                "re-read as a different block"
            )
        return block

    def packed_headers(self) -> tuple[bytes, int]:
        """(buffer, count): every record's 80-byte header, contiguous, cut
        straight from the record framing with NO object parse — the exact
        shape ``replay_packed``/the native verifier take in one ctypes
        call.  For a linear store (a ``save_chain`` snapshot or compacted
        log — main branch only, append order = height order) this is the
        whole-chain PoW + linkage check at the raw-bytes rate; stores
        carrying side branches fail linkage at the first out-of-line
        record, by construction."""
        if not self.path.exists():
            return b"", 0
        data = self._read_checked()
        parts = [
            data[off : off + HEADER_SIZE] for off, _ in self._record_spans(data)
        ]
        return b"".join(parts), len(parts)

    def load_chain(
        self,
        difficulty: int,
        blocks: list[Block] | None = None,
        retarget=None,
        trusted: bool = False,
        body_cache: int = 0,
        sig_cache=None,
        orphans_ok: bool = False,
    ) -> Chain:
        """Rebuild a validated chain from the log (skipping the genesis
        record, which the Chain constructor provides).  Pass ``blocks``
        when the caller already ran ``load_blocks`` (avoids a second full
        read+parse of the log), and the store's ``RetargetRule`` if the
        chain was mined with one (the rule is part of chain identity).

        ``trusted=True`` is the fast-resume path for a node reloading its
        OWN store: every record was fully validated by this node before
        it was appended (and the store is exclusively flocked, so nothing
        else wrote it), so the stateless checks — Ed25519 signatures
        above all — are skipped while the contextual rules and the
        connect-time ledger still rebuild identical state (measured ~3x
        end-to-end at 100k blocks — 4.6 s vs 14.0 s, docs/PERF.md;
        equivalence is tested).  The v3 record checksum bounds what
        trust costs: bit-rot inside a record body now fails the CRC and
        the record is quarantined at ``acquire`` rather than trusted
        through — ``p1 node --revalidate-store`` remains the remedy for
        corruption *with* a fixed-up checksum (i.e. a hostile editor,
        not a disk).

        Raises ValueError when records exist but NONE connect — that is a
        store from a chain with different parameters (wrong difficulty /
        retarget flags), and proceeding would be catastrophic for some
        callers (``p1 compact`` would rewrite the store as a genesis-only
        snapshot of the wrong chain).  The guard lives here, once, so no
        call site can forget it; a partially-connecting store (corrupt
        tail) still loads what it can.

        ``orphans_ok`` relaxes that guard for callers that can BACKFILL:
        when this acquire's heal quarantined the head of the log, the
        surviving records legitimately hang off a missing ancestor —
        they park in the chain's orphan pool and reconnect the moment a
        peer re-serves the gap.  The chaos sweeps found the hard guard
        bricking exactly that recovery (a node refusing to boot off its
        own healed store over one rotted head record, with the whole
        suffix intact and the mesh holding every missing block); a NODE
        passes ``orphans_ok`` when its store healed, while tooling
        (``p1 compact``) keeps the refusal — compacting an unanchored
        store would discard records a sync could still save.

        Resume operates on the packed-bytes plane end to end: the batch
        parse (``load_blocks``) seeds every block's encoding caches from
        the record bytes, so the per-block hashing that ``add_block`` and
        the ledger need digests the disk bytes directly — no
        re-serialization anywhere in the resume loop (measured in
        benchmarks/host_ingest.py, recorded in docs/PERF.md).

        ``body_cache=N`` (memory-bounded resume) wires the chain's body
        refetch to THIS store and streams the log through periodic body
        eviction, so peak RSS is bounded by the keep window instead of
        the whole chain's object graph — the governor's memory-bounded
        operation starts at boot, not after it (docs/PERF.md
        "Memory-bounded operation").

        Untrusted loads (``trusted=False`` — `--revalidate-store`,
        foreign stores) run through the validation fast lane: blocks
        stream through a signature pre-verification window
        (chain/validate.py ``preverify_signatures``) that proves whole
        batches of Ed25519 signatures into the chain's verify-once
        cache before ``add_block``'s per-block ``check_block`` consults
        it — one batch call per ~4k signatures instead of one backend
        call per transfer, with bit-identical accept/reject outcomes
        (the warmer only ever caches proofs that hold; docs/PERF.md
        "Untrusted-path validation")."""
        chain = Chain(difficulty, retarget=retarget)
        if body_cache > 0:
            chain.body_source = self
        if sig_cache is not None:
            chain.sig_cache = sig_cache
        ghash = chain.genesis.block_hash()
        saw_record = False
        if blocks is None:
            blocks = self.iter_blocks() if body_cache > 0 else self.load_blocks()
        if not trusted:
            blocks = _preverify_stream(
                blocks, chain.genesis.block_hash(), chain.sig_cache
            )
        seen = 0
        for block in blocks:
            if block.block_hash() == ghash:
                continue
            saw_record = True
            chain.add_block(block, trusted=trusted)
            seen += 1
            if body_cache > 0 and seen % 1024 == 0:
                chain.evict_bodies(body_cache)
        if body_cache > 0:
            chain.evict_bodies(body_cache)
        if saw_record and not chain.height and not orphans_ok:
            raise ValueError(
                f"{self.path}: records do not connect to this chain's "
                "genesis — wrong --difficulty or "
                "--retarget-window/--target-spacing for this store?"
            )
        return chain


def _preverify_stream(blocks, chain_tag: bytes, sig_cache):
    """Stream ``blocks`` through windowed signature pre-verification.

    Buffers blocks until ~PREVERIFY_WINDOW transfer signatures are
    pending, proves them into ``sig_cache`` with one batch call, then
    yields the buffered blocks onward — so the untrusted resume loop
    stays a stream (memory O(window), compatible with ``body_cache``
    eviction) while its Ed25519 cost drops to the batch rate.  Purely an
    accelerator: outcomes are identical whether or not a block ever
    passed through here (preverify_signatures's contract).
    """
    from p1_tpu.chain.validate import PREVERIFY_WINDOW, preverify_signatures

    window: list[Block] = []
    pending_sigs = 0
    for block in blocks:
        window.append(block)
        pending_sigs += sum(1 for tx in block.txs if not tx.is_coinbase)
        # The block-count bound keeps a sparse-transfer store's window
        # from buffering unboundedly many blocks ahead of a streaming
        # (body_cache) resume; the sig bound is the batching target.
        if pending_sigs >= PREVERIFY_WINDOW or len(window) >= PREVERIFY_WINDOW:
            preverify_signatures(
                (tx for blk in window for tx in blk.txs), chain_tag, sig_cache
            )
            yield from window
            window.clear()
            pending_sigs = 0
    if window:
        preverify_signatures(
            (tx for blk in window for tx in blk.txs), chain_tag, sig_cache
        )
        yield from window


def save_chain(
    chain: Chain, path: str | os.PathLike, store_cls: type[ChainStore] = ChainStore
) -> None:
    """Snapshot a chain's main branch to a fresh store (tooling aid; nodes
    normally append incrementally as blocks arrive).  The snapshot is
    LINEAR by construction — genesis-first main branch — so its
    ``packed_headers`` buffer verifies in one native call
    (``replay_packed``), which is how ``p1 compact`` proves a snapshot
    before replacing the original log.

    Durability: one data fsync at the end (bulk snapshot; the source
    chain still exists in memory if the write is lost), then a PARENT
    DIRECTORY fsync — a freshly created file whose directory entry only
    lives in an uncommitted metadata journal vanishes wholesale on power
    loss, data fsync or not.  ``store_cls`` is the fault-injection seam
    (tests pass ``FaultStore``)."""
    p = Path(path)
    if p.exists():
        p.unlink()
    store = store_cls(p, fsync=False)
    try:
        for block in chain.main_chain():
            store.append(block)
        store.sync()
        store._fsync_dir()
    finally:
        store.close()
