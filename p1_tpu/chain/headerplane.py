"""The spillable header plane: per-segment packed-header indexes and
the archive-scale serve-only boot (round 18).

The in-RAM header index is the last O(chain) structure that matters at
archive scale: ~143 MB at 100k blocks is ~14 GB at 10M.  This module
makes chain length a *disk* problem for the serving path:

- ``write_segment_index`` distills one sealed segment into a ``.hdrx``
  sidecar: every record's 80-byte header (PR 1's packed-headers shape —
  contiguous, parse-free, the exact buffer ``replay_packed`` verifies),
  the record's (offset, length) span, a sorted block-hash index, and a
  sorted txid index.  Everything is derivable from the segment, so the
  sidecar is a cache that can always be rebuilt — and it survives
  pruning, which is what keeps a pruned store's header chain whole.
- ``SegmentIndex`` probes one sidecar via ``pread`` (O(log n) reads
  per lookup; a blocked bloom filter makes txid negatives one 64-byte
  read) — untouched history stays in the page cache, not this
  process's RSS, so memory is bounded by the query working set, not
  the chain length.
- ``ArchiveChain`` is the serve-only composition: ledger state from a
  PR 9 snapshot (``Chain.from_snapshot`` — the bounded hot window of
  real ``_Entry`` headers), cold headers/proof lookups from the
  on-disk plane below the base.  A synthetic 10M-block store boots to
  serving header/balance/proof queries under 1 GB peak RSS
  (benchmarks/archive_scale.py measures VmHWM).

Ordinal == height: the plane assumes a LINEAR store (compacted /
synthetic / pruned-serve archives — main branch only, append order =
height order), checked at attach by linking each segment's first
header to its predecessor's last.  A node's live log with side
branches is not a plane candidate; its resume path is unchanged.
"""

from __future__ import annotations

import os
import struct
import zlib
from pathlib import Path

from p1_tpu.chain.store import ChainStore, fsync_dir
from p1_tpu.core.hashutil import sha256d
from p1_tpu.core.header import HEADER_SIZE

HDRX_MAGIC = b"P1TPUHX1"
_U32 = struct.Struct(">I")
_SPAN = struct.Struct(">QI")  # record payload (offset, length)
_IDX = struct.Struct(">32sI")  # (hash, ordinal), sorted by hash

#: Blocked bloom filter over the txid set, ~10 bits/key in 64-byte
#: blocks with all k probe bits INSIDE one block: a negative costs ONE
#: page touch.  Without it, a cold-proof lookup binary-searched every
#: segment's txid index — ~17 scattered page touches per segment per
#: query, which at 10M blocks residented hundreds of MB of index pages
#: and broke the <1 GB boot bar (the measured failure this structure
#: exists for).  Txids are sha256d outputs, so the txid's own bytes
#: are the hash material.
_BLOOM_BLOCK = 64
_BLOOM_BITS_PER_KEY = 10
_BLOOM_K = 6


def _bloom_probe(txid: bytes, n_blocks: int):
    """(block index, bit offsets within the 512-bit block)."""
    block = int.from_bytes(txid[:8], "big") % n_blocks
    word = int.from_bytes(txid[8:16], "big")
    return block, [(word >> (9 * i)) & 511 for i in range(_BLOOM_K)]


def _bloom_build(txids, count: int) -> bytes:
    n_blocks = max(1, (count * _BLOOM_BITS_PER_KEY + 511) // 512)
    buf = bytearray(n_blocks * _BLOOM_BLOCK)
    for txid in txids:
        block, bits = _bloom_probe(txid, n_blocks)
        base = block * _BLOOM_BLOCK
        for b in bits:
            buf[base + (b >> 3)] |= 1 << (b & 7)
    return bytes(buf)


def write_segment_index(segment_data: bytes, out_path) -> int:
    """Distill ``segment_data`` (one v3 segment file's bytes) into the
    ``.hdrx`` sidecar at ``out_path`` (tmp + rename + dir-fsync — the
    sidecar appears atomically or not at all).  Returns record count.

    Layout after the magic: u32 count | u32 ntx | count×80 B headers
    (record order) | count×(u64 off, u32 len) spans | count×(32s, u32)
    sorted hash index | ntx×(32s, u32) sorted txid index | u32 CRC32
    over everything after the magic."""
    out_path = Path(out_path)
    spans = ChainStore.scan(segment_data).spans
    headers: list[bytes] = []
    span_rows: list[bytes] = []
    hash_rows: list[tuple[bytes, int]] = []
    tx_rows: list[tuple[bytes, int]] = []
    for ordinal, (off, n) in enumerate(spans):
        hdr = segment_data[off : off + HEADER_SIZE]
        headers.append(hdr)
        span_rows.append(_SPAN.pack(off, n))
        hash_rows.append((sha256d(hdr), ordinal))
        # Raw txid walk (no object parse), the queryplane technique.
        end = off + n
        pos = off + HEADER_SIZE
        if pos + 4 > end:
            continue
        (ntx,) = _U32.unpack_from(segment_data, pos)
        pos += 4
        for _ in range(ntx):
            if pos + 4 > end:
                break
            (tlen,) = _U32.unpack_from(segment_data, pos)
            pos += 4
            if pos + tlen > end:
                break
            tx_rows.append((sha256d(segment_data[pos : pos + tlen]), ordinal))
            pos += tlen
    hash_rows.sort()
    tx_rows.sort()
    bloom = _bloom_build((t for t, _ in tx_rows), max(len(tx_rows), 1))
    body = b"".join(
        (
            _U32.pack(len(headers)),
            _U32.pack(len(tx_rows)),
            *headers,
            *span_rows,
            *(_IDX.pack(h, o) for h, o in hash_rows),
            *(_IDX.pack(t, o) for t, o in tx_rows),
            _U32.pack(len(bloom) // _BLOOM_BLOCK),
            bloom,
        )
    )
    tmp = out_path.with_name(f"{out_path.name}.tmp.{os.getpid()}")
    with open(tmp, "wb") as f:
        f.write(HDRX_MAGIC)
        f.write(body)
        f.write(_U32.pack(zlib.crc32(body)))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, out_path)
    fsync_dir(out_path.parent)
    return len(headers)


class SegmentIndex:
    """One ``.hdrx`` sidecar, probed via ``pread`` — deliberately NOT
    memory-mapped: random faults on a file mapping drag fault-around
    clusters (~16 pages per touch, regardless of MADV_RANDOM) into
    process RSS, which at 10M blocks residented most of a GB of
    never-used neighbor pages and broke the boot bar.  ``pread`` copies
    the handful of bytes a probe needs and leaves residency to the
    page cache, where the kernel — not this process's VmHWM — owns it.
    All lookups are O(log n) reads; nothing is materialized into
    Python objects until asked."""

    def __init__(self, path, verify: bool = True):
        self.path = Path(path)
        self._fd = os.open(self.path, os.O_RDONLY)
        try:
            size = os.fstat(self._fd).st_size
            head = os.pread(self._fd, 16, 0)
        except OSError:
            os.close(self._fd)
            self._fd = None
            raise
        if head[: len(HDRX_MAGIC)] != HDRX_MAGIC:
            self.close()
            raise ValueError(f"{self.path}: not a header-plane index")
        if verify:
            # Whole-file CRC: O(file) — right for fsck and one-shot
            # readers; the archive attach passes verify=False and
            # relies on the structural checks below plus the optional
            # whole-plane PoW replay (``ArchiveChain.verify_headers``).
            data = os.pread(self._fd, size, 0)
            body = data[len(HDRX_MAGIC) : size - _U32.size]
            if zlib.crc32(body) != _U32.unpack_from(data, size - _U32.size)[0]:
                self.close()
                raise ValueError(
                    f"{self.path}: header-plane index CRC mismatch"
                )
        off = len(HDRX_MAGIC)
        if len(head) < off + 8:
            self.close()
            raise ValueError(f"{self.path}: header-plane index truncated")
        (self.count,) = _U32.unpack_from(head, off)
        (self.tx_count,) = _U32.unpack_from(head, off + 4)
        self._hdr0 = off + 8
        self._span0 = self._hdr0 + self.count * HEADER_SIZE
        self._hash0 = self._span0 + self.count * _SPAN.size
        self._tx0 = self._hash0 + self.count * _IDX.size
        bloom_len = self._tx0 + self.tx_count * _IDX.size
        bl = os.pread(self._fd, _U32.size, bloom_len)
        if len(bl) < _U32.size:
            self.close()
            raise ValueError(f"{self.path}: header-plane index truncated")
        (self._bloom_blocks,) = _U32.unpack(bl)
        self._bloom0 = bloom_len + _U32.size
        expect = (
            self._bloom0 + self._bloom_blocks * _BLOOM_BLOCK + _U32.size
        )
        if expect != size:
            self.close()
            raise ValueError(f"{self.path}: header-plane index truncated")

    def close(self) -> None:
        if getattr(self, "_fd", None) is not None:
            os.close(self._fd)
            self._fd = None

    def _read(self, off: int, n: int) -> bytes:
        return os.pread(self._fd, n, off)

    def header_at(self, ordinal: int) -> bytes:
        return self._read(self._hdr0 + ordinal * HEADER_SIZE, HEADER_SIZE)

    def headers_blob(self) -> bytes:
        return self._read(self._hdr0, self._span0 - self._hdr0)

    def record_span(self, ordinal: int) -> tuple[int, int]:
        """(payload offset, length) of the ordinal-th record in the
        segment FILE (valid while the body segment still exists)."""
        return _SPAN.unpack(
            self._read(self._span0 + ordinal * _SPAN.size, _SPAN.size)
        )

    def _bisect(self, base: int, n: int, key: bytes) -> int | None:
        lo, hi = 0, n
        while lo < hi:
            mid = (lo + hi) // 2
            row = self._read(base + mid * _IDX.size, _IDX.size)
            cand = row[:32]
            if cand < key:
                lo = mid + 1
            elif cand > key:
                hi = mid
            else:
                return _U32.unpack_from(row, 32)[0]
        return None

    def maybe_txid(self, txid: bytes) -> bool:
        """Bloom probe: False means DEFINITELY absent (one 64-byte
        read); True means fall through to the binary search (~1% of
        misses)."""
        if self._bloom_blocks == 0:
            return True
        block, bits = _bloom_probe(txid, self._bloom_blocks)
        blob = self._read(self._bloom0 + block * _BLOOM_BLOCK, _BLOOM_BLOCK)
        for b in bits:
            if not blob[b >> 3] & (1 << (b & 7)):
                return False
        return True

    def find_hash(self, block_hash: bytes) -> int | None:
        """Ordinal of the record whose header hashes to ``block_hash``,
        or None."""
        return self._bisect(self._hash0, self.count, block_hash)

    def find_txid(self, txid: bytes) -> int | None:
        """Ordinal of the (first) record containing ``txid``, or None."""
        if not self.maybe_txid(txid):
            return None
        return self._bisect(self._tx0, self.tx_count, txid)


class HeaderPlane:
    """Ordered segment indexes with cumulative ordinal bases — the
    whole cold region's header surface.  For a linear store ordinal IS
    height, so ``header_at_height`` is two integer compares and one
    80-byte pread."""

    def __init__(self, indexes: list[SegmentIndex]):
        self.indexes = indexes
        self.bases: list[int] = []
        total = 0
        for idx in indexes:
            self.bases.append(total)
            total += idx.count
        self.count = total

    def close(self) -> None:
        for idx in self.indexes:
            idx.close()

    def _locate(self, ordinal: int) -> tuple[SegmentIndex, int] | None:
        if not 0 <= ordinal < self.count:
            return None
        lo, hi = 0, len(self.indexes) - 1
        while lo < hi:
            mid = (lo + hi + 1) // 2
            if self.bases[mid] <= ordinal:
                lo = mid
            else:
                hi = mid - 1
        return self.indexes[lo], ordinal - self.bases[lo]

    def header_at(self, ordinal: int) -> bytes | None:
        loc = self._locate(ordinal)
        return None if loc is None else loc[0].header_at(loc[1])

    def hash_at(self, ordinal: int) -> bytes | None:
        hdr = self.header_at(ordinal)
        return None if hdr is None else sha256d(hdr)

    def find_txid(self, txid: bytes) -> tuple[int, SegmentIndex, int] | None:
        """(global ordinal, owning index, local ordinal) for ``txid``,
        searching newest segments first (recent history is the common
        query)."""
        for i in range(len(self.indexes) - 1, -1, -1):
            local = self.indexes[i].find_txid(txid)
            if local is not None:
                return self.bases[i] + local, self.indexes[i], local
        return None


class ArchiveChain:
    """Serve-only archive boot: a bounded hot ``Chain`` window anchored
    on a snapshot, backed by the header plane for everything below the
    base.  RAM is O(hot window + accounts + touched pages); the 10M
    synthetic store in benchmarks/archive_scale.py is the measured
    proof.

    Trust model: the snapshot passed chain/snapshot.py's integrity
    gates and the store is this host's own (or a verified copy); the
    plane's headers can additionally be PoW-replay-verified in one
    native call (``verify_headers``) — O(chain) time, O(1) RAM."""

    def __init__(self, store, snapshot_path, difficulty: int, retarget=None):
        from p1_tpu.chain.chain import Chain
        from p1_tpu.chain.segstore import SegmentedStore
        from p1_tpu.chain.snapshot import load_snapshot

        if not isinstance(store, SegmentedStore):
            store = SegmentedStore(store)
        self.store = store
        snap = load_snapshot(snapshot_path)
        self.base_height = snap.height
        self.chain = Chain.from_snapshot(difficulty, snap, retarget=retarget)
        self.plane = HeaderPlane(self._open_indexes())
        anchor = self.plane.hash_at(snap.height)
        if anchor is not None and anchor != snap.manifest.block.block_hash():
            raise ValueError(
                f"snapshot anchor at height {snap.height} does not match "
                "the store's header plane — wrong snapshot for this archive"
            )
        self._replay_tail()

    def _open_indexes(self) -> list:
        """A ``SegmentIndex`` per segment, building any missing sidecar
        from the segment bytes (sealed segments only get written once;
        the active tail is indexed in the hot chain, not the plane)."""
        out = []
        prev_last: bytes | None = None
        for seg in self.store._segments_for_read():
            hx = self.store.hdrx_path(seg)
            # The unsealed tail's sidecar goes stale with every append,
            # so it is rebuilt at attach; sealed segments build once.
            if not hx.exists() or not seg.sealed:
                if seg.pruned:
                    raise ValueError(
                        f"{hx}: pruned segment lost its header-plane "
                        "sidecar — the header chain has a hole"
                    )
                write_segment_index(
                    self.store._read_bytes_path(self.store._seg_path(seg)), hx
                )
            idx = SegmentIndex(hx, verify=False)
            if idx.count:
                first = idx.header_at(0)
                if prev_last is not None and first[4:36] != sha256d(prev_last):
                    raise ValueError(
                        f"{hx}: segment does not extend its predecessor — "
                        "archive serving needs a linear (compacted) store"
                    )
                prev_last = idx.header_at(idx.count - 1)
            out.append(idx)
        return out

    def _replay_tail(self) -> None:
        """Connect every record above the snapshot base into the hot
        chain (trusted resume — this host validated them before they
        were persisted).  Ordinal == height on a linear store, so the
        records to replay are exactly ordinals base+1..count-1 plus
        anything in the active (un-indexed) segment."""
        from p1_tpu.core.block import Block

        ordinal = -1  # genesis is record 0 in a linear store
        for i, seg in enumerate(self.store._segments_for_read()):
            count = self.plane.indexes[i].count
            if seg.pruned or ordinal + count <= self.base_height:
                # Wholly below the base (or bodiless): the plane's
                # count stands in for a scan — boot cost is O(tail +
                # segments), never O(chain) bytes.
                ordinal += count
                continue
            data = self.store._read_bytes_path(self.store._seg_path(seg))
            spans = ChainStore.scan(data).spans
            for off, n in spans:
                ordinal += 1
                if ordinal <= self.base_height:
                    continue
                self.chain.add_block(
                    Block.deserialize(data[off : off + n]), trusted=True
                )
            del data

    # -- the query surface -------------------------------------------------

    @property
    def height(self) -> int:
        return self.chain.height

    def header_bytes_at(self, height: int) -> bytes | None:
        """The 80-byte header at ``height`` — plane below the base, hot
        window above."""
        if height > self.base_height:
            bhash = self.chain.main_hash_at(height)
            if bhash is None:
                return None
            return self.chain.header_of(bhash).serialize()
        return self.plane.header_at(height)

    def hash_at(self, height: int) -> bytes | None:
        if height > self.base_height:
            return self.chain.main_hash_at(height)
        return self.plane.hash_at(height)

    def balance(self, account: str) -> int:
        return self.chain.balance(account)

    def nonce(self, account: str) -> int:
        return self.chain.nonce(account)

    def tx_proof(self, txid: bytes):
        """An SPV inclusion proof for ``txid`` — hot window first, then
        the plane's txid index (cold proofs read ONE record back from
        its segment; pruned ranges are not servable, same refusal the
        pruned node mode makes on the wire)."""
        import dataclasses as _dc

        from p1_tpu.chain.proof import build_block_proofs
        from p1_tpu.core.block import Block

        proof = self.chain.tx_proof(txid)
        if proof is not None:
            return proof
        hit = self.plane.find_txid(txid)
        if hit is None:
            return None
        height, idx, local = hit
        seg_name = Path(idx.path).name.replace(".hdrx", ".p1s")
        seg_path = Path(idx.path).with_name(seg_name)
        if not seg_path.exists():
            return None  # pruned body: headers survive, proofs don't
        off, n = idx.record_span(local)
        with open(seg_path, "rb") as f:
            f.seek(off)
            raw = f.read(n)
        block = Block.deserialize(raw)
        template = build_block_proofs(block, height).get(txid)
        if template is None:
            return None
        return _dc.replace(template, tip_height=self.chain.height)

    def verify_headers(self, retarget=None):
        """Whole-archive PoW + linkage proof over the packed plane —
        one native ``replay_packed`` call per segment blob, O(1) RAM."""
        from p1_tpu.chain.replay import replay_packed

        raw, count = self.store.packed_headers()
        return replay_packed(raw, retarget=retarget), count

    def close(self) -> None:
        self.plane.close()
        self.store.close()
