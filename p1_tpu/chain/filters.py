"""Compact block filters: BIP158-style Golomb-coded sets for light clients.

The serving-plane problem this solves (ROADMAP open item 1): an SPV
wallet watching K accounts previously had to ask a node K questions per
block (GETACCOUNT/GETPROOF fan-out), and the node had to answer every
one from the consensus thread.  A compact block filter inverts the
query: the node publishes, per block, a few-bytes-per-transaction
probabilistic digest of *everything the block touches* (txids + sender
and recipient account ids), and the wallet downloads the digest stream
and asks its K questions LOCALLY.  A match means "download this block
and look" (rarely a false positive, bounded below); a non-match is a
**guarantee** the block is irrelevant — the construction has zero false
negatives, which is the property the wallet's correctness rests on and
the one the property tests pin (tests/test_queryplane.py).

Construction (Bitcoin's BIP158, adapted):

- Each item (a byte string) is hashed to a 64-bit value and mapped
  uniformly onto ``[0, N*M)`` where N is the number of distinct items
  and 1/M the designed false-positive rate per queried item.  BIP158
  keys SipHash with the block hash; hashlib has no SipHash, so the map
  here is the first 8 bytes of SHA-256 over ``block_hash[:16] || item``
  — same independence-per-block property (a colliding pair in one
  block's filter is independent of every other block's), built from the
  primitive the codebase already trusts.
- The sorted values are delta-encoded with Golomb-Rice coding at
  parameter P (quotient in unary, P remainder bits).  With M ≈ 1.497 *
  2**P the expected cost is ~(P + 1.5) bits/item — ~2.6 bytes per item
  at the default P=19, i.e. ~8 bytes per transaction vs the hundreds of
  bytes of the transaction itself.
- The filter commits to N (u32 prefix), and matching decodes the
  stream once against the query set — O(filter + K log K), no
  per-query re-decode.

``P``/``M`` are parameters (wire payloads carry only the encoded
bytes; both sides derive P/M from the protocol constants) so the
property tests can run a deliberately lossy filter (small M) and
actually *measure* the false-positive rate against the designed bound
instead of asserting 0 ≈ 0 at the production 1/784931.

Durability note: a filter is a pure function of the block's canonical
bytes, so the append-only block log (chain/store.py) is already its
durable home — what this module adds is the bounded in-RAM
``FilterIndex`` (built incrementally at connect, backfillable for
existing stores, LRU-bounded so it cannot become the next O(chain) RAM
term the governor has to chase) and the codec both the node and the
read replicas (node/queryplane.py) share.
"""

from __future__ import annotations

import hashlib

#: Golomb-Rice remainder bits (BIP158's P) and the designed inverse
#: false-positive rate per queried item (BIP158's M).  M/2**P ≈ 1.497
#: minimizes bits/item for a given rate.
FILTER_P = 19
FILTER_M = 784931

#: How much of the block hash keys the per-block hash map.  16 bytes is
#: plenty of independence; keeping the key short keeps the per-item
#: hash input small.
_KEY_LEN = 16


def filter_items(block) -> set[bytes]:
    """The byte strings a block's filter commits to: every txid and every
    sender/recipient account id (utf-8).  Account ids are what wallets
    watch ("did anything touch my account?"); txids are what tools that
    already know a txid watch ("is my tx confirmed yet?").  A set —
    BIP158 dedups identical elements, and so does the value map below."""
    items: set[bytes] = set()
    for tx in block.txs:
        items.add(tx.txid())
        items.add(tx.sender.encode("utf-8"))
        items.add(tx.recipient.encode("utf-8"))
    return items


def _hash_to_range(key: bytes, item: bytes, f: int) -> int:
    """Map ``item`` uniformly onto [0, f) under the per-block ``key``.

    The multiply-shift map (h * f) >> 64 over a 64-bit hash is BIP158's
    uniform range reduction — unbiased for any f << 2**64, unlike a
    modulo."""
    h = int.from_bytes(
        hashlib.sha256(key + item).digest()[:8], "big"
    )
    return (h * f) >> 64


def _mapped_values(key: bytes, items, n: int, m: int) -> list[int]:
    f = n * m
    return sorted({_hash_to_range(key, it, f) for it in items})


class _BitWriter:
    __slots__ = ("_buf", "_acc", "_nbits")

    def __init__(self):
        self._buf = bytearray()
        self._acc = 0
        self._nbits = 0

    def write(self, value: int, nbits: int) -> None:
        self._acc = (self._acc << nbits) | (value & ((1 << nbits) - 1))
        self._nbits += nbits
        while self._nbits >= 8:
            self._nbits -= 8
            self._buf.append((self._acc >> self._nbits) & 0xFF)
        self._acc &= (1 << self._nbits) - 1

    def unary(self, q: int) -> None:
        # q one-bits then a zero — BIP158's quotient encoding.
        while q >= 32:
            self.write(0xFFFFFFFF, 32)
            q -= 32
        self.write(((1 << q) - 1) << 1, q + 1)

    def done(self) -> bytes:
        if self._nbits:
            self._buf.append((self._acc << (8 - self._nbits)) & 0xFF)
            self._acc = 0
            self._nbits = 0
        return bytes(self._buf)


class _BitReader:
    __slots__ = ("_data", "_pos")

    def __init__(self, data: bytes):
        self._data = data
        self._pos = 0  # bit position

    def read(self, nbits: int) -> int:
        end = self._pos + nbits
        if end > 8 * len(self._data):
            raise ValueError("filter bitstream truncated")
        out = 0
        pos = self._pos
        data = self._data
        while nbits > 0:
            byte = data[pos >> 3]
            avail = 8 - (pos & 7)
            take = min(avail, nbits)
            out = (out << take) | (
                (byte >> (avail - take)) & ((1 << take) - 1)
            )
            pos += take
            nbits -= take
        self._pos = pos
        return out

    def unary(self) -> int:
        q = 0
        while self.read(1):
            q += 1
            if q > 8 * len(self._data):
                raise ValueError("filter unary run exceeds stream")
        return q


def encode_filter(key: bytes, items, p: int = FILTER_P, m: int = FILTER_M) -> bytes:
    """Build one filter: u32 N (distinct mapped values) + the Golomb-Rice
    bitstream of sorted deltas.  ``key`` is the block hash (truncated
    internally); an empty item set encodes as four zero bytes."""
    key = key[:_KEY_LEN]
    values = _mapped_values(key, items, max(1, len(set(items))), m)
    out = _BitWriter()
    last = 0
    for v in values:
        delta = v - last
        out.unary(delta >> p)
        out.write(delta, p)
        last = v
    return len(values).to_bytes(4, "big") + out.done()


def decode_values(filter_bytes: bytes, p: int = FILTER_P):
    """Yield the filter's sorted absolute values.  Raises ValueError on a
    truncated stream — peer-supplied filters go through here, so the
    caller can treat that as a protocol fault."""
    if len(filter_bytes) < 4:
        raise ValueError("filter shorter than its count prefix")
    n = int.from_bytes(filter_bytes[:4], "big")
    reader = _BitReader(filter_bytes[4:])
    last = 0
    for _ in range(n):
        q = reader.unary()
        r = reader.read(p)
        last += (q << p) | r
        yield last


def filter_count(filter_bytes: bytes) -> int:
    if len(filter_bytes) < 4:
        raise ValueError("filter shorter than its count prefix")
    return int.from_bytes(filter_bytes[:4], "big")


def matches_any(
    filter_bytes: bytes,
    key: bytes,
    items,
    p: int = FILTER_P,
    m: int = FILTER_M,
) -> bool:
    """True when ANY of ``items`` may be in the filtered block.

    Zero false negatives by construction: an item that was in the
    block's item set maps to a value the encoder committed, and the
    same map is applied to the query — so a miss here is proof of
    absence (what lets a light client SKIP the block).  False positives
    happen at ~len(items)/M per block and cost one wasted block fetch."""
    key = key[:_KEY_LEN]
    n = filter_count(filter_bytes)
    if n == 0 or not items:
        return False
    f = n * m
    targets = sorted({_hash_to_range(key, it, f) for it in items})
    ti = 0
    for value in decode_values(filter_bytes, p):
        while ti < len(targets) and targets[ti] < value:
            ti += 1
        if ti == len(targets):
            return False
        if targets[ti] == value:
            return True
    return False


def decode_value_set(filter_bytes: bytes, p: int = FILTER_P) -> frozenset:
    """The filter's mapped values as a set — the push plane's shared
    decode: one pass per block, then ``matches_values`` per subscriber
    is a handful of hashes and set probes instead of a re-decode (the
    difference between O(subs · filter) and O(filter + subs · items)
    per connect at 100k live subscriptions)."""
    return frozenset(decode_values(filter_bytes, p))


def matches_values(
    values,
    n: int,
    key: bytes,
    items,
    m: int = FILTER_M,
) -> bool:
    """``matches_any`` against a pre-decoded value set (``values`` from
    ``decode_value_set``, ``n`` from ``filter_count``)."""
    if n == 0 or not items:
        return False
    key = key[:_KEY_LEN]
    f = n * m
    return any(_hash_to_range(key, it, f) in values for it in items)


def block_filter(block, p: int = FILTER_P, m: int = FILTER_M) -> bytes:
    """The canonical filter for ``block`` — keyed by its own hash, so a
    filter is verifiable against (and only against) the block it claims
    to summarize."""
    return encode_filter(block.block_hash(), filter_items(block), p, m)


class FilterIndex:
    """Bounded LRU of per-block filters, maintained at connect time.

    The node adds every block it connects (``Chain.add_block`` →
    node._handle_block path); anything evicted — or anything from
    before this feature existed ("backfillable for existing stores") —
    is rebuilt on demand from the block body, which the store can
    always re-serve (``ChainStore.read_body``).  ``bytes_used`` is
    charged to the node's accounted memory gauge, so a filter flood can
    never be the RAM term the PR-4 governor doesn't see."""

    def __init__(self, max_bytes: int = 16 << 20):
        import collections

        self.max_bytes = int(max_bytes)
        self._lru: "collections.OrderedDict[bytes, bytes]" = (
            collections.OrderedDict()
        )
        self.bytes_used = 0
        self.built = 0
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        return len(self._lru)

    def get(self, block_hash: bytes) -> bytes | None:
        f = self._lru.get(block_hash)
        if f is None:
            self.misses += 1
            return None
        self._lru.move_to_end(block_hash)
        self.hits += 1
        return f

    def add_block(self, block) -> bytes:
        """Build + cache ``block``'s filter (idempotent)."""
        bhash = block.block_hash()
        cached = self._lru.get(bhash)
        if cached is not None:
            self._lru.move_to_end(bhash)
            return cached
        f = block_filter(block)
        self._lru[bhash] = f
        self.bytes_used += len(f) + len(bhash)
        self.built += 1
        while self.bytes_used > self.max_bytes and len(self._lru) > 1:
            old_hash, old_f = self._lru.popitem(last=False)
            self.bytes_used -= len(old_f) + len(old_hash)
        return f

    def get_or_build(self, block_hash: bytes, block_loader) -> bytes:
        """The serving path: cached filter, or rebuild from the body
        ``block_loader(block_hash)`` re-serves (the chain's ``_block_at``
        / the store's ``read_body``)."""
        f = self.get(block_hash)
        if f is not None:
            return f
        return self.add_block(block_loader(block_hash))

    def snapshot(self) -> dict:
        return {
            "entries": len(self._lru),
            "bytes": self.bytes_used,
            "built": self.built,
            "hits": self.hits,
            "misses": self.misses,
        }


# -- the filter-header commitment chain (BIP157 analog) --------------------

#: The virtual header "before genesis" — the chain's anchor.  All-zero,
#: like BIP157's: the first real header is then a pure function of the
#: genesis block's filter, so two honest servers can never disagree.
GENESIS_FILTER_HEADER = b"\x00" * 32


def filter_hash(filter_bytes: bytes) -> bytes:
    return hashlib.sha256(filter_bytes).digest()


def next_filter_header(fhash: bytes, prev_header: bytes) -> bytes:
    """``filter_header[i] = H(filter_hash[i] || filter_header[i-1])`` —
    each header commits to every filter before it, so a wallet that
    knows ONE trusted header height can verify a whole served filter
    stream below it, and two servers that disagree anywhere disagree at
    the tip."""
    return hashlib.sha256(fhash + prev_header).digest()


class FilterHeaderChain:
    """The height-indexed commitment chain over the main branch.

    This is what closes the ROUND9 trust gap: filters themselves are
    pure functions of block bytes, but a wallet syncing from ONE
    untrusted replica had no way to tell a served filter from a forged
    one without downloading the block.  The header chain makes forgery
    *comparable*: any two servers of the same chain must serve identical
    filter headers at every height, so a wallet cross-checks the stream
    against a second source (or a single hash-pinned block fetch) and
    demotes whichever side broke the commitment.

    Maintained incrementally by ``sync()`` against any height→hash /
    height→filter source (the node's ``Chain``, a replica's mmap view).
    Entries store ``(block_hash, filter_header)`` so a reorg is detected
    by hash comparison and handled by truncate-and-extend.  A source
    that cannot produce a filter (pruned/re-based history with the body
    gone) simply stops the extension: the chain stays short and range
    queries refuse cleanly — wallets fail over to an archive holder,
    they are never served an uncommitted guess.
    """

    def __init__(self):
        self._entries: list[tuple[bytes, bytes]] = []  # index = height
        self.rebuilds = 0  # reorg truncations observed

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def tip_height(self) -> int:
        """Highest committed height; -1 when empty/unavailable."""
        return len(self._entries) - 1

    def header_at(self, height: int) -> bytes | None:
        if 0 <= height < len(self._entries):
            return self._entries[height][1]
        if height == -1:
            return GENESIS_FILTER_HEADER
        return None

    def hash_at(self, height: int) -> bytes | None:
        """The BLOCK hash the commitment at ``height`` was built for."""
        if 0 <= height < len(self._entries):
            return self._entries[height][0]
        return None

    def range(self, start: int, count: int) -> list[bytes]:
        """Headers for ``start .. start+count-1``; empty when any part of
        the span is not committed (refusal, never a partial lie)."""
        if start < 0 or count <= 0 or start + count > len(self._entries):
            return []
        return [h for _, h in self._entries[start : start + count]]

    def seed(self, entries: list[tuple[bytes, bytes]]) -> None:
        """Adopt a ``(block_hash, filter_header)`` prefix wholesale — the
        snapshot-bootstrapped replica's case (node/provision.py): the
        bodies below the snapshot base are not on disk, so the prefix
        cannot be recomputed locally; it is adopted from the bootstrap
        peer under the same trust model as the assumed snapshot itself
        (any forgery diverges from every honest server at the first
        adopted height, which is exactly what the wallet cross-check and
        hash-pinned adjudication catch).  Replaces the whole chain;
        ``sync()`` then extends from the adopted tip using real bodies."""
        self._entries = [(bytes(bh), bytes(fh)) for bh, fh in entries]

    def sync(self, tip_height: int, hash_at, filter_at) -> list[int]:
        """Advance (or repair) the chain against a source of truth;
        returns the heights whose commitments are new or changed — the
        push plane's notification list.

        ``hash_at(h) -> bytes | None`` and ``filter_at(h) -> bytes |
        None`` read the source's main branch.  The common case is O(1):
        the stored tip hash still matches and only new heights extend.
        A mismatch walks back to the fork point, truncates, and
        re-extends (the reorg path).  ``filter_at`` returning None stops
        the extension — the remaining span stays uncommitted."""
        # Walk back over any suffix the source no longer agrees with.
        top = len(self._entries) - 1
        while top >= 0 and self._entries[top][0] != hash_at(top):
            top -= 1
        if top < len(self._entries) - 1:
            del self._entries[top + 1 :]
            self.rebuilds += 1
        changed: list[int] = []
        prev = (
            self._entries[-1][1] if self._entries else GENESIS_FILTER_HEADER
        )
        for h in range(len(self._entries), tip_height + 1):
            bhash = hash_at(h)
            if bhash is None:
                break
            fbytes = filter_at(h)
            if fbytes is None:
                break  # pruned/spilled body: stay honestly short
            prev = next_filter_header(filter_hash(fbytes), prev)
            self._entries.append((bhash, prev))
            changed.append(h)
        return changed
