from p1_tpu.chain.chain import AddResult, AddStatus, Chain
from p1_tpu.chain.filters import FilterIndex, block_filter, matches_any
from p1_tpu.chain.ledger import balances
from p1_tpu.chain.proof import (
    ProofCache,
    SPVError,
    TxProof,
    build_block_proofs,
    verify_tx_proof,
)
from p1_tpu.chain.replay import (
    ReplayReport,
    generate_headers,
    pack_headers,
    parse_headers,
    replay_device,
    replay_fast,
    replay_host,
    replay_native,
    replay_packed,
)
from p1_tpu.chain.snapshot import (
    LedgerSnapshot,
    SnapshotError,
    load_snapshot,
    state_root,
    write_snapshot,
)
from p1_tpu.chain.headerplane import (
    ArchiveChain,
    HeaderPlane,
    SegmentIndex,
    write_segment_index,
)
from p1_tpu.chain.segstore import SegmentedStore, is_segmented, open_store
from p1_tpu.chain.store import ChainStore, save_chain
from p1_tpu.chain.validate import ValidationError, check_block

__all__ = [
    "AddResult",
    "AddStatus",
    "ArchiveChain",
    "Chain",
    "ChainStore",
    "HeaderPlane",
    "SegmentIndex",
    "SegmentedStore",
    "is_segmented",
    "open_store",
    "write_segment_index",
    "FilterIndex",
    "LedgerSnapshot",
    "ProofCache",
    "SnapshotError",
    "load_snapshot",
    "state_root",
    "write_snapshot",
    "block_filter",
    "build_block_proofs",
    "matches_any",
    "ReplayReport",
    "SPVError",
    "TxProof",
    "ValidationError",
    "verify_tx_proof",
    "balances",
    "check_block",
    "generate_headers",
    "pack_headers",
    "parse_headers",
    "replay_device",
    "replay_fast",
    "replay_host",
    "replay_native",
    "replay_packed",
    "save_chain",
]
