"""The block index: longest-chain fork choice with reorg support.

Capability parity: the reference's chain layer — "chain-validation code
paths" and "longest-chain" resolution on the gossip network
(BASELINE.json:5,10).  Design:

- Every valid block is indexed by hash with its height and **cumulative
  work** (2**difficulty per block — equal to chain length at the fixed
  difficulty the benchmark configs use, but correct if difficulty ever
  varies).  Fork choice = most cumulative work; ties resolve to the
  lexicographically smaller tip hash.  The tie-break makes fork choice a
  **pure function of the block set** — gossip floods every block, so any
  two nodes that have seen the same blocks pick the same tip, and a
  quiesced network converges deterministically instead of deadlocking on
  equal-work first-seen tips.
- Blocks whose parent is unknown wait in an **orphan pool** keyed by
  prev-hash (gossip delivers out of order); connecting a parent drains its
  orphans recursively.  The pool is hostile-input-safe: a block must pass
  full stateless validation (including its own PoW) *before* parking, the
  pool is capped at ``MAX_ORPHANS`` with FIFO eviction, and re-received
  orphans are not double-parked — so a buggy or malicious peer cannot grow
  node memory without bound by flooding unconnectable blocks.
- ``add_block`` reports what happened — including the reorg's removed/added
  block lists so the mempool can resurrect transactions from abandoned
  blocks and the miner knows to abort a stale search.
- **Difficulty is contextual when a ``RetargetRule`` is active** (opt-in,
  core/retarget.py): the required difficulty of a block is a pure function
  of its ancestor chain (parent's difficulty, adjusted at window
  boundaries from observed timestamps), checked at connect time; fixed
  difficulty — every benchmark config — is the ``retarget=None`` default
  and behaves exactly as before.  Cumulative work already weighs each
  block by ``2**difficulty``, so fork choice across mixed-difficulty
  branches needs no change.
- **Contextual (ledger) validity is enforced at connect time**, Bitcoin
  style: stateless checks (PoW, merkle, signatures, subsidy) gate indexing,
  but whether a transfer overdraws its sender depends on the block's whole
  ancestor chain — so the incremental ``Ledger`` held at the tip validates
  blocks exactly when the tip tries to move onto them.  A branch containing
  an overdraw is marked **invalid** (the block and every descendant,
  permanently — contextual validity is a pure function of a block's
  ancestor chain, so all nodes agree) and fork choice falls back to the
  best valid tip.  Side branches are indexed without ledger checks (their
  state isn't materialized) and get validated if work ever favors them.
"""

from __future__ import annotations

import collections
import dataclasses
import enum
from typing import Iterator

from p1_tpu.core import sigcache
from p1_tpu.core.block import Block
from p1_tpu.core.genesis import make_genesis
from p1_tpu.core.header import BlockHeader
from p1_tpu.core.retarget import RetargetRule
from p1_tpu.chain.filters import FilterHeaderChain, FilterIndex
from p1_tpu.chain.ledger import Ledger, LedgerError
from p1_tpu.chain.proof import ProofCache, TxProof, build_block_proofs
from p1_tpu.chain.snapshot import (
    DEFAULT_CHECKPOINT_INTERVAL,
    LedgerSnapshot,
    state_root,
)
from p1_tpu.chain.statedelta import block_accounts
from p1_tpu.chain.validate import ValidationError, check_block


#: Orphan-pool capacity.  Orphans exist only to absorb out-of-order gossip,
#: which a locator sync backfills within one round trip — a few hundred is
#: plenty, and the cap is what bounds memory against a flooding peer.
MAX_ORPHANS = 256


def locator_hashes(hashes: list[bytes], dense: int = 10) -> list[bytes]:
    """Tip-first sync locator over a genesis-first hash list: the last
    ``dense`` entries one by one, then exponentially spaced back to
    genesis.  ONE definition — ``Chain.locator`` (server side) and the
    light client's header fetch share it, so the shape both sides use to
    find the fork point cannot drift."""
    out = []
    height = len(hashes) - 1
    step = 1
    while True:
        out.append(hashes[height])
        if height == 0:
            return out
        if len(out) >= dense:
            step *= 2
        height = max(0, height - step)


class AddStatus(enum.Enum):
    ACCEPTED = "accepted"  # extends a known block (tip may or may not move)
    DUPLICATE = "duplicate"  # already indexed
    ORPHAN = "orphan"  # parent unknown; parked in the orphan pool
    REJECTED = "rejected"  # failed validation


@dataclasses.dataclass(frozen=True)
class AddResult:
    status: AddStatus
    reason: str = ""
    #: Set when the tip moved.  ``removed`` is the abandoned branch
    #: (old-tip-first), ``added`` the new one (fork-point-first); a plain
    #: extension has removed=() and added=(block,).
    removed: tuple[Block, ...] = ()
    added: tuple[Block, ...] = ()
    #: Every block newly indexed by this call, insertion order: the
    #: triggering block plus any orphans it unblocked.  This is what
    #: persistence must append — ``added`` alone misses side branches and
    #: cascaded orphans.
    connected: tuple[Block, ...] = ()

    @property
    def tip_changed(self) -> bool:
        return bool(self.added)


@dataclasses.dataclass(slots=True)
class _Entry:
    """One indexed block.  ``header`` is ALWAYS resident (fork choice,
    difficulty schedules, and locators need it); ``block`` may be evicted
    to ``None`` once the body is safely refetchable from the chain's
    ``body_source`` (memory-bounded operation — ``evict_bodies``).
    Slots: there is one of these per block FOREVER — the per-instance
    dict would be a ~200-byte O(chain) RAM term all by itself."""

    block: Block | None
    header: "BlockHeader"
    height: int
    work: int  # cumulative, including this block


class Chain:
    """Block index + fork choice for one chain configuration."""

    def __init__(
        self,
        difficulty: int,
        genesis: Block | None = None,
        retarget: RetargetRule | None = None,
    ):
        #: Base (genesis) difficulty.  With ``retarget`` set, per-block
        #: required difficulty is contextual (``_expected_difficulty``) and
        #: this stays the anchor the rule evolves from.
        self.difficulty = difficulty
        self.retarget = retarget
        self.genesis = (
            genesis if genesis is not None else make_genesis(difficulty, retarget)
        )
        ghash = self.genesis.block_hash()
        self._index: dict[bytes, _Entry] = {
            ghash: _Entry(self.genesis, self.genesis.header, 0, 1 << difficulty)
        }
        self._tip_hash = ghash
        #: Height of the chain's BASE block — genesis (0) normally, the
        #: snapshot anchor block's height for an assumed chain built by
        #: ``from_snapshot``.  Every height-indexed structure
        #: (``_main_hashes``) is offset by it; nothing below the base is
        #: indexed, so reorgs can never cross it.
        self.base_height = 0
        #: True for a chain whose base state came from an (untrusted)
        #: snapshot rather than replayed history — the serving node's
        #: ASSUMED validation state mirrors this until the flip.
        self.assumed = False
        #: State-root commitment cadence (chain/snapshot.py): a root of
        #: the ledger state is recorded in ``state_checkpoints`` at every
        #: multiple of this height interval as blocks apply — the
        #: retarget window when one is active (the consensus-natural
        #: cadence), DEFAULT_CHECKPOINT_INTERVAL on fixed-difficulty
        #: chains.  ``checkpoint_extra`` adds ad-hoc watch heights (the
        #: background revalidation pins the snapshot height there so the
        #: divergence check reads an exact-height root regardless of
        #: interval agreement between nodes).
        self.checkpoint_interval = (
            retarget.window if retarget is not None else DEFAULT_CHECKPOINT_INTERVAL
        )
        self.checkpoint_extra: set[int] = set()
        #: height -> ledger state root at that height, maintained in
        #: lockstep with the ledger (recorded on apply, popped on undo —
        #: a reorg re-records the new branch's roots).  O(height /
        #: interval) * 32 B; the snapshot plane's commitment surface.
        self.state_checkpoints: dict[int, bytes] = {}
        #: Accounts touched by ledger moves since the last
        #: ``collect_dirty_accounts`` — recorded on BOTH apply and undo,
        #: so the set is always a superset of the state diff between any
        #: two collection points (reorgs included).  Feeds the
        #: incremental snapshot builder (chain/snapshot.py); a too-big
        #: set only costs chunk reuse, never correctness.
        self._dirty_accounts: set[str] = set()
        #: Verify-once signature cache consulted by every ``check_block``
        #: this index runs (core/sigcache.py).  The process default by
        #: default; a Node wires its own instance in so admission-time
        #: verifies are what block connect hits, and its telemetry is
        #: per-node.
        self.sig_cache = sigcache.DEFAULT
        #: Memory-bounded operation (node/governor.py): an object with
        #: ``has_body(bhash)`` / ``read_body(bhash)`` — the ChainStore —
        #: that can re-serve an evicted block body on demand.  None (the
        #: default) keeps every body resident and ``evict_bodies`` a
        #: no-op, exactly the pre-governor behavior.
        self.body_source = None
        #: Serialized bytes of the bodies currently resident (genesis
        #: excluded — never evicted), the big term in the node's memory
        #: gauge; plus eviction/refetch telemetry.
        self.resident_body_bytes = 0
        self.bodies_evicted = 0
        self.body_refetches = 0
        #: The one indexed block whose body was never charged to
        #: ``resident_body_bytes``: the construction-time base (genesis
        #: here, the anchor in ``from_snapshot``) predates the gauge.
        #: ``rebase`` consults this so dropping it does not over-credit;
        #: every later base went through ``_insert`` and IS charged.
        self._base_body_unaccounted: bytes | None = ghash
        #: Insertion-ordered candidates for body eviction (≈ height
        #: order).  Entries already evicted or de-indexed are skipped on
        #: the sweep, so the deque stays O(resident bodies).
        self._resident_fifo: collections.deque[bytes] = collections.deque()
        #: Main-chain hashes by height (``_main_hashes[h]`` is the height-h
        #: block).  Kept in sync on every tip move so sync serving
        #: (``blocks_after``) and ``_on_main_chain`` are O(1) per block
        #: instead of re-walking the whole chain per request.
        self._main_hashes: list[bytes] = [ghash]
        self._orphans: dict[bytes, list[Block]] = {}  # prev_hash -> waiting blocks
        self._orphan_hashes: set[bytes] = set()  # parked block hashes (dedup)
        self._orphan_fifo: collections.deque[tuple[bytes, bytes]] = (
            collections.deque()
        )  # (prev_hash, block_hash) in arrival order, for FIFO eviction
        #: Account state at the current tip — advanced/rewound with every
        #: tip move, so contextual validation is O(blocks moved).
        self._ledger = Ledger()
        self._ledger.apply_block(self.genesis)
        #: Contextually invalid blocks (overdraw somewhere in their history)
        #: + why.  Membership is permanent; descendants inherit it.
        self._invalid: dict[bytes, str] = {}
        #: parent hash -> child hash(es), for invalidating indexed
        #: subtrees.  Value is the bare child hash (shared with the index
        #: key — zero extra allocation) in the universal one-child case,
        #: widening to a list only at a real fork: one list shell per
        #: block would be a ~9 MB O(chain) RAM term at 100k blocks.
        self._children: dict[bytes, bytes | list[bytes]] = {}
        #: txid -> containing main-chain block hash, maintained with every
        #: tip move (like the ledger) so SPV proof serving is O(block), not
        #: O(chain).  Main chain only: side-branch confirmations are not
        #: facts a node should attest to.
        self._tx_index: dict[bytes, bytes] = {
            tx.txid(): ghash for tx in self.genesis.txs
        }
        #: Pruned operation (round 18, chain/segstore.py): main-chain
        #: heights BELOW this have had their on-disk bodies discarded.
        #: Headers and every index structure stay; body-dependent
        #: serving (deep proofs, filter rebuilds, block sync into the
        #: pruned range) gates on ``body_available`` instead of
        #: assuming a refetch can always succeed.  0 = archive node.
        self.prune_floor = 0
        #: Serving plane (round 9).  ``proof_cache`` memoizes the
        #: reorg-stable part of inclusion proofs, filled a whole block at
        #: a time (one merkle tree amortized over every tx in the block)
        #: and invalidated per abandoned block on reorg; ``filter_index``
        #: caches per-block compact filters (chain/filters.py), built at
        #: connect and rebuilt on demand for deep history.  Both are
        #: bytes-bounded LRUs the node charges to its memory gauge.
        self.proof_cache = ProofCache()
        self.filter_index = FilterIndex()
        #: BIP157-analog filter-header commitment chain, kept in
        #: lockstep with the main chain at every connect (add_block) so
        #: the push/serving planes can hand wallets a commitment to
        #: cross-check untrusted filter streams against.  Stays
        #: honestly empty on re-based / from_snapshot chains (no height
        #: 0 to anchor at) and honestly short past a pruned body — a
        #: refusal wallets treat as "ask an archive replica", never as
        #: a partial answer.
        self.filter_headers = FilterHeaderChain()
        #: Stateless-validation entry point used by ``_insert`` and
        #: ``_park_orphan`` — an instance attribute so the staged node
        #: (node/pipeline.py) can interpose and so tests can instrument
        #: connect order.  With the staged pipeline on, every wire
        #: block's signatures are pre-verified OFF-loop before
        #: ``add_block`` runs, so this call is a sig-cache hit on the
        #: valid path — only hostile (invalid-signature) blocks pay an
        #: on-loop verify here, bounded by the ban that follows.
        self.check_block = check_block

    @classmethod
    def from_snapshot(
        cls,
        difficulty: int,
        snap: LedgerSnapshot,
        retarget: RetargetRule | None = None,
    ) -> "Chain":
        """An ASSUMED chain anchored on a verified snapshot: the index
        holds exactly the anchor block, the ledger holds the snapshot
        state, and everything below the base simply does not exist here
        — new blocks extend the anchor, queries serve immediately, and
        the real history is somebody else's (the background
        revalidation's) problem until the flip.

        Trust: ``snap`` passed chain/snapshot.py's integrity gates
        (digests, root, anchor hash) — but the STATE is still only the
        serving peer's claim; the caller owns tracking that (ASSUMED
        vs VALIDATED, node/node.py).

        Cumulative work below the base is unknowable without the
        history, so the anchor's work is assumed at ``height + 1``
        blocks of its own difficulty.  This only weighs fork choice
        against branches attached below the base — which an assumed
        chain cannot index anyway (their parents are unknown, they park
        as orphans) — so the approximation is unobservable until the
        flip replaces this chain wholesale.
        """
        chain = cls(difficulty, retarget=retarget)
        block = snap.manifest.block
        bhash = block.block_hash()
        if snap.height < 1:
            raise ValueError("snapshot base must be above genesis")
        work = (snap.height + 1) * (1 << block.header.difficulty)
        chain._index = {bhash: _Entry(block, block.header, snap.height, work)}
        chain._tip_hash = bhash
        chain.base_height = snap.height
        chain.assumed = True
        chain._main_hashes = [bhash]
        chain._ledger = Ledger.restore(snap.balances, snap.nonces)
        chain._tx_index = {tx.txid(): bhash for tx in block.txs}
        chain._children = {}
        chain._base_body_unaccounted = bhash
        # The snapshot's own claim IS the base checkpoint: background
        # revalidation compares its replayed root against this height.
        chain.state_checkpoints = {snap.height: snap.state_root}
        return chain

    # -- queries ---------------------------------------------------------

    @property
    def tip(self) -> Block:
        return self._block_at(self._tip_hash)

    @property
    def tip_hash(self) -> bytes:
        return self._tip_hash

    @property
    def height(self) -> int:
        return self._index[self._tip_hash].height

    def __contains__(self, block_hash: bytes) -> bool:
        return block_hash in self._index

    def __len__(self) -> int:
        return len(self._index)

    def get(self, block_hash: bytes) -> Block | None:
        if block_hash not in self._index:
            return None
        return self._block_at(block_hash)

    def header_of(self, block_hash: bytes) -> BlockHeader | None:
        """The indexed block's header — always resident, so queries that
        only need header fields never cost a body refetch."""
        entry = self._index.get(block_hash)
        return entry.header if entry else None

    def _block_at(self, block_hash: bytes) -> Block:
        """The full block for an INDEXED hash, refetching an evicted body
        from ``body_source`` on demand.  Refetches are transient — the
        body is NOT re-cached into the index, so serving deep history to
        a syncing peer cannot silently re-grow the working set the
        eviction sweep just bounded."""
        entry = self._index[block_hash]
        if entry.block is not None:
            return entry.block
        block = self.body_source.read_body(block_hash)
        self.body_refetches += 1
        return block

    def height_of(self, block_hash: bytes) -> int:
        return self._index[block_hash].height

    def body_available(self, block_hash: bytes) -> bool:
        """True when ``_block_at`` can actually produce this block's
        body — resident in RAM, or durably refetchable from the body
        source.  The gate body-dependent serving consults on a pruned
        node: an evicted body whose segment was discarded is headers-
        only forever, and asking for it must be a clean refusal, not a
        KeyError out of the span map."""
        entry = self._index.get(block_hash)
        if entry is None:
            return False
        if entry.block is not None:
            return True
        return self.body_source is not None and self.body_source.has_body(
            block_hash
        )

    def best_block_within(self, ts_bound: int) -> Block:
        """The most-work block (main chain or branch) whose timestamp is
        <= ``ts_bound``.  Serves the miner's hostile-anchor policy
        (node.py _mining_parent): when the tip's stamp is absurdly far
        past wall time, honest mining continues from the heaviest sane
        block — which includes the policy fork's own earlier blocks, so
        the honest branch makes progress instead of re-mining one
        candidate forever.  O(index); only called in that rare mode.
        Genesis always qualifies (its stamp is a fixed past constant)."""
        best_hash = self.genesis.block_hash()
        best = self._index.get(best_hash)
        if best is None:
            # Genesis is not indexed on a from_snapshot or re-based
            # chain — anchor the scan on the base block instead (the
            # oldest block this index can even offer; nothing below it
            # exists here, so it is the degenerate fallback by
            # construction even when its stamp exceeds the bound).
            best_hash = self._main_hashes[0]
            best = self._index[best_hash]
        for bhash, entry in self._index.items():
            if (
                entry.header.timestamp > ts_bound
                or bhash in self._invalid
            ):
                # Invalid branches keep their index entries (permanent
                # rejection memory) but nothing may mine on them — the
                # same exclusion _best_valid_tip applies.
                continue
            # Work tie-break on the hash — compared via the index KEYS,
            # which already are the hashes: re-deriving block_hash() per
            # entry would put a redundant sha256d inside this O(index)
            # scan (ADVICE r5).
            if entry.work > best.work or (
                entry.work == best.work and bhash < best_hash
            ):
                best, best_hash = entry, bhash
        return self._block_at(best_hash)

    def balance(self, account: str) -> int:
        """``account``'s balance at the current tip (consensus ledger) —
        never negative, because an overdrawing block cannot connect."""
        return self._ledger.balance(account)

    def balances_snapshot(self) -> dict[str, int]:
        """All non-zero balances at the current tip."""
        return self._ledger.snapshot()

    def nonce(self, account: str) -> int:
        """The seq ``account``'s next transfer must carry (strict account
        nonce — see ledger.py's replay rule)."""
        return self._ledger.nonce(account)

    def next_difficulty(self) -> int:
        """The difficulty consensus requires of the next block on the tip
        — what a miner must put in the header it assembles.  Equal to the
        chain difficulty unless a ``RetargetRule`` is active."""
        return self._expected_difficulty(self._index[self._tip_hash])

    def required_difficulty(self, prev_hash: bytes) -> int | None:
        """The difficulty consensus requires of a child of ``prev_hash``,
        or None when the parent is unknown.  Lets gossip handlers price a
        pushed header at its EXACT contextual work bar before spending any
        state or round trips on it (node.py's compact-block gate)."""
        entry = self._index.get(prev_hash)
        return None if entry is None else self._expected_difficulty(entry)

    def _expected_difficulty(self, prev: _Entry) -> int:
        """Required difficulty for a child of ``prev`` — a pure function
        of the ancestor chain, so every node computes the same value for
        the same parent (side branches included)."""
        rule = self.retarget
        if rule is None:
            return self.difficulty
        height = prev.height + 1
        if height % rule.window != 0:
            return prev.header.difficulty
        # Window boundary: observe the span of the closing window (its
        # first block is `window-1` parents above `prev`; the walk is
        # O(window) once per window, amortized O(1)/block — and headers
        # are always resident, so it never refetches).
        anchor = prev
        for _ in range(rule.window - 1):
            anchor = self._index[anchor.header.prev_hash]
        span = prev.header.timestamp - anchor.header.timestamp
        return rule.adjusted(prev.header.difficulty, span)

    def fee_stats(self, window: int = 32) -> dict:
        """Fee percentiles over the transfers confirmed in the last
        ``window`` main-chain blocks — what a wallet consults to price a
        spend (`p1 tx --fee auto`).  With no recent transfers every
        percentile is 0 and callers fall back to the minimum fee; the
        sample is confirmed-fees-only by design (pending-pool fees are a
        bid book, confirmed fees are what actually cleared)."""
        fees: list[int] = []
        blocks = 0
        for h in reversed(self._main_hashes[-window:] if window else []):
            entry = self._index[h]
            if entry.height <= self.base_height:
                break  # the base block anchors, it does not sample
            blocks += 1
            fees.extend(
                tx.fee for tx in self._block_at(h).txs if not tx.is_coinbase
            )
        fees.sort()

        def pct(p: float) -> int:
            if not fees:
                return 0
            return fees[min(len(fees) - 1, int(p * len(fees)))]

        return {
            "window_blocks": blocks,
            "samples": len(fees),
            "p25": pct(0.25),
            "p50": pct(0.50),
            "p75": pct(0.75),
        }

    def tx_proof(self, txid: bytes) -> TxProof | None:
        """SPV inclusion proof for a main-chain-confirmed transaction, or
        ``None`` if ``txid`` is not confirmed at the current tip.  Served
        from the txid index (O(containing block) worst case) through the
        proof cache: a miss builds proof templates for the WHOLE
        containing block with one merkle tree (amortizing the tree over
        every tx in it — the batch economics of chain/proof.py), a hit
        is a dict lookup plus a tip-height stamp."""
        entry = self.tx_proof_entry(txid)
        return None if entry is None else entry.at_tip(self.height)

    def tx_proof_entry(self, txid: bytes):
        """The cached (tip-height-free) proof entry for ``txid``, or None
        when it is not confirmed on the current main chain.  The wire
        layer uses this to memoize serialized payloads on the entry
        (node/node.py, node/queryplane.py)."""
        bhash = self._tx_index.get(txid)
        if bhash is None:
            return None
        cached = self.proof_cache.get(bhash, txid)
        if cached is not None:
            return cached
        if not self.body_available(bhash):
            # Pruned range (or a read-failed segment): the body this
            # proof's merkle tree needs is gone — refuse cleanly, the
            # same answer an unconfirmed txid gets.
            return None
        # Miss: build every proof for the containing block at once —
        # requests cluster by block (a wallet checking a payment batch,
        # a reorg re-audit), so the amortized fill is the common win.
        block = self._block_at(bhash)
        height = self._index[bhash].height
        txids = [tx.txid() for tx in block.txs]
        for tid, proof in build_block_proofs(block, height, txids).items():
            entry = self.proof_cache.add(bhash, tid, proof)
            if tid == txid:
                cached = entry
        return cached

    def tx_proofs(self, txids) -> dict[bytes, TxProof | None]:
        """Batch proof lookup: one ``TxProof`` (or None) per requested
        txid, sharing a single merkle-tree construction per distinct
        containing block via the proof cache.  The serving plane's
        amortized API (benchmarks/query_plane.py measures it against
        the serial per-proof baseline)."""
        tip = self.height
        out: dict[bytes, TxProof | None] = {}
        for txid in txids:
            entry = self.tx_proof_entry(txid)
            out[txid] = None if entry is None else entry.at_tip(tip)
        return out

    def block_filter(self, block_hash: bytes) -> bytes | None:
        """The compact filter for an indexed block (chain/filters.py),
        from the filter index — rebuilt on demand from the (possibly
        evicted, store-refetchable) body for deep history."""
        if block_hash not in self._index:
            return None
        cached = self.filter_index.get(block_hash)
        if cached is not None:
            return cached
        if not self.body_available(block_hash):
            return None  # pruned body and no cached filter: refuse
        return self.filter_index.get_or_build(block_hash, self._block_at)

    def _sync_filter_headers(self) -> None:
        """Advance ``filter_headers`` to the current main chain.  O(1)
        per plain extension (one filter build, cached in the filter
        index); reorgs walk back by hash comparison.  Pruned bodies
        with no cached filter stop the walk — the commitment stays
        honestly short rather than guessing."""

        def filter_at(height: int) -> bytes | None:
            bh = self.main_hash_at(height)
            return None if bh is None else self.block_filter(bh)

        self.filter_headers.sync(self.height, self.main_hash_at, filter_at)

    def main_hash_at(self, height: int) -> bytes | None:
        """The main-chain block hash at ``height`` (None above the tip,
        and None below an assumed chain's base — heights this index
        simply does not hold) — the filter-serving path's height → hash
        step."""
        i = height - self.base_height
        if 0 <= i < len(self._main_hashes):
            return self._main_hashes[i]
        return None

    # -- snapshot-state plane (chain/snapshot.py) -------------------------

    def state_root(self) -> bytes:
        """Merkle root of the ledger state at the current tip — the
        canonical commitment chain/snapshot.py defines."""
        return state_root(self._ledger._balances, self._ledger._nonces)

    def _is_checkpoint(self, height: int) -> bool:
        if height <= self.base_height:
            return False
        return (
            height % self.checkpoint_interval == 0
            or height in self.checkpoint_extra
        )

    def _ledger_apply(self, block: Block) -> None:
        """Apply one block to the tip ledger, recording the state root
        when the block lands on a checkpoint height — the ONE place
        application happens, so the commitment can never miss a move."""
        self._ledger.apply_block(block)
        self._dirty_accounts.update(block_accounts(block))
        height = self._index[block.block_hash()].height
        if self._is_checkpoint(height):
            self.state_checkpoints[height] = state_root(
                self._ledger._balances, self._ledger._nonces
            )

    def _ledger_undo(self, block: Block) -> None:
        """Reverse one block, dropping any root recorded at its height
        (a reorg onto another branch re-records through
        ``_ledger_apply``)."""
        self._ledger.undo_block(block)
        self._dirty_accounts.update(block_accounts(block))
        self.state_checkpoints.pop(
            self._index[block.block_hash()].height, None
        )

    def collect_dirty_accounts(self) -> set[str]:
        """Consume-and-clear the dirty-account set: every account the
        ledger touched since the previous collection.  The incremental
        snapshot builder calls this once per build; between two calls
        the set is a guaranteed superset of the state diff, so entries
        NOT in it are safe to reuse byte-for-byte."""
        dirty = self._dirty_accounts
        self._dirty_accounts = set()
        return dirty

    def snapshot_state(
        self,
    ) -> tuple[int, Block, dict[str, int], dict[str, int], bytes] | None:
        """Materialize the ledger state at the LATEST checkpoint height
        — (height, anchor block, balances, nonces, state root) — by
        rolling a ledger copy back from the tip (O(interval) undos; the
        live ledger is untouched).  None when no checkpoint above the
        base exists yet (too-short chains serve no snapshot).  This is
        what GETSNAPSHOT serving and ``p1 snapshot create`` package."""
        interval = self.checkpoint_interval
        height = (self.height // interval) * interval
        if height <= self.base_height:
            return None
        ledger = self._ledger.copy()
        for h in range(self.height, height, -1):
            ledger.undo_block(
                self._block_at(self._main_hashes[h - self.base_height])
            )
        balances = ledger.snapshot()
        nonces = ledger.nonces_snapshot()
        root = state_root(balances, nonces)
        recorded = self.state_checkpoints.get(height)
        if recorded is not None and recorded != root:
            # The incremental commitment and the rollback disagree —
            # an internal invariant break, never peer input.
            raise RuntimeError(
                f"state root at checkpoint {height} diverged from the "
                "recorded commitment"
            )
        block = self._block_at(self._main_hashes[height - self.base_height])
        return height, block, balances, nonces, root

    def main_chain(self) -> Iterator[Block]:
        """Genesis-first iteration of the current best chain."""
        for h in self._main_hashes:
            yield self._block_at(h)

    def locator(self, dense: int = 10) -> list[bytes]:
        """Hashes from tip back to genesis: the last ``dense`` blocks one by
        one, then exponentially spaced — the classic sync locator shape."""
        return locator_hashes(self._main_hashes, dense)

    def sync_start_height(self, locator: list[bytes]) -> int:
        """The height a GETBLOCKS reply would start at for ``locator``
        — the first hash we recognize on the main chain, plus one.
        Split out so the node can price a request against its prune
        floor BEFORE touching any block body."""
        for h in locator:
            entry = self._index.get(h)
            if entry and self._on_main_chain(h):
                return entry.height + 1
        return self.base_height

    def headers_after(
        self, locator: list[bytes], limit: int = 500
    ) -> list[BlockHeader]:
        """Main-chain HEADERS after the first recognized locator hash —
        the body-free sibling of ``blocks_after`` (headers are always
        resident, so serving a headers-first sync never costs a body
        refetch and keeps working over pruned ranges)."""
        start = self.sync_start_height(locator) - self.base_height
        end = min(start + limit, len(self._main_hashes))
        return [
            self._index[self._main_hashes[i]].header
            for i in range(start, end)
        ]

    def blocks_after(self, locator: list[bytes], limit: int = 500) -> list[Block]:
        """Main-chain blocks after the first locator hash we recognize.

        O(limit) per call: served straight from the height index instead of
        materializing the whole main chain (which made a full peer sync
        O(height²/batch))."""
        start = self.sync_start_height(locator) - self.base_height
        end = min(start + limit, len(self._main_hashes))
        return [
            self._block_at(self._main_hashes[i]) for i in range(start, end)
        ]

    # -- mutation --------------------------------------------------------

    def add_block(self, block: Block, trusted: bool = False) -> AddResult:
        """Index ``block`` (and any orphans it unblocks); report the outcome.

        The reorg paths in the result describe the net tip movement of the
        whole call — computed once against the tip as it was on entry, so
        an orphan cascade that moves the tip twice still reports one
        coherent removed/added pair.

        ``trusted=True`` skips the stateless per-block checks (PoW,
        merkle, signatures, coinbase rules) — strictly for records this
        node itself validated before persisting (ChainStore's fast
        resume: the store is exclusively flocked and append-only, so its
        contents are this node's own past accepts).  Contextual rules
        (difficulty schedule, timestamp bounds) and the connect-time
        ledger/nonce validation still run, so the rebuilt state is
        byte-identical to a full revalidation — tested both ways.

        Hashing discipline: this method (and everything it calls —
        validation, the tx index, reorg paths) asks for ``block_hash()``
        and ``txid()`` freely; both are memoized on the frozen core types
        (core/header.py's cache notes), so the whole add costs ONE header
        digest and ONE digest per transaction regardless of how many
        sites re-ask — for wire/disk-ingested blocks, computed directly
        over the arrival bytes.
        """
        old_tip = self._tip_hash
        bhash = block.block_hash()
        status, reason = self._insert(block, prevalidated=trusted)
        if status is not AddStatus.ACCEPTED:
            return AddResult(status, reason=reason)

        # A newly indexed block may be the missing parent of parked orphans.
        connected = [block]
        pending = [bhash]
        while pending:
            for orphan in self._orphans.pop(pending.pop(), []):
                self._orphan_hashes.discard(orphan.block_hash())
                # Orphans were fully validated when parked; only linkage
                # (now satisfied) was missing — don't re-hash the block.
                st, _ = self._insert(orphan, prevalidated=True)
                if st is AddStatus.ACCEPTED:
                    connected.append(orphan)
                    pending.append(orphan.block_hash())
        # Connected orphans leave _orphans/_orphan_hashes but their FIFO
        # entries linger; compact once the stale fraction dominates so the
        # deque stays O(MAX_ORPHANS) over the node's lifetime.
        if len(self._orphan_fifo) > 2 * MAX_ORPHANS:
            self._orphan_fifo = collections.deque(
                e for e in self._orphan_fifo if e[1] in self._orphan_hashes
            )

        removed, added = self._settle_tip(old_tip)
        if removed:
            del self._main_hashes[len(self._main_hashes) - len(removed) :]
        self._main_hashes.extend(b.block_hash() for b in added)
        # Keep the txid index in lockstep with the main chain (pop the
        # abandoned branch first: a tx confirmed on both branches must end
        # up pointing at its new block).
        for b in removed:
            for tx in b.txs:
                self._tx_index.pop(tx.txid(), None)
            # Reorg event path: proofs cut for an abandoned block must
            # not linger (chain/proof.py's invalidation layer — the tx
            # index above already makes them unreachable; this makes
            # them also stop existing, the property the reorg test pins).
            self.proof_cache.invalidate_block(b.block_hash())
        for b in added:
            bh = b.block_hash()
            for tx in b.txs:
                self._tx_index[tx.txid()] = bh
        # Extend (or reorg-repair) the filter-header commitment chain in
        # the same call that moved the tip — every connect site (mining,
        # gossip, sync, store replay) funnels through here, so the
        # commitment can never lag the chain it commits to.
        self._sync_filter_headers()
        if bhash in self._invalid:
            # Indexed but contextually invalid (its transfers overdraw
            # somewhere on its branch) — callers see a rejection, and the
            # block is excluded from ``connected`` so persistence skips it.
            return AddResult(AddStatus.REJECTED, reason=self._invalid[bhash])
        return AddResult(
            AddStatus.ACCEPTED,
            removed=removed,
            added=added,
            connected=tuple(
                b for b in connected if b.block_hash() not in self._invalid
            ),
        )

    def _settle_tip(
        self, old_tip: bytes
    ) -> tuple[tuple[Block, ...], tuple[Block, ...]]:
        """Advance the ledger to the work-chosen tip, demoting invalid
        branches until a contextually valid tip wins.

        Returns the net (removed, added) paths from ``old_tip`` to the
        settled tip.  Terminates: each failed candidate marks at least one
        block permanently invalid, and ``old_tip`` itself (whose state the
        ledger currently holds) is always a valid fallback.
        """
        # Fast path — the overwhelmingly common case on the ingest hot
        # loop: the new tip is old tip's direct child (plain extension,
        # no reorg walk needed).  Same semantics as the general loop
        # below for this shape, including the invalid-branch fallback.
        if self._tip_hash != old_tip:
            candidate = self._block_at(self._tip_hash)
            if candidate.header.prev_hash == old_tip:
                try:
                    self._ledger_apply(candidate)
                    return (), (candidate,)
                except LedgerError as e:
                    self._mark_invalid_subtree(self._tip_hash, str(e))
                    self._tip_hash = self._best_valid_tip()
        while self._tip_hash != old_tip:
            removed, added = self._reorg_paths(old_tip, self._tip_hash)
            for b in removed:
                self._ledger_undo(b)
            applied: list[Block] = []
            failed: LedgerError | None = None
            for b in added:
                try:
                    self._ledger_apply(b)
                except LedgerError as e:
                    self._mark_invalid_subtree(b.block_hash(), str(e))
                    failed = e
                    break
                applied.append(b)
            if failed is None:
                return removed, added
            # Roll the ledger back to old_tip and re-run fork choice over
            # the remaining valid blocks.
            for b in reversed(applied):
                self._ledger_undo(b)
            for b in reversed(removed):
                self._ledger_apply(b)
            self._tip_hash = self._best_valid_tip()
        return (), ()

    def _mark_invalid_subtree(self, bhash: bytes, reason: str) -> None:
        """Permanently invalidate ``bhash`` and every indexed descendant."""
        pending = [(bhash, reason)]
        while pending:
            h, why = pending.pop()
            if h in self._invalid:
                continue
            self._invalid[h] = why
            pending.extend(
                (c, "descends from invalid block")
                for c in self._children_of(h)
            )

    def _best_valid_tip(self) -> bytes:
        """Most-work non-invalid block (smaller hash on ties) — the same
        ordering ``_insert`` applies incrementally, re-derived over the
        whole index.  Only runs when a branch was just invalidated."""
        best_hash, best = None, None
        for h, entry in self._index.items():
            if h in self._invalid:
                continue
            if (
                best is None
                or entry.work > best.work
                or (entry.work == best.work and h < best_hash)
            ):
                best_hash, best = h, entry
        assert best_hash is not None  # genesis is always valid
        return best_hash

    def _insert(
        self, block: Block, prevalidated: bool = False
    ) -> tuple[AddStatus, str]:
        """Validate + index one block and advance the tip by work."""
        bhash = block.block_hash()
        if bhash in self._index:
            return AddStatus.DUPLICATE, ""
        prev = self._index.get(block.header.prev_hash)
        if prev is None:
            return self._park_orphan(block, bhash)
        # Contextual header rules — they need the parent, so they run here
        # even for prevalidated orphans (parking could only check the
        # block's internal consistency).
        expected = self._expected_difficulty(prev)
        if block.header.difficulty != expected:
            return AddStatus.REJECTED, (
                f"difficulty {block.header.difficulty} != required {expected}"
            )
        if self.retarget is not None:
            # Strict increase (positive retarget spans; time-freezing
            # unprofitable) + the forward-dating cap with its height-1
            # bootstrap-anchor exemption — the rule lives in ONE place,
            # RetargetRule.timestamp_violation, shared with the replay
            # verifier and the miner's clamp.
            reason = self.retarget.timestamp_violation(
                prev.height,
                prev.header.timestamp,
                block.header.timestamp,
            )
            if reason is not None:
                return AddStatus.REJECTED, reason
        if not prevalidated:
            try:
                self.check_block(
                    block,
                    expected,
                    chain_tag=self.genesis.block_hash(),
                    sig_cache=self.sig_cache,
                )
            except ValidationError as e:
                return AddStatus.REJECTED, str(e)
        entry = _Entry(
            block,
            block.header,
            prev.height + 1,
            prev.work + (1 << block.header.difficulty),
        )
        self._index[bhash] = entry
        # Body residency accounting (memory-bounded operation): the
        # serialized length is a cached-bytes len for wire/disk-ingested
        # blocks (encoding cache) and needed for store/gossip anyway for
        # local ones — the gauge costs the hot path nothing.
        self.resident_body_bytes += len(block.serialize())
        self._resident_fifo.append(bhash)
        kids = self._children.get(block.header.prev_hash)
        if kids is None:
            self._children[block.header.prev_hash] = bhash
        elif isinstance(kids, bytes):
            self._children[block.header.prev_hash] = [kids, bhash]
        else:
            kids.append(bhash)
        if block.header.prev_hash in self._invalid:
            # An extension of an invalid branch is invalid by inheritance —
            # index it (dedup/duplicate detection) but never offer it as tip.
            self._invalid[bhash] = "descends from invalid block"
            return AddStatus.ACCEPTED, ""
        tip = self._index[self._tip_hash]
        if entry.work > tip.work or (
            entry.work == tip.work and bhash < self._tip_hash
        ):
            self._tip_hash = bhash
        return AddStatus.ACCEPTED, ""

    # -- memory-bounded operation (body eviction) -------------------------

    def evict_bodies(self, keep_recent: int) -> int:
        """Evict block bodies below the keep window, keeping headers and
        every index structure intact; returns bytes freed.

        Eviction policy, not correctness: only bodies the ``body_source``
        can re-serve (``has_body`` — i.e. durably in the append-only
        store) are dropped, and the last ``keep_recent`` heights stay hot
        (the tip region serves gossip, reorgs, and mining; deep history
        serves only the occasional IBD peer, which can afford the
        refetch).  Side branches below the window evict on the same
        terms.  The sweep walks the insertion-ordered candidate deque,
        so repeated calls cost O(resident), not O(index)."""
        if self.body_source is None or keep_recent < 1:
            return 0
        floor = self.height - keep_recent
        freed = 0
        keep: collections.deque[bytes] = collections.deque()
        while self._resident_fifo:
            bhash = self._resident_fifo.popleft()
            entry = self._index.get(bhash)
            if entry is None or entry.block is None:
                continue  # stale candidate (already evicted)
            if entry.height > floor or not self.body_source.has_body(bhash):
                keep.append(bhash)  # hot window, or not yet durable
                continue
            blen = len(entry.block.serialize())
            entry.block = None
            try:
                # The header's memoized 80-byte encoding goes with the
                # body: repacking is byte-identical (canonical fixed
                # width, tested) and deep-history header serves are rare
                # — another ~113 B/block the evicted region doesn't pin.
                object.__delattr__(entry.header, "_raw")
            except AttributeError:
                pass
            self.resident_body_bytes -= blen
            self.bodies_evicted += 1
            freed += blen
        self._resident_fifo = keep
        return freed

    # -- live re-basing (round 20: the always-on node) --------------------

    def rebase(self, new_base: int) -> dict:
        """Advance the chain's base to ``new_base`` IN PLACE — the
        long-running-node move ``from_snapshot`` performs only at boot:
        everything strictly below the new base (and every side branch
        not descending from it) leaves the in-RAM index, and the chain
        behaves from here on exactly like one booted from a snapshot
        anchored at ``new_base``.  The ledger, tip, and mining are
        untouched — this runs on a live node between awaits.

        Caller's contract (Node.rebase owns it): the history being
        dropped must already be durable and servable from disk — sealed
        segments with their ``.hdrx`` sidecars written
        (``SegmentedStore.ensure_sidecars``) — because after this call
        the only copy of those headers this process can serve is the
        disk plane.

        ``new_base`` must be a checkpoint-interval multiple with a
        recorded state root: the interval equals the retarget window on
        retargeting chains, so ``_expected_difficulty``'s window walk
        can never cross the new base (the same alignment
        ``from_snapshot`` chains rely on), and the recorded root is
        what continuous snapshot publication anchors to.

        Returns ``{"old_base", "new_base", "dropped_blocks",
        "freed_bytes"}``.
        """
        if not self.base_height < new_base <= self.height:
            raise ValueError(
                f"rebase target {new_base} outside "
                f"({self.base_height}, {self.height}]"
            )
        if new_base % self.checkpoint_interval != 0:
            raise ValueError(
                f"rebase target {new_base} not on the "
                f"{self.checkpoint_interval}-block checkpoint cadence"
            )
        if new_base not in self.state_checkpoints:
            raise ValueError(
                f"no recorded state root at rebase target {new_base}"
            )
        base_hash = self._main_hashes[new_base - self.base_height]
        keep = {base_hash}
        pending = [base_hash]
        while pending:
            for c in self._children_of(pending.pop()):
                if c not in keep:
                    keep.add(c)
                    pending.append(c)
        dropped = [h for h in self._index if h not in keep]
        freed = 0
        for h in dropped:
            entry = self._index.pop(h)
            if entry.block is not None and h != self._base_body_unaccounted:
                freed += len(entry.block.serialize())
            self.proof_cache.invalidate_block(h)
            # Orphans parked on a dropped block can never connect —
            # nothing below the base can ever re-index (its parent is
            # gone too, recursively).  Their FIFO entries go stale and
            # the existing sweep skips them.
            for orphan in self._orphans.pop(h, []):
                self._orphan_hashes.discard(orphan.block_hash())
        self.resident_body_bytes -= freed
        self._base_body_unaccounted = None
        self._tx_index = {
            t: h for t, h in self._tx_index.items() if h in keep
        }
        self._children = {
            h: kids for h, kids in self._children.items() if h in keep
        }
        self._invalid = {
            h: why for h, why in self._invalid.items() if h in keep
        }
        self.state_checkpoints = {
            h: r for h, r in self.state_checkpoints.items() if h >= new_base
        }
        self.checkpoint_extra = {
            h for h in self.checkpoint_extra if h > new_base
        }
        old_base = self.base_height
        self._main_hashes = self._main_hashes[new_base - self.base_height :]
        self.base_height = new_base
        return {
            "old_base": old_base,
            "new_base": new_base,
            "dropped_blocks": len(dropped),
            "freed_bytes": freed,
        }

    # -- internals -------------------------------------------------------

    def _children_of(self, bhash: bytes) -> tuple[bytes, ...]:
        """``bhash``'s indexed children, normalized over the compact
        one-child representation."""
        kids = self._children.get(bhash)
        if kids is None:
            return ()
        if isinstance(kids, bytes):
            return (kids,)
        return tuple(kids)

    def _park_orphan(self, block: Block, bhash: bytes) -> tuple[AddStatus, str]:
        """Hold a parentless block until its parent arrives — safely.

        The block must carry its own valid PoW (full stateless validation)
        before it costs us memory, and the pool is FIFO-capped: unconnectable
        junk from a hostile peer evicts, it does not accumulate.

        On a retargeting chain the parent-dependent required difficulty is
        unknowable here, so parking checks PoW at the block's *claimed*
        difficulty and ``_insert`` re-checks the claim against the parent
        when the orphan connects.  A flood of cheap low-difficulty orphans
        is still bounded by the FIFO cap — it can churn the pool, never
        grow it — and a genuine gap is backfilled by locator sync anyway.
        """
        if bhash in self._orphan_hashes:
            return AddStatus.ORPHAN, "already parked"
        claimed = (
            block.header.difficulty
            if self.retarget is not None
            else self.difficulty
        )
        if claimed < 1:
            # Difficulty 0 passes every PoW check vacuously — a literally
            # free frame must not be able to evict orphans that cost real
            # work (same floor as proof.py's SPV check).
            return AddStatus.REJECTED, "difficulty-0 block carries no work"
        try:
            self.check_block(
                block,
                claimed,
                chain_tag=self.genesis.block_hash(),
                sig_cache=self.sig_cache,
            )
        except ValidationError as e:
            return AddStatus.REJECTED, str(e)
        self._orphans.setdefault(block.header.prev_hash, []).append(block)
        self._orphan_hashes.add(bhash)
        self._orphan_fifo.append((block.header.prev_hash, bhash))
        while len(self._orphan_hashes) > MAX_ORPHANS:
            self._evict_oldest_orphan()
        return AddStatus.ORPHAN, ""

    def _evict_oldest_orphan(self) -> None:
        while self._orphan_fifo:
            prev_hash, bhash = self._orphan_fifo.popleft()
            if bhash not in self._orphan_hashes:
                continue  # stale entry: orphan was connected meanwhile
            waiting = self._orphans.get(prev_hash, [])
            for i, blk in enumerate(waiting):
                if blk.block_hash() == bhash:
                    waiting.pop(i)
                    break
            if not waiting:
                self._orphans.pop(prev_hash, None)
            self._orphan_hashes.discard(bhash)
            return

    def _on_main_chain(self, block_hash: bytes) -> bool:
        entry = self._index[block_hash]
        i = entry.height - self.base_height
        return 0 <= i < len(self._main_hashes) and self._main_hashes[i] == block_hash

    def _reorg_paths(
        self, old_tip: bytes, new_tip: bytes
    ) -> tuple[tuple[Block, ...], tuple[Block, ...]]:
        """(removed old-tip-first, added fork-point-first) between two tips."""
        a, b = old_tip, new_tip
        removed: list[Block] = []
        added: list[Block] = []
        while self._index[a].height > self._index[b].height:
            removed.append(self._block_at(a))
            a = self._index[a].header.prev_hash
        while self._index[b].height > self._index[a].height:
            added.append(self._block_at(b))
            b = self._index[b].header.prev_hash
        while a != b:
            removed.append(self._block_at(a))
            added.append(self._block_at(b))
            a = self._index[a].header.prev_hash
            b = self._index[b].header.prev_hash
        return tuple(removed), tuple(reversed(added))
