"""Offline store maintenance: the `p1 compact` and `p1 fsck` engines.

Extracted from ``cli.py`` (which keeps only parsing + dispatch).  Both
commands keep their CLI contract exactly: JSON report on stdout, human
diagnostics on stderr, and the documented exit codes (`p1 fsck`: 0 clean
/ 1 salvaged / 2 unrecoverable; `p1 compact`: 0 ok / 2 refused / 3
snapshot self-check failed).
"""

from __future__ import annotations

import json
import os
import sys


def load_store(
    path: str, expected_difficulty: int | None = None, retarget=None
):
    """(blocks, chain) from a persisted store, difficulty inferred from the
    records (every block declares the chain difficulty — validation
    enforces it — so the store is self-describing; the retarget rule is
    NOT, so retarget chains need their flags).  Raises SystemExit 2 for an
    empty/missing store, an ``expected_difficulty`` mismatch, or records
    that do not connect to the selected genesis (wrong retarget flags)."""
    from p1_tpu.chain import ChainStore

    store = ChainStore(path)
    try:
        blocks = store.load_blocks()
    finally:
        store.close()
    if not blocks:
        print(f"{path}: empty or missing chain store", file=sys.stderr)
        raise SystemExit(2)
    stored = blocks[0].header.difficulty
    if expected_difficulty is not None and expected_difficulty != stored:
        # A wrong flag would otherwise silently yield an empty chain.
        print(
            f"--difficulty {expected_difficulty} does not match the store's "
            f"chain (difficulty {stored})",
            file=sys.stderr,
        )
        raise SystemExit(2)
    try:
        chain = store.load_chain(stored, blocks, retarget=retarget)
    except ValueError as e:  # none-connected guard (store.py)
        print(str(e), file=sys.stderr)
        raise SystemExit(2)
    return blocks, chain


def run_balances(
    store_path: str,
    account: str | None,
    expected_difficulty: int | None = None,
    retarget=None,
) -> int:
    """`p1 balances`: account balances from a persisted chain, plus the
    offline conservation audit when no single account is selected."""
    from p1_tpu.chain import balances

    _, chain = load_store(
        store_path, expected_difficulty, retarget=retarget
    )
    ledger = balances(chain.main_chain())
    if account is not None:
        print(
            json.dumps(
                {
                    "config": "balances",
                    "height": chain.height,
                    "account": account,
                    "balance": ledger.get(account, 0),
                }
            )
        )
        return 0
    # Offline audit: the store loads through full consensus validation, so
    # the view must agree with the incremental ledger, hold nothing
    # negative, and conserve exactly — total = coinbase minted minus the
    # fees burned by the rare coinbase-less blocks.  A False here means a
    # corrupted store or a consensus bug — surface it in the exit code.
    minted = burned = 0
    for b in chain.main_chain():
        if b.txs and b.txs[0].is_coinbase:
            minted += b.txs[0].amount
        else:
            burned += sum(t.fee for t in b.txs)
    conserved = (
        sum(ledger.values()) == minted - burned
        and all(v >= 0 for v in ledger.values())
        and {a: v for a, v in ledger.items() if v} == chain.balances_snapshot()
    )
    print(
        json.dumps(
            {
                "config": "balances",
                "height": chain.height,
                "conserved": conserved,
                "balances": dict(sorted(ledger.items())),
            }
        )
    )
    return 0 if conserved else 1


def run_snapshot(
    action: str,
    store_path: str | None,
    file_path: str | None,
    interval: int = 0,
    retarget=None,
) -> int:
    """`p1 snapshot` engine — the established exit-code contract:

    - **create** (``--store`` → ``--file``): materialize the store's
      latest checkpoint state (balances + nonces + merkle state root +
      anchor block) into a CRC-framed snapshot file.  Exit 0 written /
      2 unrecoverable (bad store, or no checkpoint height yet).
    - **verify** (``--file``): full integrity pass — framing, manifest,
      chunk digests, state root.  Exit 0 clean / 1 salvageable issue
      (framing noise past a complete verified snapshot) / 2
      unrecoverable.
    - **info** (``--file``): print the manifest (no chunk verification).
      Exit 0 / 2 unreadable.

    The verify/info reports spell out the trust model: a verified
    snapshot proves the FILE matches its own manifest — whether the
    state is true is only provable by replaying the chain's history
    (what a node's background revalidation does before flipping out of
    the ASSUMED state)."""
    from p1_tpu.chain import snapshot as chain_snapshot

    if action == "create":
        if not store_path or not file_path:
            print("snapshot create needs --store and --file", file=sys.stderr)
            return 2
        _, chain = load_store(store_path, retarget=retarget)
        if interval > 0:
            chain.checkpoint_interval = interval
            # Recorded roots followed the default cadence during the
            # load; re-derive the requested height from the rollback
            # path (snapshot_state cross-checks any recorded root).
            chain.state_checkpoints.clear()
        state = chain.snapshot_state()
        if state is None:
            print(
                f"{store_path}: chain height {chain.height} holds no "
                f"checkpoint at interval {chain.checkpoint_interval} — "
                "nothing to snapshot",
                file=sys.stderr,
            )
            return 2
        height, block, balances, nonces, root = state
        manifest_payload, chunks = chain_snapshot.build_records(
            height, block, balances, nonces
        )
        try:
            chain_snapshot.write_snapshot(file_path, manifest_payload, chunks)
        except OSError as e:
            print(f"could not write {file_path}: {e}", file=sys.stderr)
            return 2
        print(
            json.dumps(
                {
                    "config": "snapshot",
                    "action": "create",
                    "store": store_path,
                    "file": file_path,
                    "height": height,
                    "block_hash": block.block_hash().hex(),
                    "state_root": root.hex(),
                    "accounts": len(
                        set(balances) | set(nonces)
                    ),
                    "chunks": len(chunks),
                    "bytes": os.path.getsize(file_path),
                }
            )
        )
        return 0
    if not file_path:
        print(f"snapshot {action} needs --file", file=sys.stderr)
        return 2
    if action == "verify":
        report = chain_snapshot.verify_file(file_path)
        verdict = report.pop("verdict")
        print(json.dumps({"config": "snapshot", "action": "verify", **report}))
        return verdict
    # info
    try:
        manifest_payload, chunk_payloads, issues = chain_snapshot.read_records(
            file_path
        )
        manifest = chain_snapshot.parse_manifest(manifest_payload)
    except (OSError, chain_snapshot.SnapshotError) as e:
        print(str(e), file=sys.stderr)
        return 2
    print(
        json.dumps(
            {
                "config": "snapshot",
                "action": "info",
                "file": file_path,
                "height": manifest.height,
                "block_hash": manifest.block_hash.hex(),
                "state_root": manifest.state_root.hex(),
                "accounts": manifest.accounts,
                "chunks": len(manifest.chunk_digests),
                "chunks_present": len(chunk_payloads),
                "issues": issues,
                "trust": "integrity proves the file matches its manifest; "
                "the STATE is unproven until a node replays the history "
                "(ASSUMED -> VALIDATED flip)",
            }
        )
    )
    return 0


def run_compact(
    store_path: str,
    out_path: str | None,
    retarget=None,
    store_cls=None,
) -> int:
    """Store maintenance: the append-only log keeps every side branch and
    reorged-away block forever (that's what makes restarts deterministic);
    compaction snapshots just the current main branch, shrinking the file
    while resume behavior for the surviving chain is unchanged.

    Segmented stores compact PER SEGMENT: only segments holding records
    off the current main branch are rewritten (tmp + rename + dir-fsync
    each), clean segments' bytes are never touched — O(dirty), not
    O(chain).  ``store_cls`` is the fault-injection seam for the
    single-file snapshot write (tests drive ENOSPC through it)."""
    from p1_tpu.chain import ChainStore, save_chain
    from p1_tpu.chain.segstore import is_segmented

    if not os.path.exists(store_path):
        print(f"{store_path}: empty or missing chain store", file=sys.stderr)
        return 2
    if is_segmented(store_path):
        return _compact_segmented(store_path, out_path, retarget=retarget)
    # Lock FIRST, then load: records appended between an unlocked read and
    # the rewrite would be silently dropped, and replacing the inode under
    # a live node would orphan everything it appends afterwards.
    src = ChainStore(store_path)
    try:
        try:
            # allow_v2: compaction IS the upgrade path for pre-checksum
            # stores (the snapshot below is written in v3 framing).
            src.acquire(allow_v2=True)
        except RuntimeError as e:
            print(f"{e} — stop it before compacting", file=sys.stderr)
            return 2
        blocks = src.load_blocks()
        if not blocks:
            print(f"{store_path}: empty chain store", file=sys.stderr)
            return 2
        try:
            chain = src.load_chain(
                blocks[0].header.difficulty,
                blocks,
                retarget=retarget,
            )
        except ValueError as e:
            # Without this, compacting a retarget store with forgotten
            # flags would REPLACE it with a genesis-only snapshot of the
            # wrong chain — the one unrecoverable failure mode here.
            print(str(e), file=sys.stderr)
            return 2
        before = os.path.getsize(store_path)
        out = out_path or store_path
        dst = None
        if out_path and os.path.realpath(out) != os.path.realpath(store_path):
            # The destination needs the same in-use guard: replacing it
            # would orphan a live node's inode there.
            dst = ChainStore(out)
            try:
                dst.acquire()
            except RuntimeError as e:
                print(f"{e} — stop it before overwriting", file=sys.stderr)
                return 2
        else:
            out = store_path
        try:
            # Always write a sibling temp file and atomically replace, so
            # a crash mid-write can never leave EITHER path deleted or
            # truncated.
            tmp = f"{out}.compact.{os.getpid()}"
            try:
                save_chain(
                    chain,
                    tmp,
                    **({"store_cls": store_cls} if store_cls else {}),
                )
            except OSError as e:
                # ENOSPC/EIO mid-rewrite: the ORIGINAL store was never
                # touched (we only wrote the sibling tmp) — remove the
                # partial tmp and report, leaving the log byte-identical
                # and the writer flock released by the finally below.
                if os.path.exists(tmp):
                    os.unlink(tmp)
                print(
                    f"compaction write failed ({e}) — original store "
                    "left untouched",
                    file=sys.stderr,
                )
                return 2
            # Prove the snapshot BEFORE it replaces the original: the
            # main branch is linear, so its packed headers verify (PoW +
            # linkage + difficulty) in one native call straight off the
            # bytes just written — a torn or miswritten snapshot can
            # never clobber a good log.
            from p1_tpu.chain import replay_packed

            raw_headers, n_headers = ChainStore(tmp).packed_headers()
            snap = replay_packed(raw_headers, retarget=retarget)
            if not snap.valid:
                os.unlink(tmp)
                print(
                    f"snapshot self-check failed at record "
                    f"{snap.first_invalid} of {n_headers} — original store "
                    "left untouched",
                    file=sys.stderr,
                )
                return 3
            os.replace(tmp, out)
            # The rename itself must survive a metadata-journal loss:
            # save_chain fsynced the tmp's data and directory entry, but
            # the replace is a second directory mutation.
            from p1_tpu.chain.store import fsync_dir

            fsync_dir(os.path.dirname(os.path.abspath(out)))
        finally:
            if dst is not None:
                dst.close()
    finally:
        src.close()
    print(
        json.dumps(
            {
                "config": "compact",
                "height": chain.height,
                "records_before": len(blocks),
                "records_after": chain.height + 1,
                "bytes_before": before,
                "bytes_after": os.path.getsize(out),
                "out": out,
            }
        )
    )
    return 0


def _compact_segmented(
    store_path: str, out_path: str | None, retarget=None
) -> int:
    """Per-segment compaction: drop records off the current main branch,
    rewriting ONLY the segments that hold any (tmp + rename + dir-fsync
    per segment — a crash at any point leaves every segment either old
    or new, never half-written).  ``--out`` is refused: a segmented
    store is a directory of bounded files, compacted in place by
    design."""
    from p1_tpu.chain.segstore import SegmentedStore
    from p1_tpu.chain.store import _CRC, _LEN, MAGIC, ChainStore, fsync_dir
    from p1_tpu.core.hashutil import sha256d

    if out_path:
        print(
            "segmented stores compact in place (bounded per-segment "
            "rewrites); --out applies to single-file stores only",
            file=sys.stderr,
        )
        return 2
    store = SegmentedStore(store_path)
    try:
        try:
            store.acquire()
        except RuntimeError as e:
            print(f"{e} — stop it before compacting", file=sys.stderr)
            return 2
        blocks = store.load_blocks()
        if not blocks:
            print(f"{store_path}: empty chain store", file=sys.stderr)
            return 2
        try:
            chain = store.load_chain(
                blocks[0].header.difficulty,
                blocks,
                retarget=retarget,
                orphans_ok=store.pruned_below > 0,
            )
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2
        main = set()
        h = chain.tip_hash
        while h in chain:
            main.add(h)
            hdr = chain.header_of(h)
            if hdr is None or chain.height_of(h) == chain.base_height:
                break
            h = hdr.prev_hash
        # With a pruned store the surviving records park as orphans off
        # the missing history: treat every connected record as keepable
        # (compaction must never widen a prune's loss).
        if store.pruned_below > 0:
            main.update(b.block_hash() for b in blocks)
        before_records = len(blocks)
        rewritten = kept = 0
        for seg, scan in store.scan_segments():
            if scan is None or not scan.spans:
                continue
            path = store._seg_path(seg)
            data = path.read_bytes()
            frames = []
            for off, n in scan.spans:
                if sha256d(data[off : off + 80]) in main:
                    frames.append(data[off - _LEN.size : off + n + _CRC.size])
            kept += len(frames)
            if len(frames) == len(scan.spans):
                continue  # clean segment: bytes never touched
            tmp = path.with_name(f"{path.name}.seg.{os.getpid()}")
            try:
                with open(tmp, "wb") as f:
                    f.write(MAGIC)
                    for frame in frames:
                        f.write(frame)
                    f.flush()
                    os.fsync(f.fileno())
                # Self-check the rewrite before it replaces anything.
                vscan = ChainStore.scan(tmp.read_bytes())
                if not vscan.clean or len(vscan.spans) != len(frames):
                    raise OSError("segment self-check failed")
            except OSError as e:
                if tmp.exists():
                    tmp.unlink()
                print(
                    f"compaction write failed ({e}) — {path} left "
                    "untouched",
                    file=sys.stderr,
                )
                return 2
            os.replace(tmp, path)
            fsync_dir(path.parent)
            seg.records = len(frames)
            seg.bytes = os.path.getsize(path)
            if seg.sealed:
                # The packed-header sidecar mirrors the new record set.
                from p1_tpu.chain.headerplane import write_segment_index

                write_segment_index(
                    path.read_bytes(), store.hdrx_path(seg)
                )
            rewritten += 1
        store._write_manifest()
        store.reindex_spans()
    finally:
        store.close()
    print(
        json.dumps(
            {
                "config": "compact",
                "layout": "segmented",
                "height": chain.height,
                "records_before": before_records,
                "records_after": kept,
                "segments": len(store.segments),
                "segments_rewritten": rewritten,
                "out": store_path,
            }
        )
    )
    return 0


def _fsck_segmented(store_path: str, json_out: bool) -> int:
    """Per-segment fsck: scan/report, then salvage ONLY the segments
    that need it — mid-log corruption loses at most one segment's bad
    span, and no other segment's bytes are ever rewritten.  Same exit
    contract (0 clean / 1 salvaged / 2 unrecoverable); the JSON report
    carries one row per segment with its own verdict."""
    from p1_tpu.chain.segstore import SegmentedStore, _torn_magic
    from p1_tpu.chain.store import ChainStore

    store = SegmentedStore(store_path)
    lf = None
    try:
        import fcntl

        store.path.parent.mkdir(parents=True, exist_ok=True)
        lf = open(store.lock_path, "a+b")
        try:
            # Lock first (a live node's appends must not race a
            # salvage), scan without healing: fsck reports BEFORE it
            # mutates, per segment.
            fcntl.flock(lf, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            print(
                f"{store.path} is locked by another process (a running "
                "node?)",
                file=sys.stderr,
            )
            return 2
        rows = []
        worst = 0
        salvaged_any = False
        segments = store._segments_for_read()
        if not segments:
            print(
                f"{store_path}: unreadable manifest and no segments",
                file=sys.stderr,
            )
            return 2
        for seg, scan in store.scan_segments():
            row = {
                "segment": seg.name,
                "pruned": seg.pruned,
                "sealed": seg.sealed,
            }
            if scan is None:
                row["verdict"] = 0  # pruned body: nothing to scan
                rows.append(row)
                continue
            row.update(
                records_valid=len(scan.spans),
                bad_spans=len(scan.bad_spans),
                bytes_quarantined=scan.quarantined_bytes,
                torn_tail_bytes=(
                    scan.size - scan.torn_tail
                    if scan.torn_tail is not None
                    else 0
                ),
            )
            if scan.clean:
                row["verdict"] = 0
                rows.append(row)
                continue
            # Salvage this segment only: quarantine + rebuild / truncate.
            path = store._seg_path(seg)
            data = store._read_bytes_path(path)
            if _torn_magic(data):
                os.truncate(path, 0)
                row["verdict"] = 1
                row["records_salvaged"] = 0
                salvaged_any = True
                rows.append(row)
                continue
            if scan.bad_spans:
                store._heal_segment(path, data, scan)
            if scan.torn_tail is not None:
                os.truncate(path, scan.torn_tail)
            vscan = ChainStore.scan(store._read_bytes_path(path))
            if not vscan.clean:
                row["verdict"] = 2
                worst = 2
            else:
                row["verdict"] = 1
                row["records_salvaged"] = len(vscan.spans)
                salvaged_any = True
            rows.append(row)
        status = (
            "unrecoverable"
            if worst == 2
            else ("salvaged" if salvaged_any else "clean")
        )
        report = {
            "config": "fsck",
            "store": store_path,
            "layout": "segmented",
            "pruned_below": store.pruned_below,
            "segments": rows,
            "status": status,
        }
        print(json.dumps(report))
        return 2 if worst == 2 else (1 if salvaged_any else 0)
    finally:
        if lf is not None:
            lf.close()
        store.close()


def run_fsck(
    store_path: str, out_path: str | None, json_out: bool = False
) -> int:
    """Offline store integrity scan + salvage (the disk counterpart of
    Bitcoin's -checkblocks/salvagewallet tooling).  Exit contract:

    - **0 clean** — every record checksum-valid, nothing rewritten (a
      lossless v2→v3 upgrade also exits 0: no information was lost);
    - **1 salvaged** — corruption or a torn tail was found; every
      checksum-valid record was rewritten into a fresh verified store,
      bad spans quarantined to the ``.quarantine`` sidecar;
    - **2 unrecoverable** — missing/empty/locked store, unrecognizable
      magic, or zero salvageable records.

    Unlike ``p1 compact`` this preserves insertion order and side
    branches (it salvages the LOG, not the main branch), so the
    self-check is framing-level — every salvaged record re-reads
    checksum-valid and byte-identical — rather than the linear-chain
    ``replay_packed`` proof compaction can afford.

    Segmented stores (chain/segstore.py) scan and salvage PER SEGMENT —
    ``_fsck_segmented``; ``json_out`` (`p1 fsck --json`) emits the
    machine-readable per-segment report (one row per segment with its
    own verdict/spans/salvage counts) for both layouts, same exit
    codes."""
    import struct

    from p1_tpu.chain import ChainStore
    from p1_tpu.chain.segstore import is_segmented
    from p1_tpu.chain.store import fsync_dir
    from p1_tpu.core.block import Block

    if not os.path.exists(store_path) or os.path.getsize(store_path) == 0:
        print(f"{store_path}: empty or missing chain store", file=sys.stderr)
        return 2
    if is_segmented(store_path):
        if out_path:
            print(
                "segmented stores salvage in place (bounded per-segment "
                "rewrites); --out applies to single-file stores only",
                file=sys.stderr,
            )
            return 2
        return _fsck_segmented(store_path, json_out)

    def _emit(report: dict, status: str, verdict: int) -> None:
        """One print, two shapes: the legacy flat report (default), or
        the --json per-segment shape shared with segmented stores."""
        if not json_out:
            print(json.dumps({**report, "status": status}))
            return
        row = {
            "segment": os.path.basename(report["store"]),
            "pruned": False,
            "sealed": False,
            "verdict": verdict,
            "records_valid": report["records_valid"],
            "bad_spans": report["bad_spans"],
            "bytes_quarantined": report["bytes_quarantined"],
            "torn_tail_bytes": report["torn_tail_bytes"],
        }
        if "records_salvaged" in report:
            row["records_salvaged"] = report["records_salvaged"]
        print(
            json.dumps(
                {
                    "config": "fsck",
                    "store": report["store"],
                    "layout": "single",
                    "version": report["version"],
                    "segments": [row],
                    "status": status,
                }
            )
        )

    store = ChainStore(store_path)
    try:
        try:
            # Lock first (a live node's in-flight appends must not race
            # the rewrite), scan without healing: fsck owns the salvage
            # decision and must report BEFORE mutating.
            store.acquire(allow_v2=True, heal=False)
        except RuntimeError as e:
            print(str(e), file=sys.stderr)
            return 2
        data = store._read_bytes()
        scan = store.scan(data)
        report = {
            "config": "fsck",
            "store": store_path,
            "version": scan.version,
            "records_valid": len(scan.spans),
            "bad_spans": len(scan.bad_spans),
            "bytes_quarantined": scan.quarantined_bytes,
            "torn_tail_bytes": (
                scan.size - scan.torn_tail if scan.torn_tail is not None else 0
            ),
        }
        if scan.version == 3 and scan.clean:
            _emit(report, "clean", 0)
            return 0

        # Salvage: every checksum-valid record that still parses as a
        # block, in original insertion order, into a fresh v3 store.
        blocks, parse_failures = [], 0
        for off, n in scan.spans:
            try:
                blocks.append(Block.deserialize(data[off : off + n]))
            except ValueError:
                parse_failures += 1
        report["parse_failures"] = parse_failures
        if not blocks:
            _emit(report, "unrecoverable", 2)
            print(
                f"{store_path}: no salvageable records", file=sys.stderr
            )
            return 2
        if scan.bad_spans:
            # Evidence first, durably, before the original bytes go away.
            qpath = store.quarantine_path()
            with open(qpath, "ab") as qf:
                for s, e in scan.bad_spans:
                    qf.write(struct.pack(">QI", s, e - s))
                    qf.write(data[s:e])
                qf.flush()
                os.fsync(qf.fileno())
            report["quarantine"] = str(qpath)
        out = out_path or store_path
        tmp = f"{out}.fsck.{os.getpid()}"
        dst = ChainStore(tmp, fsync=False)
        try:
            for block in blocks:
                dst.append(block)
            dst.sync()
            dst._fsync_dir()
        finally:
            dst.close()
        # Self-check BEFORE the replace: the fresh store must re-scan
        # clean with every record byte-identical to what was salvaged —
        # a miswritten salvage must never clobber the evidence.
        vdata = ChainStore(tmp)._read_checked()
        vscan = ChainStore.scan(vdata)
        ok = (
            vscan.version == 3
            and vscan.clean
            and len(vscan.spans) == len(blocks)
            and all(
                vdata[off : off + n] == block.serialize()
                for (off, n), block in zip(vscan.spans, blocks)
            )
        )
        if not ok:
            os.unlink(tmp)
            print(
                "salvage self-check failed — original store left untouched",
                file=sys.stderr,
            )
            return 2
        os.replace(tmp, out)
        fsync_dir(os.path.dirname(os.path.abspath(out)))
        lossless = (
            not scan.bad_spans
            and scan.torn_tail is None
            and not parse_failures
        )
        report.update(
            {
                "records_salvaged": len(blocks),
                "out": out,
            }
        )
        _emit(
            report,
            "upgraded" if lossless else "salvaged",
            0 if lossless else 1,
        )
        return 0 if lossless else 1
    finally:
        store.close()
