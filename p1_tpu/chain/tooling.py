"""Offline store maintenance: the `p1 compact` and `p1 fsck` engines.

Extracted from ``cli.py`` (which keeps only parsing + dispatch).  Both
commands keep their CLI contract exactly: JSON report on stdout, human
diagnostics on stderr, and the documented exit codes (`p1 fsck`: 0 clean
/ 1 salvaged / 2 unrecoverable; `p1 compact`: 0 ok / 2 refused / 3
snapshot self-check failed).
"""

from __future__ import annotations

import json
import os
import sys


def load_store(
    path: str, expected_difficulty: int | None = None, retarget=None
):
    """(blocks, chain) from a persisted store, difficulty inferred from the
    records (every block declares the chain difficulty — validation
    enforces it — so the store is self-describing; the retarget rule is
    NOT, so retarget chains need their flags).  Raises SystemExit 2 for an
    empty/missing store, an ``expected_difficulty`` mismatch, or records
    that do not connect to the selected genesis (wrong retarget flags)."""
    from p1_tpu.chain import ChainStore

    store = ChainStore(path)
    try:
        blocks = store.load_blocks()
    finally:
        store.close()
    if not blocks:
        print(f"{path}: empty or missing chain store", file=sys.stderr)
        raise SystemExit(2)
    stored = blocks[0].header.difficulty
    if expected_difficulty is not None and expected_difficulty != stored:
        # A wrong flag would otherwise silently yield an empty chain.
        print(
            f"--difficulty {expected_difficulty} does not match the store's "
            f"chain (difficulty {stored})",
            file=sys.stderr,
        )
        raise SystemExit(2)
    try:
        chain = store.load_chain(stored, blocks, retarget=retarget)
    except ValueError as e:  # none-connected guard (store.py)
        print(str(e), file=sys.stderr)
        raise SystemExit(2)
    return blocks, chain


def run_balances(
    store_path: str,
    account: str | None,
    expected_difficulty: int | None = None,
    retarget=None,
) -> int:
    """`p1 balances`: account balances from a persisted chain, plus the
    offline conservation audit when no single account is selected."""
    from p1_tpu.chain import balances

    _, chain = load_store(
        store_path, expected_difficulty, retarget=retarget
    )
    ledger = balances(chain.main_chain())
    if account is not None:
        print(
            json.dumps(
                {
                    "config": "balances",
                    "height": chain.height,
                    "account": account,
                    "balance": ledger.get(account, 0),
                }
            )
        )
        return 0
    # Offline audit: the store loads through full consensus validation, so
    # the view must agree with the incremental ledger, hold nothing
    # negative, and conserve exactly — total = coinbase minted minus the
    # fees burned by the rare coinbase-less blocks.  A False here means a
    # corrupted store or a consensus bug — surface it in the exit code.
    minted = burned = 0
    for b in chain.main_chain():
        if b.txs and b.txs[0].is_coinbase:
            minted += b.txs[0].amount
        else:
            burned += sum(t.fee for t in b.txs)
    conserved = (
        sum(ledger.values()) == minted - burned
        and all(v >= 0 for v in ledger.values())
        and {a: v for a, v in ledger.items() if v} == chain.balances_snapshot()
    )
    print(
        json.dumps(
            {
                "config": "balances",
                "height": chain.height,
                "conserved": conserved,
                "balances": dict(sorted(ledger.items())),
            }
        )
    )
    return 0 if conserved else 1


def run_snapshot(
    action: str,
    store_path: str | None,
    file_path: str | None,
    interval: int = 0,
    retarget=None,
) -> int:
    """`p1 snapshot` engine — the established exit-code contract:

    - **create** (``--store`` → ``--file``): materialize the store's
      latest checkpoint state (balances + nonces + merkle state root +
      anchor block) into a CRC-framed snapshot file.  Exit 0 written /
      2 unrecoverable (bad store, or no checkpoint height yet).
    - **verify** (``--file``): full integrity pass — framing, manifest,
      chunk digests, state root.  Exit 0 clean / 1 salvageable issue
      (framing noise past a complete verified snapshot) / 2
      unrecoverable.
    - **info** (``--file``): print the manifest (no chunk verification).
      Exit 0 / 2 unreadable.

    The verify/info reports spell out the trust model: a verified
    snapshot proves the FILE matches its own manifest — whether the
    state is true is only provable by replaying the chain's history
    (what a node's background revalidation does before flipping out of
    the ASSUMED state)."""
    from p1_tpu.chain import snapshot as chain_snapshot

    if action == "create":
        if not store_path or not file_path:
            print("snapshot create needs --store and --file", file=sys.stderr)
            return 2
        _, chain = load_store(store_path, retarget=retarget)
        if interval > 0:
            chain.checkpoint_interval = interval
            # Recorded roots followed the default cadence during the
            # load; re-derive the requested height from the rollback
            # path (snapshot_state cross-checks any recorded root).
            chain.state_checkpoints.clear()
        state = chain.snapshot_state()
        if state is None:
            print(
                f"{store_path}: chain height {chain.height} holds no "
                f"checkpoint at interval {chain.checkpoint_interval} — "
                "nothing to snapshot",
                file=sys.stderr,
            )
            return 2
        height, block, balances, nonces, root = state
        manifest_payload, chunks = chain_snapshot.build_records(
            height, block, balances, nonces
        )
        try:
            chain_snapshot.write_snapshot(file_path, manifest_payload, chunks)
        except OSError as e:
            print(f"could not write {file_path}: {e}", file=sys.stderr)
            return 2
        print(
            json.dumps(
                {
                    "config": "snapshot",
                    "action": "create",
                    "store": store_path,
                    "file": file_path,
                    "height": height,
                    "block_hash": block.block_hash().hex(),
                    "state_root": root.hex(),
                    "accounts": len(
                        set(balances) | set(nonces)
                    ),
                    "chunks": len(chunks),
                    "bytes": os.path.getsize(file_path),
                }
            )
        )
        return 0
    if not file_path:
        print(f"snapshot {action} needs --file", file=sys.stderr)
        return 2
    if action == "verify":
        report = chain_snapshot.verify_file(file_path)
        verdict = report.pop("verdict")
        print(json.dumps({"config": "snapshot", "action": "verify", **report}))
        return verdict
    # info
    try:
        manifest_payload, chunk_payloads, issues = chain_snapshot.read_records(
            file_path
        )
        manifest = chain_snapshot.parse_manifest(manifest_payload)
    except (OSError, chain_snapshot.SnapshotError) as e:
        print(str(e), file=sys.stderr)
        return 2
    print(
        json.dumps(
            {
                "config": "snapshot",
                "action": "info",
                "file": file_path,
                "height": manifest.height,
                "block_hash": manifest.block_hash.hex(),
                "state_root": manifest.state_root.hex(),
                "accounts": manifest.accounts,
                "chunks": len(manifest.chunk_digests),
                "chunks_present": len(chunk_payloads),
                "issues": issues,
                "trust": "integrity proves the file matches its manifest; "
                "the STATE is unproven until a node replays the history "
                "(ASSUMED -> VALIDATED flip)",
            }
        )
    )
    return 0


def run_compact(store_path: str, out_path: str | None, retarget=None) -> int:
    """Store maintenance: the append-only log keeps every side branch and
    reorged-away block forever (that's what makes restarts deterministic);
    compaction snapshots just the current main branch, shrinking the file
    while resume behavior for the surviving chain is unchanged."""
    from p1_tpu.chain import ChainStore, save_chain

    if not os.path.exists(store_path):
        print(f"{store_path}: empty or missing chain store", file=sys.stderr)
        return 2
    # Lock FIRST, then load: records appended between an unlocked read and
    # the rewrite would be silently dropped, and replacing the inode under
    # a live node would orphan everything it appends afterwards.
    src = ChainStore(store_path)
    try:
        try:
            # allow_v2: compaction IS the upgrade path for pre-checksum
            # stores (the snapshot below is written in v3 framing).
            src.acquire(allow_v2=True)
        except RuntimeError as e:
            print(f"{e} — stop it before compacting", file=sys.stderr)
            return 2
        blocks = src.load_blocks()
        if not blocks:
            print(f"{store_path}: empty chain store", file=sys.stderr)
            return 2
        try:
            chain = src.load_chain(
                blocks[0].header.difficulty,
                blocks,
                retarget=retarget,
            )
        except ValueError as e:
            # Without this, compacting a retarget store with forgotten
            # flags would REPLACE it with a genesis-only snapshot of the
            # wrong chain — the one unrecoverable failure mode here.
            print(str(e), file=sys.stderr)
            return 2
        before = os.path.getsize(store_path)
        out = out_path or store_path
        dst = None
        if out_path and os.path.realpath(out) != os.path.realpath(store_path):
            # The destination needs the same in-use guard: replacing it
            # would orphan a live node's inode there.
            dst = ChainStore(out)
            try:
                dst.acquire()
            except RuntimeError as e:
                print(f"{e} — stop it before overwriting", file=sys.stderr)
                return 2
        else:
            out = store_path
        try:
            # Always write a sibling temp file and atomically replace, so
            # a crash mid-write can never leave EITHER path deleted or
            # truncated.
            tmp = f"{out}.compact.{os.getpid()}"
            save_chain(chain, tmp)
            # Prove the snapshot BEFORE it replaces the original: the
            # main branch is linear, so its packed headers verify (PoW +
            # linkage + difficulty) in one native call straight off the
            # bytes just written — a torn or miswritten snapshot can
            # never clobber a good log.
            from p1_tpu.chain import replay_packed

            raw_headers, n_headers = ChainStore(tmp).packed_headers()
            snap = replay_packed(raw_headers, retarget=retarget)
            if not snap.valid:
                os.unlink(tmp)
                print(
                    f"snapshot self-check failed at record "
                    f"{snap.first_invalid} of {n_headers} — original store "
                    "left untouched",
                    file=sys.stderr,
                )
                return 3
            os.replace(tmp, out)
            # The rename itself must survive a metadata-journal loss:
            # save_chain fsynced the tmp's data and directory entry, but
            # the replace is a second directory mutation.
            from p1_tpu.chain.store import fsync_dir

            fsync_dir(os.path.dirname(os.path.abspath(out)))
        finally:
            if dst is not None:
                dst.close()
    finally:
        src.close()
    print(
        json.dumps(
            {
                "config": "compact",
                "height": chain.height,
                "records_before": len(blocks),
                "records_after": chain.height + 1,
                "bytes_before": before,
                "bytes_after": os.path.getsize(out),
                "out": out,
            }
        )
    )
    return 0


def run_fsck(store_path: str, out_path: str | None) -> int:
    """Offline store integrity scan + salvage (the disk counterpart of
    Bitcoin's -checkblocks/salvagewallet tooling).  Exit contract:

    - **0 clean** — every record checksum-valid, nothing rewritten (a
      lossless v2→v3 upgrade also exits 0: no information was lost);
    - **1 salvaged** — corruption or a torn tail was found; every
      checksum-valid record was rewritten into a fresh verified store,
      bad spans quarantined to the ``.quarantine`` sidecar;
    - **2 unrecoverable** — missing/empty/locked store, unrecognizable
      magic, or zero salvageable records.

    Unlike ``p1 compact`` this preserves insertion order and side
    branches (it salvages the LOG, not the main branch), so the
    self-check is framing-level — every salvaged record re-reads
    checksum-valid and byte-identical — rather than the linear-chain
    ``replay_packed`` proof compaction can afford."""
    import struct

    from p1_tpu.chain import ChainStore
    from p1_tpu.chain.store import fsync_dir
    from p1_tpu.core.block import Block

    if not os.path.exists(store_path) or os.path.getsize(store_path) == 0:
        print(f"{store_path}: empty or missing chain store", file=sys.stderr)
        return 2
    store = ChainStore(store_path)
    try:
        try:
            # Lock first (a live node's in-flight appends must not race
            # the rewrite), scan without healing: fsck owns the salvage
            # decision and must report BEFORE mutating.
            store.acquire(allow_v2=True, heal=False)
        except RuntimeError as e:
            print(str(e), file=sys.stderr)
            return 2
        data = store._read_bytes()
        scan = store.scan(data)
        report = {
            "config": "fsck",
            "store": store_path,
            "version": scan.version,
            "records_valid": len(scan.spans),
            "bad_spans": len(scan.bad_spans),
            "bytes_quarantined": scan.quarantined_bytes,
            "torn_tail_bytes": (
                scan.size - scan.torn_tail if scan.torn_tail is not None else 0
            ),
        }
        if scan.version == 3 and scan.clean:
            print(json.dumps({**report, "status": "clean"}))
            return 0

        # Salvage: every checksum-valid record that still parses as a
        # block, in original insertion order, into a fresh v3 store.
        blocks, parse_failures = [], 0
        for off, n in scan.spans:
            try:
                blocks.append(Block.deserialize(data[off : off + n]))
            except ValueError:
                parse_failures += 1
        report["parse_failures"] = parse_failures
        if not blocks:
            print(
                json.dumps({**report, "status": "unrecoverable"}),
            )
            print(
                f"{store_path}: no salvageable records", file=sys.stderr
            )
            return 2
        if scan.bad_spans:
            # Evidence first, durably, before the original bytes go away.
            qpath = store.quarantine_path()
            with open(qpath, "ab") as qf:
                for s, e in scan.bad_spans:
                    qf.write(struct.pack(">QI", s, e - s))
                    qf.write(data[s:e])
                qf.flush()
                os.fsync(qf.fileno())
            report["quarantine"] = str(qpath)
        out = out_path or store_path
        tmp = f"{out}.fsck.{os.getpid()}"
        dst = ChainStore(tmp, fsync=False)
        try:
            for block in blocks:
                dst.append(block)
            dst.sync()
            dst._fsync_dir()
        finally:
            dst.close()
        # Self-check BEFORE the replace: the fresh store must re-scan
        # clean with every record byte-identical to what was salvaged —
        # a miswritten salvage must never clobber the evidence.
        vdata = ChainStore(tmp)._read_checked()
        vscan = ChainStore.scan(vdata)
        ok = (
            vscan.version == 3
            and vscan.clean
            and len(vscan.spans) == len(blocks)
            and all(
                vdata[off : off + n] == block.serialize()
                for (off, n), block in zip(vscan.spans, blocks)
            )
        )
        if not ok:
            os.unlink(tmp)
            print(
                "salvage self-check failed — original store left untouched",
                file=sys.stderr,
            )
            return 2
        os.replace(tmp, out)
        fsync_dir(os.path.dirname(os.path.abspath(out)))
        lossless = (
            not scan.bad_spans
            and scan.torn_tail is None
            and not parse_failures
        )
        report.update(
            {
                "records_salvaged": len(blocks),
                "out": out,
                "status": "upgraded" if lossless else "salvaged",
            }
        )
        print(json.dumps(report))
        return 0 if lossless else 1
    finally:
        store.close()
