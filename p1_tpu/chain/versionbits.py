"""BIP9-analog version-bits: in-place protocol evolution by miner signal.

Round 20, leg (d) of the always-on node: a deployed feature activates on
a RUNNING mesh — no flag-day restart — by miners signaling readiness in
the header ``version`` field they already mine, with activation decided
by a pure function of the header chain so every node that has the same
headers reports the same state at the same height.

The state machine is Bitcoin's BIP9 shape, per deployment:

- **DEFINED** until the window containing ``start_height`` begins;
- **STARTED** from there: miners aware of the deployment set its bit;
- **LOCKED_IN** once a completed window carries >= ``threshold``
  signaling headers (checked before the timeout each boundary — the
  "speedy trial" ordering, so a window that both crosses the timeout
  and meets the threshold still locks in);
- **ACTIVE** one full window after LOCKED_IN (the grace period
  stragglers get to upgrade);
- **FAILED** permanently if the timeout window starts first.

Signaling uses the BIP9 top-bits convention: ``version`` =
``TOP_BITS | (1 << bit)`` per signaled deployment.  ``TOP_BITS``
(0x20000000) distinguishes a version-bits header from the legacy
``version=1`` every pre-round-20 header carries — a legacy header
signals nothing, and ``mining_version`` returns literal 1 when no
deployments are configured, so a node with an empty deployment table
produces byte-identical traces to every earlier round.

**What activation does NOT do here**: header ``version`` is not a
consensus field (core/validate.py checks PoW/merkle/signatures, never
version), and activation adds no retroactive validity rule — so a mixed
mesh can NEVER fork on version bits alone, by construction.  That
no-fork property is exactly what the ``version_activation`` scenario
(node/scenarios.py) pins with an impossible-bound control.  Activation
is the coordination layer: what feature a node advertises, mines with,
and reports — the wire-contract rule (``p1 lint``) keeps the frame
catalog exhaustively versioned underneath it.

State is computed per window boundary and memoized by (deployment,
boundary block hash): a reorg across a boundary lands on a different
boundary hash and recomputes, while steady-state queries are a dict
hit.  Headers below an assumed/re-based chain's base are unknowable;
the walk treats them as non-signaling, which only ever DELAYS lock-in
(conservative, documented in the node's maintenance report).
"""

from __future__ import annotations

import dataclasses
import enum

__all__ = [
    "Deployment",
    "TOP_BITS",
    "TOP_MASK",
    "VBState",
    "VersionBits",
    "signals",
]

#: BIP9 top-bits: the high 3 bits of a signaling header's version must
#: be exactly 001.  Legacy headers (version=1) never match.
TOP_BITS = 0x20000000
TOP_MASK = 0xE0000000


class VBState(enum.Enum):
    DEFINED = "defined"
    STARTED = "started"
    LOCKED_IN = "locked_in"
    ACTIVE = "active"
    FAILED = "failed"


@dataclasses.dataclass(frozen=True)
class Deployment:
    """One named feature deployment.

    ``bit`` is the version bit miners set while STARTED/LOCKED_IN
    (0..28 — bits 29..31 are the top-bits tag).  ``start_height`` /
    ``timeout_height`` bound the signaling period in heights (BIP9 uses
    median-time-past; heights are this chain's deterministic analog —
    the sim's virtual clocks make time-based bounds unreproducible)."""

    name: str
    bit: int
    start_height: int
    timeout_height: int

    def __post_init__(self):
        if not 0 <= self.bit <= 28:
            raise ValueError(f"deployment bit {self.bit} outside 0..28")
        if self.timeout_height <= self.start_height:
            raise ValueError(
                f"{self.name}: timeout {self.timeout_height} <= "
                f"start {self.start_height}"
            )


def signals(version: int, bit: int) -> bool:
    """True when a header ``version`` signals ``bit`` under the
    top-bits convention."""
    return (version & TOP_MASK) == TOP_BITS and bool(version & (1 << bit))


class VersionBits:
    """The per-chain activation engine: deployments + window/threshold,
    evaluated against a ``Chain``'s header index."""

    def __init__(
        self,
        deployments: tuple[Deployment, ...],
        window: int,
        threshold: int,
    ):
        if window < 1:
            raise ValueError("window must be >= 1")
        if not 1 <= threshold <= window:
            raise ValueError(
                f"threshold {threshold} outside 1..window({window})"
            )
        bits = [d.bit for d in deployments]
        if len(set(bits)) != len(bits):
            raise ValueError("deployments share a version bit")
        self.deployments = tuple(deployments)
        self.window = window
        self.threshold = threshold
        #: (deployment name, boundary block hash) -> state.  Bounded by
        #: O(deployments x boundaries actually queried); reorgs change
        #: the boundary hash, so stale entries are simply never hit.
        self._cache: dict[tuple[str, bytes], VBState] = {}

    # -- state machine -----------------------------------------------------

    def state_for_next(self, chain, prev_hash: bytes, dep: Deployment) -> VBState:
        """The deployment's state governing the block that would be
        mined ON ``prev_hash`` — a pure function of the header chain up
        to ``prev_hash`` (every node agrees given the same headers).

        BIP9 evaluates state per retarget period; here the state is
        constant across each ``window``-aligned height span and
        transitions only at boundaries, evaluated by walking completed
        windows from the deployment's start.
        """
        entry_height = chain.height_of(prev_hash) + 1
        boundary = entry_height - (entry_height % self.window)
        # Walk prev_hash back to the boundary's last header (height
        # boundary-1); headers are always resident, O(window).
        bh = prev_hash
        h = entry_height - 1
        while h >= boundary:
            hdr = chain.header_of(bh)
            if hdr is None:
                return VBState.DEFINED  # below the base: unknowable
            bh = hdr.prev_hash
            h -= 1
        return self._state_at_boundary(chain, boundary, bh, dep)

    def _state_at_boundary(
        self, chain, boundary: int, last_hash: bytes, dep: Deployment
    ) -> VBState:
        """State for the window starting at ``boundary``, whose parent
        chain ends at ``last_hash`` (the height ``boundary - 1`` block,
        or the below-base sentinel when the walk fell off the index).
        Recurses boundary-by-boundary toward the deployment start;
        memoized per (deployment, boundary hash)."""
        if boundary < self.window or boundary <= dep.start_height - self.window:
            # Before any window wholly past start can complete —
            # genesis-adjacent or pre-start: DEFINED unless started.
            if boundary >= dep.start_height:
                return (
                    VBState.FAILED
                    if boundary >= dep.timeout_height
                    else VBState.STARTED
                )
            return VBState.DEFINED
        key = (dep.name, last_hash)
        cached = self._cache.get(key)
        if cached is not None:
            return cached
        # Walk the just-completed window [boundary - window, boundary)
        # counting signals, and find the previous boundary's last hash.
        count = 0
        bh = last_hash
        truncated = False
        for _ in range(self.window):
            hdr = chain.header_of(bh)
            if hdr is None:
                truncated = True  # window crosses the base: count what we saw
                break
            if signals(hdr.version, dep.bit):
                count += 1
            bh = hdr.prev_hash
        prev_boundary = boundary - self.window
        prev = self._state_at_boundary(chain, prev_boundary, bh, dep)
        if prev is VBState.DEFINED:
            if boundary >= dep.timeout_height:
                state = VBState.FAILED
            elif boundary >= dep.start_height:
                state = VBState.STARTED
            else:
                state = VBState.DEFINED
        elif prev is VBState.STARTED:
            # Threshold before timeout at each boundary (speedy-trial
            # ordering): a window meeting both locks in.
            if count >= self.threshold and prev_boundary >= dep.start_height:
                state = VBState.LOCKED_IN
            elif boundary >= dep.timeout_height:
                state = VBState.FAILED
            else:
                state = VBState.STARTED
        elif prev is VBState.LOCKED_IN:
            state = VBState.ACTIVE
        else:  # ACTIVE / FAILED are terminal
            state = prev
        if not truncated:
            self._cache[key] = state
        return state

    # -- the two consumers -------------------------------------------------

    def mining_version(self, chain, prev_hash: bytes) -> int:
        """The ``version`` a block mined on ``prev_hash`` should carry:
        top-bits plus every deployment bit currently worth signaling
        (STARTED or LOCKED_IN).  Literal 1 — the legacy constant every
        pre-round-20 header carries — when no deployments are
        configured, so an empty table is byte-identical to history."""
        if not self.deployments:
            return 1
        version = TOP_BITS
        for dep in self.deployments:
            state = self.state_for_next(chain, prev_hash, dep)
            if state in (VBState.STARTED, VBState.LOCKED_IN):
                version |= 1 << dep.bit
        return version

    def states_report(self, chain) -> dict:
        """Per-deployment state at the current tip — the maintenance
        plane's JSON surface (``p1 maintain status``, MAINTAIN wire)."""
        out = {}
        for dep in self.deployments:
            state = self.state_for_next(chain, chain.tip_hash, dep)
            out[dep.name] = {
                "bit": dep.bit,
                "start_height": dep.start_height,
                "timeout_height": dep.timeout_height,
                "state": state.value,
            }
        return out
