"""Disk-fault injection harness: scripted bad storage behavior.

The storage analog of ``node/testing.py``'s ``HostilePeer``: where that
module scripts delivery pathologies against a real node's sockets, this
one scripts DISK pathologies against a real ``ChainStore`` — a
``FaultStore`` is a ChainStore whose file layer is shimmed per a
declarative ``StoreFaultPlan``:

- **fail the Nth write** with ENOSPC/EIO (one-shot, or every write from
  the Nth until ``clear_faults()`` — the full-disk that later drains);
- **torn writes**: the failing write lands only its first K bytes, the
  on-disk shape of a crash/power-cut mid-append;
- **fsync failure** (file or directory), the journaling-loss profile;
- **bit-flips on read**, transient bad-sector reads that corrupt what
  the process sees while the platter bytes stay intact.

Write counting is at the file layer and one append = one write (the
store frames each record as a single write exactly so a tear is bounded
to one record); on a fresh store the magic is write #1.

Test infrastructure, not product: nothing in the node imports this.  It
lives in the package (rather than tests/) so external soak rigs can
script disk faults against real nodes without vendoring test helpers —
``append_soak`` is the subprocess driver the kill-9 crash soak uses.
"""

from __future__ import annotations

import dataclasses
import errno
import os

from p1_tpu.chain.store import ChainStore
from p1_tpu.chain.segstore import SegmentedStore

__all__ = ["StoreFaultPlan", "FaultStore", "SegFaultStore", "append_soak"]


@dataclasses.dataclass(frozen=True)
class StoreFaultPlan:
    """One scripted disk pathology.  Default = a perfectly healthy disk."""

    #: One-shot: the Nth write call raises ``write_errno`` (1-based).
    fail_write_at: int | None = None
    #: Persistent: every write from the Nth on raises ``write_errno``
    #: until ``FaultStore.clear_faults()`` — ENOSPC that later drains.
    fail_writes_from: int | None = None
    write_errno: int = errno.ENOSPC
    #: The failing write lands this many bytes before raising — a torn
    #: record, exactly what a power cut mid-append leaves behind.
    torn_bytes: int | None = None
    #: The Nth data fsync raises ``fsync_errno`` (the EIO-on-fsync case
    #: that famously eats acknowledged writes).
    fail_fsync_at: int | None = None
    #: The Nth DIRECTORY fsync raises ``fsync_errno``.
    fail_dir_fsync_at: int | None = None
    fsync_errno: int = errno.EIO
    #: Flip ``flip_mask`` into the byte at this absolute file offset on
    #: every read — the disk holds good bytes, the process sees bad ones.
    flip_read_at: int | None = None
    flip_mask: int = 0x01
    #: Persistent: every body-refetch pread from the Nth on raises
    #: ``pread_errno`` until ``clear_faults()`` — a sector (or a whole
    #: segment file) going EIO under a live serve.
    fail_preads_from: int | None = None
    pread_errno: int = errno.EIO


class _FaultFile:
    """Write-path shim around the store's buffered writer: counts write
    calls and injects the plan's write faults; everything else passes
    through (flock needs ``fileno``, close needs ``close``...)."""

    def __init__(self, fh, owner: "FaultStore"):
        self._fh = fh
        self._owner = owner

    def write(self, data: bytes) -> int:
        owner = self._owner
        owner.writes += 1
        owner.events.append("write")
        plan = owner.plan
        n = owner.writes
        failing = plan.fail_write_at == n or (
            plan.fail_writes_from is not None and n >= plan.fail_writes_from
        )
        if failing:
            if plan.torn_bytes:
                # The tear must actually reach the file, not sit in the
                # buffer: flush so a reopening reader sees the torn tail.
                self._fh.write(data[: plan.torn_bytes])
                self._fh.flush()
            raise OSError(plan.write_errno, os.strerror(plan.write_errno))
        return self._fh.write(data)

    def flush(self) -> None:
        self._fh.flush()

    def fileno(self) -> int:
        return self._fh.fileno()

    def close(self) -> None:
        self._fh.close()

    @property
    def closed(self) -> bool:
        return self._fh.closed


class _FaultSeams:
    """The shimmed file layer, shared by the single-file ``FaultStore``
    and the segmented ``SegFaultStore``: both stores route every file
    open / fsync / dir-fsync / whole-file read through the ``*_path``
    seams (chain/store.py), so ONE shim covers both layouts — a plan's
    write counter ticks across segment boundaries exactly as it ticks
    across records in one file."""

    def _init_faults(self, plan: StoreFaultPlan | None) -> None:
        self.plan = plan if plan is not None else StoreFaultPlan()
        self.writes = 0
        self.fsyncs = 0
        self.dir_fsyncs = 0
        self.reads = 0
        self.events: list[str] = []

    def clear_faults(self) -> None:
        """Lift every injected fault (the disk 'recovered')."""
        self.plan = StoreFaultPlan()

    # -- shimmed file-layer seams -----------------------------------------

    def _open_fh_path(self, path):
        return _FaultFile(super()._open_fh_path(path), self)

    def _fsync_file(self, fh) -> None:
        self.fsyncs += 1
        self.events.append("fsync")
        if self.plan.fail_fsync_at == self.fsyncs:
            raise OSError(
                self.plan.fsync_errno, os.strerror(self.plan.fsync_errno)
            )
        os.fsync(fh.fileno())

    def _fsync_dir_path(self, path) -> None:
        self.dir_fsyncs += 1
        self.events.append("dir_fsync")
        if self.plan.fail_dir_fsync_at == self.dir_fsyncs:
            raise OSError(
                self.plan.fsync_errno, os.strerror(self.plan.fsync_errno)
            )
        super()._fsync_dir_path(path)

    def _read_bytes_path(self, path) -> bytes:
        self.reads += 1
        data = super()._read_bytes_path(path)
        plan = self.plan
        if plan.flip_read_at is not None and plan.flip_read_at < len(data):
            buf = bytearray(data)
            buf[plan.flip_read_at] ^= plan.flip_mask
            data = bytes(buf)
        return data

    def _pread(self, fd: int, n: int, off: int) -> bytes:
        self.preads = getattr(self, "preads", 0) + 1
        plan = self.plan
        if (
            plan.fail_preads_from is not None
            and self.preads >= plan.fail_preads_from
        ):
            raise OSError(plan.pread_errno, os.strerror(plan.pread_errno))
        return super()._pread(fd, n, off)


class FaultStore(_FaultSeams, ChainStore):
    """A ``ChainStore`` with an unreliable disk, per a ``StoreFaultPlan``.

    Usage::

        store = FaultStore(path, plan=StoreFaultPlan(fail_writes_from=3))
        node = Node(config, store=store)   # injectable: Node's store seam
        ...
        store.clear_faults()               # "space was freed"

    Counters (``writes``/``fsyncs``/``dir_fsyncs``/``reads``) and the
    ordered ``events`` trace let tests assert what the store actually
    did — e.g. that ``save_chain`` fsyncs the data BEFORE the directory.
    The heal/rebuild path writes through plain ``open`` (it replaces the
    inode wholesale), so faults apply to the append plane only.
    """

    def __init__(
        self,
        path,
        plan: StoreFaultPlan | None = None,
        fsync: bool = True,
    ):
        super().__init__(path, fsync=fsync)
        self._init_faults(plan)


class SegFaultStore(_FaultSeams, SegmentedStore):
    """A ``SegmentedStore`` with the same unreliable disk: faults land
    on whichever SEGMENT the store touches (appends, rolls, per-segment
    scans), which is how the round-7 fault families port to segment
    boundaries — e.g. ``fail_write_at`` aimed one past the roll point
    tears the FIRST record of a fresh segment.  Manifest writes ride
    the plain heal plane (atomic tmp+rename), like the base heal."""

    def __init__(
        self,
        path,
        plan: StoreFaultPlan | None = None,
        fsync: bool = True,
        segment_bytes: int = 1 << 16,
    ):
        super().__init__(path, fsync=fsync, segment_bytes=segment_bytes)
        self._init_faults(plan)


def append_soak(
    path,
    n_blocks: int = 24,
    difficulty: int = 12,
    delay_s: float = 0.0,
    segment_bytes: int = 0,
) -> None:
    """Subprocess driver for the kill-9 crash soak: (re)open the store at
    ``path`` and append the DETERMINISTIC ``make_blocks`` chain from
    wherever the store left off, fsync per append.  The parent SIGKILLs
    this at a random moment, reopens the store, and asserts the
    surviving records are exactly a prefix of the same chain — then
    relaunches to keep appending.  Determinism is what makes the
    invariant checkable: same difficulty + miner id → byte-identical
    blocks in every process.  ``delay_s`` paces the appends so a
    random-time kill reliably lands INSIDE the append window instead of
    after a sub-second sprint."""
    import time

    from p1_tpu.node.testing import make_blocks

    blocks = make_blocks(n_blocks, difficulty=difficulty)
    if segment_bytes > 0:
        # The segmented variant of the same soak: tiny segments put the
        # random kill INSIDE roll boundaries, not just appends.
        store = SegmentedStore(path, segment_bytes=segment_bytes)
    else:
        store = ChainStore(path)
    store.acquire()
    try:
        done = len(store.load_blocks())
        for block in blocks[done:]:
            store.append(block)
            if delay_s:
                time.sleep(delay_s)
    finally:
        store.close()


if __name__ == "__main__":  # the crash-soak child: append until killed
    import sys

    append_soak(
        sys.argv[1],
        int(sys.argv[2]),
        int(sys.argv[3]),
        float(sys.argv[4]) if len(sys.argv) > 4 else 0.0,
        int(sys.argv[5]) if len(sys.argv) > 5 else 0,
    )
