"""Chain replay: generate and verify long header chains (benchmark config 3).

Capability parity: "chain replay: verify 10k-block header chain (hash-only,
no mining)" (BASELINE.json:9).  TPU-first: verification packs the whole
chain into one (N, 20) uint32 array and runs PoW + prev-hash linkage as a
single batched device computation (``verify_header_chain``) — segmented at
a fixed size so one compiled program serves any chain length.  A host
(hashlib) path provides the oracle and the CPU baseline.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from p1_tpu.core.hashutil import sha256d
from p1_tpu.core.header import BlockHeader, meets_target
from p1_tpu.core.genesis import make_genesis


def generate_headers(
    n: int, difficulty: int, backend=None, progress=None
) -> list[BlockHeader]:
    """Mine an ``n``-header chain (genesis first) at ``difficulty``.

    Header-only mining: empty merkle root, timestamps stepping one second.
    ``backend`` is any HashBackend (default cpu); low difficulties make
    10k-header generation cheap enough for a test fixture.
    """
    from p1_tpu.hashx import get_backend
    from p1_tpu.miner import Miner

    miner = Miner(backend=backend if backend is not None else get_backend("cpu"))
    headers = [make_genesis(difficulty).header]
    for height in range(1, n):
        draft = BlockHeader(
            version=1,
            prev_hash=headers[-1].block_hash(),
            merkle_root=bytes(32),
            timestamp=headers[-1].timestamp + 1,
            difficulty=difficulty,
            nonce=0,
        )
        sealed = miner.search_nonce(draft)
        assert sealed is not None
        headers.append(sealed)
        if progress is not None:
            progress(height)
    return headers


def headers_to_words(headers: list[BlockHeader]) -> np.ndarray:
    """(N, 20) big-endian uint32 view of serialized headers."""
    raw = b"".join(h.serialize() for h in headers)
    return np.frombuffer(raw, dtype=">u4").astype(np.uint32).reshape(-1, 20)


@dataclasses.dataclass(frozen=True)
class ReplayReport:
    n_headers: int
    valid: bool
    first_invalid: int | None  # header index, None when valid
    elapsed_s: float
    method: str

    @property
    def headers_per_sec(self) -> float:
        return self.n_headers / self.elapsed_s if self.elapsed_s > 0 else 0.0


def replay_host(headers: list[BlockHeader]) -> ReplayReport:
    """Sequential hashlib verification: PoW + prev-hash linkage."""
    t0 = time.perf_counter()
    prev_digest = bytes(32)
    first_invalid = None
    difficulty = headers[0].difficulty if headers else 0
    for i, header in enumerate(headers):
        digest = sha256d(header.serialize())
        pow_ok = i == 0 or meets_target(digest, difficulty)
        diff_ok = header.difficulty == difficulty
        if not (pow_ok and diff_ok and header.prev_hash == prev_digest):
            first_invalid = i
            break
        prev_digest = digest
    return ReplayReport(
        len(headers),
        first_invalid is None,
        first_invalid,
        time.perf_counter() - t0,
        "host",
    )


def replay_device(
    headers: list[BlockHeader], segment: int = 4096, platform: str | None = None
) -> ReplayReport:
    """Batched device verification in fixed-size segments.

    Each segment checks PoW for all its headers and linkage both within the
    segment and across the segment boundary (via the previous segment's
    last digest, recomputed on host — one hash per 4096).  The final short
    segment is padded with copies of its last header; every pad lane FAILS
    linkage (a copied header's prev_hash never equals the preceding copy's
    digest), intentionally: the ``idx < valid_len`` clamp on host is what
    discards pad-lane failures, so do not "fix" the clamp away.
    """
    import jax.numpy as jnp

    from p1_tpu.core.header import target_from_difficulty, target_to_words
    from p1_tpu.hashx.jax_sha256 import jit_verify_chain

    if not headers:
        raise ValueError("empty chain")
    difficulty = headers[0].difficulty
    target = jnp.asarray(
        target_to_words(target_from_difficulty(difficulty)), jnp.uint32
    )
    words = headers_to_words(headers)
    n = len(headers)
    step = jit_verify_chain(segment, platform)

    t0 = time.perf_counter()
    first_invalid = None
    prev_digest_words = jnp.zeros((8,), jnp.uint32)  # genesis links to zero
    for base in range(0, n, segment):
        chunk = words[base : base + segment]
        valid_len = chunk.shape[0]
        if valid_len < segment:
            pad = np.repeat(chunk[-1:], segment - valid_len, axis=0)
            chunk = np.concatenate([chunk, pad], axis=0)
        idx = int(
            step(
                jnp.asarray(chunk),
                target,
                prev_digest_words,
                jnp.asarray(base == 0),
                jnp.uint32(difficulty),
            )
        )
        if idx < valid_len:
            first_invalid = base + idx
            break
        # Host-hash the segment's last real header to seed the next link.
        last = sha256d(headers[base + valid_len - 1].serialize())
        prev_digest_words = jnp.asarray(
            np.frombuffer(last, dtype=">u4").astype(np.uint32)
        )
    return ReplayReport(
        n, first_invalid is None, first_invalid, time.perf_counter() - t0, "device"
    )
