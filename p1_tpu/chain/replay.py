"""Chain replay: generate and verify long header chains (benchmark config 3).

Capability parity: "chain replay: verify 10k-block header chain (hash-only,
no mining)" (BASELINE.json:9).  TPU-first: verification packs the whole
chain into one (S, segment, 20) uint32 array and runs PoW + prev-hash
linkage as a single batched device program — a ``lax.scan`` over segments
with the cross-segment digest carried on device
(``jax_sha256.verify_header_chain_segments``).  A host (hashlib) path
provides the oracle and the CPU baseline.
"""

from __future__ import annotations

import dataclasses
import operator
import time

import numpy as np

from p1_tpu.core.hashutil import sha256d
from p1_tpu.core.header import HEADER_SIZE, BlockHeader, meets_target
from p1_tpu.core.genesis import make_genesis


def _expected_difficulty_at(
    headers: list[BlockHeader], i: int, retarget
) -> int:
    """Required difficulty of ``headers[i]`` given its predecessors — the
    linear-chain form of ``Chain._expected_difficulty`` (same boundary,
    same window-1-interval span, same rule)."""
    if retarget is None or i == 0:
        return headers[0].difficulty if headers else 0
    if i % retarget.window != 0:
        return headers[i - 1].difficulty
    span = headers[i - 1].timestamp - headers[i - retarget.window].timestamp
    return retarget.adjusted(headers[i - 1].difficulty, span)


def generate_headers(
    n: int, difficulty: int, backend=None, progress=None, retarget=None
) -> list[BlockHeader]:
    """Mine an ``n``-header chain (genesis first) at ``difficulty``.

    Header-only mining: empty merkle root, timestamps stepping one second.
    ``backend`` is any HashBackend (default cpu); low difficulties make
    10k-header generation cheap enough for a test fixture.  With a
    ``RetargetRule`` the chain follows the rule's difficulty schedule
    (and its genesis commits to the rule).
    """
    from p1_tpu.hashx import get_backend
    from p1_tpu.miner import Miner

    miner = Miner(backend=backend if backend is not None else get_backend("cpu"))
    headers = [make_genesis(difficulty, retarget).header]
    for height in range(1, n):
        draft = BlockHeader(
            version=1,
            prev_hash=headers[-1].block_hash(),
            merkle_root=bytes(32),
            timestamp=headers[-1].timestamp + 1,
            difficulty=_expected_difficulty_at(headers, height, retarget),
            nonce=0,
        )
        sealed = miner.search_nonce(draft)
        assert sealed is not None
        headers.append(sealed)
        if progress is not None:
            progress(height)
    return headers


def pack_headers(headers: list[BlockHeader]) -> bytes:
    """The contiguous (N*80)-byte buffer of the headers' canonical
    encodings — ONE packer shared by the native, device, and export
    planes.  ``BlockHeader.serialize`` memoizes, so for headers a node
    already holds (ingested off the wire, or serialized once before)
    this is a join of cached buffers: no per-header struct packing, which
    is what closes replay-from-objects toward the raw-bytes rate
    (docs/PERF.md "host ingest plane")."""
    try:
        # C-level gather of the memoized encodings (the cache slot is a
        # plain instance attribute) — the join is the whole cost.
        return b"".join(map(operator.attrgetter("_raw"), headers))
    except AttributeError:
        # Some header not yet encoded: pay its one-time pack.
        return b"".join([h.serialize() for h in headers])


def parse_headers(raw: bytes) -> list[BlockHeader]:
    """Batch-parse a packed header buffer (the inverse of
    ``pack_headers``).  Each header's encoding cache is seeded with its
    exact 80-byte slice, so a subsequent verify/export never repacks."""
    if len(raw) % HEADER_SIZE:
        raise ValueError(
            f"packed header buffer must be a multiple of {HEADER_SIZE} bytes"
        )
    return [
        BlockHeader.deserialize(raw[off : off + HEADER_SIZE])
        for off in range(0, len(raw), HEADER_SIZE)
    ]


def headers_to_words(headers: list[BlockHeader]) -> np.ndarray:
    """(N, 20) big-endian uint32 view of serialized headers."""
    raw = pack_headers(headers)
    return np.frombuffer(raw, dtype=">u4").astype(np.uint32).reshape(-1, 20)


@dataclasses.dataclass(frozen=True)
class ReplayReport:
    n_headers: int
    valid: bool
    first_invalid: int | None  # header index, None when valid
    elapsed_s: float
    method: str

    @property
    def headers_per_sec(self) -> float:
        return self.n_headers / self.elapsed_s if self.elapsed_s > 0 else 0.0


def replay_host(headers: list[BlockHeader], retarget=None) -> ReplayReport:
    """Sequential hashlib verification: PoW + prev-hash linkage.

    With a ``RetargetRule`` this is the full light-client header check for
    retargeting chains: the required difficulty is recomputed per header
    from the sequence itself (it is a pure function of the headers), and
    timestamps must strictly increase — exactly the rules ``Chain``
    enforces at connect time.  This is the oracle the SPV docs point
    wallet operators at when a one-header proof's work bar is not enough
    (chain/proof.py); ``replay_native`` runs the identical retarget
    rules ~100x faster in C++ (parity-fuzzed), while the DEVICE engine
    stays fixed-difficulty (the benchmark-config form).

    Trust note: ``headers[0]`` self-attests the base difficulty — the
    CALLER must pin it to the chain it cares about
    (``headers[0].block_hash() == genesis_hash(difficulty, rule)``), or a
    forged file claiming a trivial base difficulty "verifies" cheaply.
    ``p1 replay --verify`` performs exactly that check.
    """
    t0 = time.perf_counter()
    prev_digest = bytes(32)
    first_invalid = None
    expected = headers[0].difficulty if headers else 0
    for i, header in enumerate(headers):
        digest = sha256d(header.serialize())
        if retarget is not None and i >= 1:
            expected = _expected_difficulty_at(headers, i, retarget)
        pow_ok = i == 0 or meets_target(digest, expected)
        diff_ok = header.difficulty == expected
        # The shared timestamp rule (strict increase + forward cap with
        # the height-1 anchor exemption) — RetargetRule owns it.
        ts_ok = retarget is None or i == 0 or (
            retarget.timestamp_violation(
                i - 1, headers[i - 1].timestamp, header.timestamp
            )
            is None
        )
        if not (pow_ok and diff_ok and ts_ok and header.prev_hash == prev_digest):
            first_invalid = i
            break
        prev_digest = digest
    return ReplayReport(
        len(headers),
        first_invalid is None,
        first_invalid,
        time.perf_counter() - t0,
        "host",
    )


def replay_native(
    headers: list[BlockHeader], retarget=None
) -> ReplayReport:
    """C++ verification engine: one ctypes call over the packed headers
    (SHA-NI compressions, no per-header Python) — the native tier of
    benchmark config 3, same rules as ``replay_host`` (its oracle),
    including the contextual difficulty schedule + timestamp rules on
    retargeting chains (``p1_verify_chain_retarget``)."""
    from p1_tpu.hashx.native_backend import (
        verify_header_chain,
        verify_header_chain_retarget,
    )

    difficulty = headers[0].difficulty if headers else 0
    # Packing is inside the timer: replay_host pays per-header serialize
    # in ITS timer too, so the reported rates compare end-to-end.  With
    # the encoding cache this is a join of already-canonical buffers for
    # any header the process has serialized or ingested before — ONE
    # contiguous buffer, ONE ctypes call, no per-header Python.
    t0 = time.perf_counter()
    raw = pack_headers(headers)
    if retarget is None:
        first_invalid = verify_header_chain(raw, len(headers), difficulty)
    else:
        first_invalid = verify_header_chain_retarget(
            raw, len(headers), retarget
        )
    return ReplayReport(
        len(headers),
        first_invalid is None,
        first_invalid,
        time.perf_counter() - t0,
        "native",
    )


def _probe_native() -> None:
    """Force the C library load NOW: ``native_backend._lib()`` builds
    (or finds) the .so and binds every symbol the wrapper uses, so all
    environment failure modes — no toolchain (``NativeBuildError``),
    unloadable .so (``OSError``), stale symbol table (``AttributeError``
    from ctypes, surfacing deliberately) — fire here, in a scope where
    the caller knows exactly what it is excusing."""
    from p1_tpu.hashx import native_backend

    native_backend._lib()


def replay_fast(
    headers: list[BlockHeader], retarget=None
) -> ReplayReport:
    """Strongest available verification engine: the C++ core (~2-3x the
    host oracle end-to-end, rule-for-rule parity-tested on fixed and
    retargeting chains alike), falling back to the hashlib oracle when
    the native library cannot build (no toolchain).  The light-client
    entry point (`p1 headers`, `p1 proof --headers`).

    The fallback excuses ENVIRONMENT failures only, and only from the
    probe: ``replay_native`` itself runs outside any except scope, so a
    genuine wrapper bug (bad argtypes, a broken ``ReplayReport``
    construction, an AttributeError anywhere past the load) crashes
    loudly instead of silently degrading every light-client
    verification to the slow host path forever (ADVICE r5)."""
    from p1_tpu.hashx.native_build import NativeBuildError

    try:
        _probe_native()
    except (NativeBuildError, OSError):
        # No compiler / unloadable .so: the host path is always
        # available and equally correct, just slower.
        return replay_host(headers, retarget=retarget)
    return replay_native(headers, retarget=retarget)


def replay_packed(raw: bytes, retarget=None) -> ReplayReport:
    """Verify a header chain straight from its packed wire/disk buffer —
    the zero-repack entry for callers that hold raw bytes (header files,
    store exports): the buffer goes to the native verifier in one ctypes
    call with NO object parse at all; only the no-toolchain fallback
    pays a batch parse before the hashlib oracle."""
    n = len(raw) // HEADER_SIZE
    if len(raw) != n * HEADER_SIZE or n == 0:
        raise ValueError(
            f"packed header buffer must be a non-empty multiple of "
            f"{HEADER_SIZE} bytes"
        )
    from p1_tpu.hashx.native_build import NativeBuildError

    try:
        from p1_tpu.hashx.native_backend import (
            verify_header_chain,
            verify_header_chain_retarget,
        )

        difficulty = raw[72:76]
        t0 = time.perf_counter()
        if retarget is None:
            first_invalid = verify_header_chain(
                raw, n, int.from_bytes(difficulty, "big")
            )
        else:
            first_invalid = verify_header_chain_retarget(raw, n, retarget)
        return ReplayReport(
            n,
            first_invalid is None,
            first_invalid,
            time.perf_counter() - t0,
            "native",
        )
    except (NativeBuildError, OSError, AttributeError):
        return replay_host(parse_headers(raw), retarget=retarget)


def replay_device(
    headers: list[BlockHeader], segment: int = 8192, platform: str | None = None
) -> ReplayReport:
    """Whole-chain device verification in ONE dispatch.

    The chain is padded to a multiple of ``segment`` with byte-copies of
    its last header, reshaped to (S, segment, 20), and handed to a single
    jitted ``lax.scan`` that carries the cross-segment digest on device
    (``jax_sha256.verify_header_chain_segments``) — no per-segment host
    round-trips, no host re-hashing.  Per-dispatch relay overhead (~125 ms,
    docs/PERF.md) is therefore paid exactly once per replay regardless of
    chain length.

    Padding semantics: every pad lane FAILS linkage (a copied header's
    prev_hash never equals the preceding copy's digest), intentionally —
    padding sits strictly after every real header, so a reported first
    failure ``>= n`` means the real chain is clean; the host-side ``< n``
    clamp is what discards pad-lane failures.  Do not "fix" the clamp away.
    The pad copies also make the device-carried digest chain correct at the
    boundary: the last pad lane's digest equals the last real header's.
    """
    import jax.numpy as jnp

    from p1_tpu.core.header import target_from_difficulty, target_to_words
    from p1_tpu.hashx.jax_sha256 import jit_verify_chain_scan

    if not headers:
        raise ValueError("empty chain")
    difficulty = headers[0].difficulty
    target = jnp.asarray(
        target_to_words(target_from_difficulty(difficulty)), jnp.uint32
    )
    words = headers_to_words(headers)
    n = len(headers)
    n_segments = -(-n // segment)
    pad = n_segments * segment - n
    if pad:
        words = np.concatenate([words, np.repeat(words[-1:], pad, axis=0)])
    words3 = words.reshape(n_segments, segment, 20)
    step = jit_verify_chain_scan(n_segments, segment, platform)

    t0 = time.perf_counter()
    idxs = np.asarray(
        step(jnp.asarray(words3), target, jnp.uint32(difficulty))
    )
    offsets = np.arange(n_segments, dtype=np.int64) * segment
    bad = offsets + idxs
    bad = bad[idxs < segment]
    first_invalid = int(bad.min()) if bad.size else None
    if first_invalid is not None and first_invalid >= n:
        first_invalid = None  # pad-lane failure: real chain is clean
    return ReplayReport(
        n, first_invalid is None, first_invalid, time.perf_counter() - t0, "device"
    )
