"""Snapshot-state sync: canonical ledger serialization + merkle state root.

ROADMAP item 2 (the assumeUTXO analog, Bitcoin-Core lineage): a new node
should boot from a *state snapshot* in seconds and serve queries
immediately, while the chain history revalidates in the background.  The
hard part is the robustness contract — a snapshot is untrusted input
from an adversarial peer — so everything in this module is built to be
checkable:

- **Canonical serialization.**  Account state (balances + nonces) is
  encoded as a sorted-by-account sequence of fixed-layout entries, cut
  into chunks of ``CHUNK_ACCOUNTS``.  Same state ⇒ same bytes,
  regardless of dict insertion order or ``PYTHONHASHSEED``
  (property-tested in tests/test_snapshot.py) — which is what makes the
  digests below meaningful.
- **Merkle-ized state root.**  One SHA-256d leaf per account entry,
  combined with the same duplicate-odd-leaf tree as block merkle roots
  (``core/block.py``).  ``Chain`` commits this root at checkpoint
  heights (the retarget interval, or ``DEFAULT_CHECKPOINT_INTERVAL`` on
  fixed-difficulty chains) as it applies blocks, so a replaying node can
  compare its own state against a snapshot's claim at exactly one
  height.
- **Self-describing manifest + chunk digests.**  The manifest names the
  snapshot height, block hash, state root, account count, and one
  SHA-256d digest per chunk — plus the full serialized anchor block, so
  a receiver can check the block hash, PoW, and merkle commitment
  before spending anything on chunks.  Chunks verify *incrementally* as
  they arrive (digest per chunk), so a peer lying mid-transfer is
  caught on the first bad chunk, not after the whole download.
- **CRC-framed v3-style records on disk.**  Snapshot files reuse the
  chain store's framing discipline (``P1TPUSS1`` magic; per-record
  CRC32 trailer over length prefix + payload): a torn tail or bit-rot
  is detected, never trusted through.

Trust model (spelled out because it is easy to over-read): the state
root proves the *chunks* match the *manifest* — nothing more.  Until
background revalidation replays the real history and reproduces the
same root at the same height, the whole snapshot — root included — is
just the serving peer's claim.  ``docs/ROUND12.md`` carries the full
honesty notes; ``node/node.py`` carries the ASSUMED→VALIDATED flip.
"""

from __future__ import annotations

import dataclasses
import struct
import zlib
from pathlib import Path

from p1_tpu.core.block import Block, merkle_root
from p1_tpu.core.hashutil import sha256d

__all__ = [
    "CHUNK_ACCOUNTS",
    "DEFAULT_CHECKPOINT_INTERVAL",
    "MAGIC",
    "IncrementalState",
    "LedgerSnapshot",
    "Manifest",
    "SnapshotError",
    "build_records",
    "build_records_incremental",
    "chunk_digest",
    "encode_chunks",
    "load_snapshot",
    "parse_chunk",
    "parse_manifest",
    "read_records",
    "state_root",
    "verify_file",
    "write_snapshot",
]

#: Snapshot file format tag — versioned like the chain store's magic.
MAGIC = b"P1TPUSS1"

#: Accounts per chunk.  ~26 B/entry for short account ids means a chunk
#: is a few hundred KB at worst — far under the wire frame cap, so one
#: SNAPSHOT reply can carry several chunks.
CHUNK_ACCOUNTS = 4096

#: Checkpoint spacing on fixed-difficulty chains (retargeting chains use
#: their retarget window — the "natural" consensus cadence this feature
#: is specified against).  Chain commits a state root at every multiple.
DEFAULT_CHECKPOINT_INTERVAL = 64

_LEN = struct.Struct(">I")
_CRC = struct.Struct(">I")
_U32 = struct.Struct(">I")
_ENTRY_TAIL = struct.Struct(">QQ")  # balance, nonce
_MANIFEST_VERSION = 1
#: Hard cap on one snapshot record (manifest or chunk) — the same bound
#: the chain store enforces, for the same reason: a corrupt length
#: prefix must not drive an unbounded read.
MAX_RECORD = 32 << 20


class SnapshotError(ValueError):
    """Snapshot bytes that fail their own integrity contract (framing,
    digest, root, or layout) — untrusted input doing what untrusted
    input does."""


# -- canonical state encoding ---------------------------------------------


def _encode_entry(account: str, balance: int, nonce: int) -> bytes:
    raw = account.encode("utf-8")
    if not 0 < len(raw) <= 255:
        raise SnapshotError(f"account id encodes to {len(raw)} bytes")
    if balance < 0 or nonce < 0:
        raise SnapshotError(f"negative state for {account!r}")
    return bytes([len(raw)]) + raw + _ENTRY_TAIL.pack(balance, nonce)


def _iter_entries(
    balances: dict[str, int], nonces: dict[str, int]
):
    """(account, balance, nonce) for every account with ANY nonzero
    state, in canonical (utf-8 byte) order — the one definition both the
    root and the chunk encoder share, so they cannot drift."""
    accounts = {a for a, v in balances.items() if v}
    accounts.update(a for a, n in nonces.items() if n)
    for account in sorted(accounts, key=lambda a: a.encode("utf-8")):
        yield account, balances.get(account, 0), nonces.get(account, 0)


def state_root(balances: dict[str, int], nonces: dict[str, int]) -> bytes:
    """Merkle root over the canonical account entries (32 bytes).  Empty
    state maps to the all-zeros root, like an empty merkle tree."""
    leaves = [
        sha256d(_encode_entry(a, b, n)) for a, b, n in _iter_entries(balances, nonces)
    ]
    return merkle_root(leaves)


def encode_chunks(
    balances: dict[str, int],
    nonces: dict[str, int],
    chunk_accounts: int = CHUNK_ACCOUNTS,
) -> list[bytes]:
    """The canonical chunk payloads: sorted entries, ``chunk_accounts``
    per chunk.  Deterministic for a given state by construction."""
    entries = [
        _encode_entry(a, b, n) for a, b, n in _iter_entries(balances, nonces)
    ]
    chunks = []
    for i in range(0, len(entries), chunk_accounts):
        part = entries[i : i + chunk_accounts]
        chunks.append(_U32.pack(len(part)) + b"".join(part))
    return chunks


def parse_chunk(payload: bytes) -> list[tuple[str, int, int]]:
    """Decode one chunk payload back to (account, balance, nonce) rows;
    raises ``SnapshotError`` on any malformation (hostile input)."""
    if len(payload) < _U32.size:
        raise SnapshotError("truncated chunk")
    (n,) = _U32.unpack_from(payload)
    off = _U32.size
    rows = []
    for _ in range(n):
        if len(payload) < off + 1:
            raise SnapshotError("truncated chunk entry")
        alen = payload[off]
        if alen == 0 or len(payload) < off + 1 + alen + _ENTRY_TAIL.size:
            raise SnapshotError("bad chunk entry")
        account = payload[off + 1 : off + 1 + alen].decode("utf-8")
        balance, nonce = _ENTRY_TAIL.unpack_from(payload, off + 1 + alen)
        rows.append((account, balance, nonce))
        off += 1 + alen + _ENTRY_TAIL.size
    if off != len(payload):
        raise SnapshotError("trailing bytes in chunk")
    return rows


def chunk_digest(payload: bytes) -> bytes:
    return sha256d(payload)


# -- the manifest ----------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Manifest:
    """The snapshot's self-description: what it claims, and the digests
    that make every other byte of it checkable against the claim."""

    height: int
    block_hash: bytes
    state_root: bytes
    accounts: int
    chunk_digests: tuple[bytes, ...]
    #: The full anchor block at ``height`` — hash, PoW, and merkle
    #: commitment are checkable before any chunk is fetched, and the
    #: header is what the assumed chain extends from.
    block: Block


def encode_manifest(m: Manifest) -> bytes:
    raw_block = m.block.serialize()
    parts = [
        bytes([_MANIFEST_VERSION]),
        struct.pack(">I", m.height),
        m.block_hash,
        m.state_root,
        struct.pack(">II", m.accounts, len(m.chunk_digests)),
        *m.chunk_digests,
        _LEN.pack(len(raw_block)),
        raw_block,
    ]
    return b"".join(parts)


def parse_manifest(payload: bytes) -> Manifest:
    """Decode + internally verify a manifest payload: the embedded block
    must hash to the claimed block hash (a manifest whose anchor does
    not even match itself is rejected before any network round)."""
    if len(payload) < 1 + 4 + 32 + 32 + 8:
        raise SnapshotError("truncated manifest")
    if payload[0] != _MANIFEST_VERSION:
        raise SnapshotError(f"unknown manifest version {payload[0]}")
    (height,) = struct.unpack_from(">I", payload, 1)
    block_hash = payload[5:37]
    root = payload[37:69]
    accounts, n_chunks = struct.unpack_from(">II", payload, 69)
    off = 77
    if len(payload) < off + 32 * n_chunks + _LEN.size:
        raise SnapshotError("truncated manifest digests")
    digests = tuple(
        payload[off + 32 * i : off + 32 * (i + 1)] for i in range(n_chunks)
    )
    off += 32 * n_chunks
    (blen,) = _LEN.unpack_from(payload, off)
    off += _LEN.size
    if len(payload) != off + blen:
        raise SnapshotError("bad manifest block length")
    try:
        block = Block.deserialize(payload[off:])
    except ValueError as e:
        raise SnapshotError(f"bad manifest anchor block: {e}") from e
    if block.block_hash() != block_hash:
        raise SnapshotError("manifest anchor block does not match its hash")
    return Manifest(height, block_hash, root, accounts, digests, block)


@dataclasses.dataclass(frozen=True)
class LedgerSnapshot:
    """A fully verified snapshot: manifest + the reconstructed state.
    ``assemble`` is the only constructor that matters — it re-derives
    every digest and the root, so holding one of these means the bytes
    were at least internally consistent (NOT that the state is true;
    that is the background revalidation's job)."""

    manifest: Manifest
    balances: dict[str, int]
    nonces: dict[str, int]

    @property
    def height(self) -> int:
        return self.manifest.height

    @property
    def block_hash(self) -> bytes:
        return self.manifest.block_hash

    @property
    def state_root(self) -> bytes:
        return self.manifest.state_root


def assemble(manifest: Manifest, chunk_payloads: list[bytes]) -> LedgerSnapshot:
    """Rebuild the state from verified parts; raises ``SnapshotError``
    on any digest/count/order/root mismatch.  This is the LAST integrity
    gate before a node dares serve the state in ASSUMED mode."""
    if len(chunk_payloads) != len(manifest.chunk_digests):
        raise SnapshotError(
            f"{len(chunk_payloads)} chunks for "
            f"{len(manifest.chunk_digests)} digests"
        )
    balances: dict[str, int] = {}
    nonces: dict[str, int] = {}
    prev_key: bytes | None = None
    total = 0
    for i, payload in enumerate(chunk_payloads):
        if chunk_digest(payload) != manifest.chunk_digests[i]:
            raise SnapshotError(f"chunk {i} fails its manifest digest")
        for account, balance, nonce in parse_chunk(payload):
            key = account.encode("utf-8")
            if prev_key is not None and key <= prev_key:
                raise SnapshotError("chunk entries out of canonical order")
            prev_key = key
            if balance:
                balances[account] = balance
            if nonce:
                nonces[account] = nonce
            total += 1
    if total != manifest.accounts:
        raise SnapshotError(
            f"{total} accounts decoded, manifest claims {manifest.accounts}"
        )
    if state_root(balances, nonces) != manifest.state_root:
        raise SnapshotError("state root mismatch")
    return LedgerSnapshot(manifest, balances, nonces)


def build_records(
    height: int,
    block: Block,
    balances: dict[str, int],
    nonces: dict[str, int],
    chunk_accounts: int = CHUNK_ACCOUNTS,
) -> tuple[bytes, list[bytes]]:
    """(manifest payload, chunk payloads) for a state — the serving
    side's one-stop shop (node GETSNAPSHOT cache, ``p1 snapshot
    create``)."""
    chunks = encode_chunks(balances, nonces, chunk_accounts)
    manifest = Manifest(
        height=height,
        block_hash=block.block_hash(),
        state_root=state_root(balances, nonces),
        accounts=sum(1 for _ in _iter_entries(balances, nonces)),
        chunk_digests=tuple(chunk_digest(c) for c in chunks),
        block=block,
    )
    return encode_manifest(manifest), chunks


# -- incremental building (round 20: continuous snapshot publication) ------


@dataclasses.dataclass
class IncrementalState:
    """The reusable residue of one ``build_records_incremental`` run:
    every per-account encoded entry and leaf hash, the canonical key
    order, and the chunk payloads + digests — everything the NEXT build
    can reuse for accounts a dirty set does not name.  Purely an
    optimization cache: holding a stale or wrong one can cost bytes
    re-encoded, never a wrong snapshot, because reuse is gated on the
    dirty set the chain derived from its own ledger applications."""

    entries: dict[str, bytes]
    leaves: dict[str, bytes]
    keys: list[str]
    chunks: list[bytes]
    digests: list[bytes]
    chunk_accounts: int
    #: Every level of the state-root merkle tree (leaves up to root,
    #: virtual odd-tail duplication — see ``_merkle_levels``) and the
    #: key → leaf-index map: together they turn the root recompute into
    #: an O(delta·log n) path update when the key set is stable, which
    #: profiling showed was the whole residual cost of a warm build.
    levels: list[list[bytes]] = dataclasses.field(default_factory=list)
    index: dict[str, int] = dataclasses.field(default_factory=dict)


def _merkle_levels(leaves: list[bytes]) -> list[list[bytes]]:
    """All levels of ``merkle_root``'s tree WITHOUT materializing the
    odd-tail duplicates (the pair step treats a missing right sibling
    as the left one, exactly like core/block.py's combine) — so a path
    update never has to keep a trailing copy coherent."""
    levels = [list(leaves)]
    while len(levels[-1]) > 1:
        lvl = levels[-1]
        levels.append(
            [
                sha256d(lvl[i] + (lvl[i + 1] if i + 1 < len(lvl) else lvl[i]))
                for i in range(0, len(lvl), 2)
            ]
        )
    return levels


def _merkle_update(levels: list[list[bytes]], changed: set[int]) -> None:
    """Recompute only the tree paths above the ``changed`` leaf indices
    (level 0 must already hold the new leaves)."""
    for depth in range(len(levels) - 1):
        lvl, up = levels[depth], levels[depth + 1]
        parents = {i // 2 for i in changed}
        for pi in sorted(parents):
            i = 2 * pi
            up[pi] = sha256d(
                lvl[i] + (lvl[i + 1] if i + 1 < len(lvl) else lvl[i])
            )
        changed = parents


def build_records_incremental(
    prev: IncrementalState | None,
    height: int,
    block: Block,
    balances: dict[str, int],
    nonces: dict[str, int],
    dirty: set[str],
    chunk_accounts: int = CHUNK_ACCOUNTS,
) -> tuple[bytes, list[bytes], IncrementalState, int]:
    """``build_records``, continuously: re-encode and re-hash ONLY the
    accounts in ``dirty`` (plus any the previous build never saw),
    reuse untouched chunk payloads and digests outright, and return
    the new reusable state alongside ``(manifest_payload, chunks)``
    plus the count of chunks reused verbatim.

    **Byte-identity contract** (pinned in tests): the manifest and
    chunk payloads are byte-for-byte what ``build_records`` produces
    for the same state — incremental is a cost model, never a format.

    **Correctness contract on ``dirty``**: it must be a superset of
    every account whose (balance, nonce) differs from the state
    ``prev`` was built over — the chain guarantees this by recording
    touched accounts on BOTH apply and undo, so reorgs and tip
    advances alike land in the set.  A too-big set only costs reuse.

    Cost: O(delta·log accounts) on the steady-state path — the key set
    is stable (no account created or emptied), so entry encodes, leaf
    hashes, chunk joins, and the merkle path updates are all bounded by
    the delta, and the O(accounts) work left is pointer copies of the
    cached dicts/levels.  A membership change (create/delete shifts the
    canonical order) degrades that build to the O(accounts) re-sort and
    tree rebuild, exactly like the chunk-reuse gate below.
    """
    # Steady-state fast path: by the dirty-superset contract, the key
    # set can only change at accounts the dirty set names — if each of
    # those keeps its membership (existed before and still has state,
    # or neither), the canonical order is prev's, verbatim.
    if (
        prev is not None
        and prev.levels
        and prev.chunk_accounts == chunk_accounts
        and all(
            (a in prev.entries)
            == bool(balances.get(a, 0) or nonces.get(a, 0))
            for a in dirty
        )
    ):
        keys = prev.keys
        entries = dict(prev.entries)
        leaves = dict(prev.leaves)
        levels = [lvl.copy() for lvl in prev.levels]
        changed: set[int] = set()
        for a in dirty:
            if a not in entries:
                continue  # touched but stateless before and after
            e = _encode_entry(a, balances.get(a, 0), nonces.get(a, 0))
            if entries[a] == e:
                continue  # dirty is a superset; this one didn't move
            entries[a] = e
            leaves[a] = sha256d(e)
            pos = prev.index[a]
            levels[0][pos] = leaves[a]
            changed.add(pos)
        if changed:
            _merkle_update(levels, changed)
        chunks = list(prev.chunks)
        digests = list(prev.digests)
        dirty_chunks = sorted({pos // chunk_accounts for pos in sorted(changed)})
        for ci in dirty_chunks:
            i = ci * chunk_accounts
            part_keys = keys[i : i + chunk_accounts]
            payload = _U32.pack(len(part_keys)) + b"".join(
                entries[a] for a in part_keys
            )
            chunks[ci] = payload
            digests[ci] = chunk_digest(payload)
        reused = len(chunks) - len(dirty_chunks)
        manifest = Manifest(
            height=height,
            block_hash=block.block_hash(),
            state_root=levels[-1][0] if keys else merkle_root([]),
            accounts=len(keys),
            chunk_digests=tuple(digests),
            block=block,
        )
        state = IncrementalState(
            entries=entries,
            leaves=leaves,
            keys=keys,
            chunks=chunks,
            digests=digests,
            chunk_accounts=chunk_accounts,
            levels=levels,
            index=prev.index,
        )
        return encode_manifest(manifest), chunks, state, reused

    accounts = {a for a, v in balances.items() if v}
    accounts.update(a for a, n in nonces.items() if n)
    keys = sorted(accounts, key=lambda a: a.encode("utf-8"))
    reuse_entries = prev is not None
    entries: dict[str, bytes] = {}
    leaves: dict[str, bytes] = {}
    for a in keys:
        if reuse_entries and a not in dirty and a in prev.entries:
            entries[a] = prev.entries[a]
            leaves[a] = prev.leaves[a]
        else:
            e = _encode_entry(a, balances.get(a, 0), nonces.get(a, 0))
            entries[a] = e
            leaves[a] = sha256d(e)
    chunks: list[bytes] = []
    digests: list[bytes] = []
    reused = 0
    reuse_chunks = prev is not None and prev.chunk_accounts == chunk_accounts
    for ci, i in enumerate(range(0, len(keys), chunk_accounts)):
        part_keys = keys[i : i + chunk_accounts]
        if (
            reuse_chunks
            and ci < len(prev.chunks)
            and prev.keys[i : i + chunk_accounts] == part_keys
            and not any(a in dirty for a in part_keys)
        ):
            chunks.append(prev.chunks[ci])
            digests.append(prev.digests[ci])
            reused += 1
            continue
        payload = _U32.pack(len(part_keys)) + b"".join(
            entries[a] for a in part_keys
        )
        chunks.append(payload)
        digests.append(chunk_digest(payload))
    levels = _merkle_levels([leaves[a] for a in keys]) if keys else []
    manifest = Manifest(
        height=height,
        block_hash=block.block_hash(),
        state_root=levels[-1][0] if keys else merkle_root([]),
        accounts=len(keys),
        chunk_digests=tuple(digests),
        block=block,
    )
    state = IncrementalState(
        entries=entries,
        leaves=leaves,
        keys=keys,
        chunks=chunks,
        digests=digests,
        chunk_accounts=chunk_accounts,
        levels=levels,
        index={a: i for i, a in enumerate(keys)},
    )
    return encode_manifest(manifest), chunks, state, reused


# -- the file format -------------------------------------------------------


def _frame(payload: bytes) -> bytes:
    prefix = _LEN.pack(len(payload))
    return prefix + payload + _CRC.pack(zlib.crc32(payload, zlib.crc32(prefix)))


def write_snapshot(path, manifest_payload: bytes, chunk_payloads: list[bytes]) -> None:
    """Atomic snapshot file write: tmp + fsync + rename + directory
    fsync (the chain store's durability discipline — a half-written
    snapshot must never exist under the real name)."""
    import os

    from p1_tpu.chain.store import fsync_dir

    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(f"{path.name}.tmp.{os.getpid()}")
    with open(tmp, "wb") as fh:
        fh.write(MAGIC)
        fh.write(_frame(manifest_payload))
        for chunk in chunk_payloads:
            fh.write(_frame(chunk))
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    fsync_dir(path.parent)


def _scan_records(data: bytes) -> tuple[list[bytes], list[str]]:
    """(payloads, issues) from a snapshot file's raw bytes.  Framing
    damage is reported, never trusted: a record that fails its CRC ends
    the scan (everything behind it is unreachable — unlike the chain
    store, snapshot records have no independent value worth resyncing
    for: an incomplete chunk set is unusable anyway)."""
    issues: list[str] = []
    if not data.startswith(MAGIC):
        raise SnapshotError("not a snapshot file")
    payloads: list[bytes] = []
    off = len(MAGIC)
    while off < len(data):
        if off + _LEN.size + _CRC.size > len(data):
            issues.append(f"torn tail at {off}")
            break
        (n,) = _LEN.unpack_from(data, off)
        if n > MAX_RECORD:
            issues.append(f"oversized record length at {off}")
            break
        end = off + _LEN.size + n + _CRC.size
        if end > len(data):
            issues.append(f"torn record at {off}")
            break
        body_end = end - _CRC.size
        if zlib.crc32(data[off:body_end]) != _CRC.unpack_from(data, body_end)[0]:
            issues.append(f"checksum mismatch at {off}")
            break
        payloads.append(data[off + _LEN.size : body_end])
        off = end
    return payloads, issues


def read_records(path) -> tuple[bytes, list[bytes], list[str]]:
    """(manifest payload, chunk payloads, framing issues) from a
    snapshot file.  Raises ``SnapshotError`` when no manifest record is
    readable at all."""
    data = Path(path).read_bytes()
    payloads, issues = _scan_records(data)
    if not payloads:
        raise SnapshotError(f"{path}: no readable snapshot records")
    return payloads[0], payloads[1:], issues


def load_snapshot(path) -> LedgerSnapshot:
    """Read + fully verify a snapshot file (manifest parse, chunk
    digests, state root).  The boot path: everything a node needs to
    enter ASSUMED mode, or a ``SnapshotError`` explaining why not."""
    manifest_payload, chunk_payloads, _issues = read_records(path)
    manifest = parse_manifest(manifest_payload)
    # Extra records past the manifest's chunk count are tolerated as
    # framing noise only when the needed set is complete and verifies.
    return assemble(manifest, chunk_payloads[: len(manifest.chunk_digests)])


def verify_file(path) -> dict:
    """The `p1 snapshot verify` engine: a JSON-ready report plus the
    documented exit verdict — 0 clean, 1 salvageable issue (framing
    noise past a complete, root-verified snapshot), 2 unrecoverable
    (unreadable manifest, missing/corrupt chunks, digest or root
    mismatch)."""
    path = Path(path)
    report: dict = {"snapshot": str(path)}
    if not path.exists():
        report.update(status="missing", verdict=2)
        return report
    try:
        manifest_payload, chunk_payloads, issues = read_records(path)
        manifest = parse_manifest(manifest_payload)
    except SnapshotError as e:
        report.update(status="unrecoverable", error=str(e), verdict=2)
        return report
    report.update(
        height=manifest.height,
        block_hash=manifest.block_hash.hex(),
        state_root=manifest.state_root.hex(),
        accounts=manifest.accounts,
        chunks=len(manifest.chunk_digests),
        chunks_present=len(chunk_payloads),
        issues=issues,
    )
    if len(chunk_payloads) > len(manifest.chunk_digests):
        issues.append(
            f"{len(chunk_payloads) - len(manifest.chunk_digests)} extra "
            "records past the manifest's chunk count"
        )
    try:
        assemble(manifest, chunk_payloads[: len(manifest.chunk_digests)])
    except SnapshotError as e:
        report.update(status="unrecoverable", error=str(e), verdict=2)
        return report
    if issues:
        # The needed record set is complete and verifies end to end;
        # the damage is confined to bytes past it — rewriting the file
        # from the verified records recovers a clean snapshot.
        report.update(status="salvageable", verdict=1)
    else:
        report.update(status="clean", verdict=0)
    return report
