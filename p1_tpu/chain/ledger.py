"""Account ledger: balances + account nonces as consensus state.

Capability parity: the reference is "a Bitcoin-like toy cryptocurrency"
whose "chain-validation code paths" are a named capability (BASELINE.json:5
via SURVEY.md §0).  Round 4 makes account state *consensus*: a block that
spends money its sender does not have — or replays an already-confirmed
authorization — cannot connect to the main chain.

Two layers live here:

- ``Ledger`` — the incremental account state at the chain tip.  ``Chain``
  applies blocks as the tip advances and *undoes* them across reorgs using
  the exact removed/added paths ``add_block`` already computes, so keeping
  the ledger current is O(blocks moved), never O(chain).  ``apply_block``
  is transactional: it validates the whole block against the running state
  (in tx order — a transfer may spend coins received earlier in the same
  block, including the block's own coinbase) and raises ``LedgerError``
  without mutating anything if any transfer overdraws or reuses a
  sequence number.
- ``balances`` — the original pure *view* over an arbitrary block
  iterable, kept for audit (``p1 balances`` on a store) and as a test
  oracle against the incremental state.  The view itself never rejects;
  on a consensus-valid main chain it can never print a negative balance
  because ``Chain`` refused the overdraw at connect time.

Rules (mirrored exactly by the view): the coinbase credits its recipient
the block subsidy; each transfer debits sender ``amount + fee`` (must not
overdraw at its position in block order) and credits the recipient; the
summed fees credit the block's miner (its coinbase recipient) at block end,
or are burned for the rare coinbase-less block.

**Sequence numbers are strict account nonces** (the Ethereum account-model
rule): transfer i from an account must carry ``seq`` equal to the number
of transfers that account has already confirmed on this chain, so one
signed authorization spends exactly once — a hostile miner re-including a
confirmed transfer in a later block fails ``seq == nonce`` and the block
cannot connect.  Nonces are part of the undo state: a reorg that abandons
a spend rolls the nonce back, and the transaction becomes valid to
re-confirm on the new branch (the mempool resurrects it).
"""

from __future__ import annotations

import dataclasses
from typing import Iterable

from p1_tpu.core.block import Block


class LedgerError(Exception):
    """A block's transfers overdraw an account or reuse a sequence number
    (contextual invalidity)."""


@dataclasses.dataclass
class _BlockDelta:
    """Net effect of one block: balance shifts + per-sender transfer counts."""

    balances: dict[str, int]
    nonces: dict[str, int]


class Ledger:
    """Mutable account state (balances + nonces) with transactional block
    apply/undo."""

    def __init__(self) -> None:
        self._balances: dict[str, int] = {}
        #: account -> number of transfers it has confirmed (= the seq its
        #: NEXT transfer must carry).  Absent key = 0.
        self._nonces: dict[str, int] = {}

    def balance(self, account: str) -> int:
        return self._balances.get(account, 0)

    def nonce(self, account: str) -> int:
        """The seq the account's next transfer must carry."""
        return self._nonces.get(account, 0)

    def snapshot(self) -> dict[str, int]:
        """Copy of all non-zero balances (JSON-ready, for status/CLI)."""
        return {a: v for a, v in self._balances.items() if v}

    def nonces_snapshot(self) -> dict[str, int]:
        """Copy of all non-zero account nonces — the other half of the
        consensus state a snapshot (chain/snapshot.py) must carry: a
        snapshot that restored balances but forgot nonces would re-open
        every confirmed authorization for replay."""
        return {a: n for a, n in self._nonces.items() if n}

    def copy(self) -> "Ledger":
        """Independent copy of the full state — what checkpoint-state
        materialization rolls back (``Chain.snapshot_state``) without
        touching the live tip ledger."""
        dup = Ledger()
        dup._balances = dict(self._balances)
        dup._nonces = dict(self._nonces)
        return dup

    @classmethod
    def restore(
        cls, balances: dict[str, int], nonces: dict[str, int]
    ) -> "Ledger":
        """A ledger seeded from externally supplied state (a verified
        snapshot).  Zero entries are dropped on the way in so the
        invariant ``_shift`` maintains (no zero-valued keys) holds from
        the first block applied."""
        ledger = cls()
        ledger._balances = {a: v for a, v in balances.items() if v}
        ledger._nonces = {a: n for a, n in nonces.items() if n}
        return ledger

    def apply_block(self, block: Block) -> None:
        """Credit/debit ``block``'s transactions; all-or-nothing.

        Raises ``LedgerError`` (leaving the ledger untouched) if any
        transfer overdraws its sender or carries a wrong sequence number
        at its position in block order.
        """
        self._shift(self._block_delta(block, check=True), +1)

    def undo_block(self, block: Block) -> None:
        """Reverse a previously-applied block (reorg rollback).  Never
        fails: the inverse of a valid application is always consistent."""
        self._shift(self._block_delta(block, check=False), -1)

    def _shift(self, delta: _BlockDelta, sign: int) -> None:
        """Merge a block delta into the state (zero entries are dropped) —
        the ONE place the merge rule lives."""
        for account, d in delta.balances.items():
            v = self._balances.get(account, 0) + sign * d
            if v:
                self._balances[account] = v
            else:
                self._balances.pop(account, None)
        for account, n in delta.nonces.items():
            v = self._nonces.get(account, 0) + sign * n
            if v:
                self._nonces[account] = v
            else:
                self._nonces.pop(account, None)

    def _block_delta(self, block: Block, check: bool) -> _BlockDelta:
        """Net effect of ``block``; with ``check`` the running (base +
        partial delta) balance is enforced non-negative at every debit and
        every transfer's seq must equal its sender's running nonce, in tx
        order."""
        delta: dict[str, int] = {}
        counts: dict[str, int] = {}
        miner: str | None = None
        fees = 0
        for i, tx in enumerate(block.txs):
            if i == 0 and tx.is_coinbase:
                miner = tx.recipient
                delta[miner] = delta.get(miner, 0) + tx.amount
                continue
            if check:
                expected = self._nonces.get(tx.sender, 0) + counts.get(
                    tx.sender, 0
                )
                if tx.seq != expected:
                    raise LedgerError(
                        f"tx {tx.txid().hex()[:16]} has seq {tx.seq}, "
                        f"{tx.sender} is at nonce {expected} (replay or gap)"
                    )
                cost = tx.amount + tx.fee
                have = self._balances.get(tx.sender, 0) + delta.get(
                    tx.sender, 0
                )
                if have < cost:
                    raise LedgerError(
                        f"tx {tx.txid().hex()[:16]} overdraws {tx.sender}: "
                        f"spends {cost}, has {have}"
                    )
            counts[tx.sender] = counts.get(tx.sender, 0) + 1
            delta[tx.sender] = delta.get(tx.sender, 0) - (tx.amount + tx.fee)
            delta[tx.recipient] = delta.get(tx.recipient, 0) + tx.amount
            fees += tx.fee
        if miner is not None and fees:
            delta[miner] = delta.get(miner, 0) + fees
        return _BlockDelta(delta, counts)


def balances(blocks: Iterable[Block]) -> dict[str, int]:
    """Account -> balance over ``blocks`` (pass ``chain.main_chain()``).

    Pure audit view — applies the same rules as ``Ledger`` but never
    rejects, so it can also describe hypothetical or pre-consensus block
    sequences in tests.
    """
    ledger = Ledger()
    for block in blocks:
        ledger._shift(ledger._block_delta(block, check=False), +1)
    return dict(ledger._balances)
