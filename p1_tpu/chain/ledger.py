"""Ledger view: account balances derived from the main chain.

Capability parity: the reference is "a Bitcoin-like toy cryptocurrency"
(BASELINE.json:5 via SURVEY.md §0) — a currency needs a way to ask who
owns what.  This is a pure *view* over the chain's account model: coinbase
credits the miner the block reward, a transfer debits sender by
amount + fee and credits the recipient, and fees go to the block's miner
(its coinbase recipient) or are burned for the rare coinbase-less block.

Deliberately NOT consensus: chain validation does not enforce
non-negative balances (the chain carries no account state — see the
mempool scope note), so a balance can legitimately print negative here;
that is information about the chain, not an error in the view.
"""

from __future__ import annotations

from typing import Iterable

from p1_tpu.core.block import Block


def balances(blocks: Iterable[Block]) -> dict[str, int]:
    """Account -> balance over ``blocks`` (pass ``chain.main_chain()``)."""
    out: dict[str, int] = {}

    def credit(account: str, amount: int) -> None:
        out[account] = out.get(account, 0) + amount

    for block in blocks:
        miner = None
        fees = 0
        for i, tx in enumerate(block.txs):
            if i == 0 and tx.is_coinbase:
                miner = tx.recipient
                credit(miner, tx.amount)
                continue
            credit(tx.sender, -(tx.amount + tx.fee))
            credit(tx.recipient, tx.amount)
            fees += tx.fee
        if miner is not None and fees:
            credit(miner, fees)
    return out
