"""Stateless block validation rules.

Capability parity: the reference's "chain-validation code paths"
(BASELINE.json:5).  Rules enforced here need no chain context beyond the
expected difficulty; linkage/height rules live in ``chain.py`` where the
block index is.
"""

from __future__ import annotations

from p1_tpu.core.block import Block, merkle_root
from p1_tpu.core.genesis import genesis_hash
from p1_tpu.core.header import meets_target
from p1_tpu.core.tx import BLOCK_REWARD


class ValidationError(Exception):
    """A block or header failed consensus validation."""


def check_block(
    block: Block,
    expected_difficulty: int,
    *,
    is_genesis: bool = False,
    chain_tag: bytes | None = None,
) -> None:
    """Raise ``ValidationError`` unless ``block`` is internally valid.

    Checks: declared difficulty matches the chain's, proof-of-work meets the
    target (waived for genesis, which anchors by identity), the merkle root
    commits to exactly these transactions, no txid appears twice —
    the duplicate-txid rejection promised at p1_tpu/core/block.py:25
    (CVE-2012-2459: duplicating the odd tail leaf forges a same-root block) —
    the coinbase mints exactly ``BLOCK_REWARD`` (a hostile miner cannot set
    an arbitrary subsidy; fees are credited separately by the ledger), and
    every transfer carries a valid Ed25519 ownership proof
    (``Transaction.verify_signature`` — only the key holder can spend).
    """
    # Digest costs here are one-time per object: block_hash/txid/merkle
    # are memoized on the frozen types, and for a wire-ingested block
    # they digest the arrival bytes — validation adds no packing.
    header = block.header
    if header.difficulty != expected_difficulty:
        raise ValidationError(
            f"difficulty {header.difficulty} != chain difficulty {expected_difficulty}"
        )
    if not is_genesis and not meets_target(block.block_hash(), header.difficulty):
        raise ValidationError("proof of work does not meet target")
    txids = [tx.txid() for tx in block.txs]
    if len(set(txids)) != len(txids):
        raise ValidationError("duplicate txid in block")
    # Structure before signatures (cheap hash checks gate the ~100 µs/tx
    # Ed25519 verifies): the root must commit to these exact transactions
    # before their ownership proofs are worth checking.  The root is
    # recombined from the txid list already in hand (one digest pass per
    # transaction for the whole check).
    if merkle_root(txids) != header.merkle_root:
        raise ValidationError("merkle root mismatch")
    # A coinbase (block-reward tx) is optional, but if present it must be
    # the first transaction and unique — any coinbase at index > 0 covers
    # both the misplaced and the duplicate case.
    # The chain id transfers must be signed for: the ACTUAL genesis when
    # the caller has one (Chain passes its own — which may be a custom
    # genesis — so we never diverge from what HELLO/mempool advertise);
    # derived from the difficulty for standalone stateless checks.
    if chain_tag is None:
        chain_tag = genesis_hash(expected_difficulty)
    for i, tx in enumerate(block.txs):
        if tx.is_coinbase:
            if i > 0:
                raise ValidationError(
                    "coinbase transaction must be first and unique"
                )
            if tx.amount != BLOCK_REWARD:
                raise ValidationError(
                    f"coinbase mints {tx.amount}, subsidy is {BLOCK_REWARD}"
                )
        elif tx.chain != chain_tag:
            # The signature is chain-bound: a spend signed for another
            # chain (or with no tag at all) cannot be replayed here.
            raise ValidationError("transaction signed for a different chain")
        if not tx.verify_signature():
            raise ValidationError(
                "bad transaction signature"
                if not tx.is_coinbase
                else "coinbase must be unsigned"
            )
