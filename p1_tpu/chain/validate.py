"""Stateless block validation rules.

Capability parity: the reference's "chain-validation code paths"
(BASELINE.json:5).  Rules enforced here need no chain context beyond the
expected difficulty; linkage/height rules live in ``chain.py`` where the
block index is.
"""

from __future__ import annotations

from p1_tpu.core.block import Block
from p1_tpu.core.header import meets_target


class ValidationError(Exception):
    """A block or header failed consensus validation."""


def check_block(block: Block, expected_difficulty: int, *, is_genesis: bool = False) -> None:
    """Raise ``ValidationError`` unless ``block`` is internally valid.

    Checks: declared difficulty matches the chain's, proof-of-work meets the
    target (waived for genesis, which anchors by identity), the merkle root
    commits to exactly these transactions, and no txid appears twice —
    the duplicate-txid rejection promised at p1_tpu/core/block.py:25
    (CVE-2012-2459: duplicating the odd tail leaf forges a same-root block).
    """
    header = block.header
    if header.difficulty != expected_difficulty:
        raise ValidationError(
            f"difficulty {header.difficulty} != chain difficulty {expected_difficulty}"
        )
    if not is_genesis and not meets_target(block.block_hash(), header.difficulty):
        raise ValidationError("proof of work does not meet target")
    txids = [tx.txid() for tx in block.txs]
    if len(set(txids)) != len(txids):
        raise ValidationError("duplicate txid in block")
    # A coinbase (block-reward tx) is optional, but if present it must be
    # the first transaction and unique — any coinbase at index > 0 covers
    # both the misplaced and the duplicate case.
    for i, tx in enumerate(block.txs):
        if i > 0 and tx.is_coinbase:
            raise ValidationError("coinbase transaction must be first and unique")
    if block.compute_merkle_root() != header.merkle_root:
        raise ValidationError("merkle root mismatch")
