"""Stateless block validation rules.

Capability parity: the reference's "chain-validation code paths"
(BASELINE.json:5).  Rules enforced here need no chain context beyond the
expected difficulty; linkage/height rules live in ``chain.py`` where the
block index is.

Validation fast lane (round 8): signature checking is **batch-first**.
Cheap hash/structure checks still gate exactly as before, then every
signature the verify-once cache (core/sigcache.py) cannot vouch for is
verified as ONE batch (``keys.verify_batch`` — threaded with the
``cryptography`` wheel, one subgroup-gated multi-scalar multiplication
in the pure-Python fallback).  Equivalence with the serial path is a
hard contract, held two ways:

- **Outcome**: batch acceptance implies serial acceptance of every
  member, and a batch failure is settled by ``keys.first_invalid``'s
  serial confirmation — which may conclude NO signature is serially
  invalid (the fallback gate rejects torsion-crafted inputs the serial
  equation tolerates), in which case the block is accepted exactly as
  the serial path would.  Rejected transaction and raised error text
  are byte-identical to what the old per-tx loop produced —
  property-tested with corrupted signatures at every position and with
  torsion-crafted fixtures (tests/test_sigbatch.py).
- **Ordering**: serial validation interleaves per-tx structural checks
  with per-tx signature checks, and every signature failure raises the
  same text regardless of index — so running the structural walk first
  and the signature batch second can only ever change WHICH failing
  transaction gets named between two failures that share one message.
  The walk records the first structural error and raises it only after
  the signatures of every EARLIER transaction proved valid, preserving
  the serial precedence.
"""

from __future__ import annotations

from p1_tpu.core import keys as _keys
from p1_tpu.core import sigcache as _sigcache
from p1_tpu.core.block import Block, merkle_root
from p1_tpu.core.genesis import genesis_hash
from p1_tpu.core.header import meets_target
from p1_tpu.core.tx import BLOCK_REWARD


class ValidationError(Exception):
    """A block or header failed consensus validation."""


def check_block(
    block: Block,
    expected_difficulty: int,
    *,
    is_genesis: bool = False,
    chain_tag: bytes | None = None,
    sig_cache=None,
) -> None:
    """Raise ``ValidationError`` unless ``block`` is internally valid.

    Checks: declared difficulty matches the chain's, proof-of-work meets the
    target (waived for genesis, which anchors by identity), the merkle root
    commits to exactly these transactions, no txid appears twice —
    the duplicate-txid rejection promised at p1_tpu/core/block.py:25
    (CVE-2012-2459: duplicating the odd tail leaf forges a same-root block) —
    the coinbase mints exactly ``BLOCK_REWARD`` (a hostile miner cannot set
    an arbitrary subsidy; fees are credited separately by the ledger), and
    every transfer carries a valid Ed25519 ownership proof (only the key
    holder can spend) — consulted against ``sig_cache`` first (None = the
    process default), then batch-verified (module docstring).
    """
    # Digest costs here are one-time per object: block_hash/txid/merkle
    # are memoized on the frozen types, and for a wire-ingested block
    # they digest the arrival bytes — validation adds no packing.
    header = block.header
    if header.difficulty != expected_difficulty:
        raise ValidationError(
            f"difficulty {header.difficulty} != chain difficulty {expected_difficulty}"
        )
    if not is_genesis and not meets_target(block.block_hash(), header.difficulty):
        raise ValidationError("proof of work does not meet target")
    txids = [tx.txid() for tx in block.txs]
    if len(set(txids)) != len(txids):
        raise ValidationError("duplicate txid in block")
    # Structure before signatures (cheap hash checks gate the Ed25519
    # verifies): the root must commit to these exact transactions
    # before their ownership proofs are worth checking.  The root is
    # recombined from the txid list already in hand (one digest pass per
    # transaction for the whole check).
    if merkle_root(txids) != header.merkle_root:
        raise ValidationError("merkle root mismatch")
    # The chain id transfers must be signed for: the ACTUAL genesis when
    # the caller has one (Chain passes its own — which may be a custom
    # genesis — so we never diverge from what HELLO/mempool advertise);
    # derived from the difficulty for standalone stateless checks.
    if chain_tag is None:
        chain_tag = genesis_hash(expected_difficulty)
    if sig_cache is None:
        sig_cache = _sigcache.DEFAULT
    # Structural walk: everything per-tx that is cheap — coinbase
    # placement/subsidy/bareness, the chain tag, the sender-fingerprint
    # binding, and the cache consult.  Stops at the first structural
    # failure; the expensive Ed25519 math for the transactions BEFORE it
    # still runs below, because serially an earlier bad signature would
    # have been reported first.
    structural: str | None = None
    pending = []  # transactions whose Ed25519 proof still needs checking
    for i, tx in enumerate(block.txs):
        if tx.is_coinbase:
            # A coinbase (block-reward tx) is optional, but if present it
            # must be the first transaction and unique — any coinbase at
            # index > 0 covers both the misplaced and the duplicate case.
            if i > 0:
                structural = "coinbase transaction must be first and unique"
                break
            if tx.amount != BLOCK_REWARD:
                structural = (
                    f"coinbase mints {tx.amount}, subsidy is {BLOCK_REWARD}"
                )
                break
            if tx.pubkey or tx.sig or tx.chain:
                structural = "coinbase must be unsigned"
                break
            continue
        if tx.chain != chain_tag:
            # The signature is chain-bound: a spend signed for another
            # chain (or with no tag at all) cannot be replayed here.
            structural = "transaction signed for a different chain"
            break
        if tx.sender != _keys.account_id_or_none(tx.pubkey):
            structural = "bad transaction signature"
            break
        if not sig_cache.hit(tx.txid(), tx.pubkey, tx.sig):
            pending.append(tx)
    if pending:
        triples = [
            (tx.pubkey, tx.sig, tx.signing_bytes()) for tx in pending
        ]
        if len(pending) >= _keys.BATCH_MIN:
            ok = _keys.verify_batch(triples)
            if not ok:
                # A failed batch is not yet a verdict: the fallback's
                # subgroup gate also rejects torsion-crafted inputs the
                # serial equation tolerates, so the serial confirmation
                # decides — identical outcome AND identical error text
                # to the per-tx loop, whichever way it lands.
                ok = _keys.first_invalid(triples) is None
        else:
            ok = all(
                _keys.verify(*t) for t in triples
            )  # tiny blocks: batch setup costs more than it saves
        if not ok:
            raise ValidationError("bad transaction signature")
        for tx in pending:
            sig_cache.add(tx.txid(), tx.pubkey, tx.sig)
    if structural is not None:
        raise ValidationError(structural)


#: Signatures per pre-verification window: what the deep-sync and
#: revalidation drivers accumulate before one ``verify_batch`` call.
#: Past ~1k the fallback MSM's per-signature gain is nearly flat and the
#: wheel path's chunks parallelize regardless, while the buffered window
#: keeps streaming resume memory O(window).
PREVERIFY_WINDOW = 4096


def preverify_signatures(txs, chain_tag: bytes, sig_cache=None) -> int:
    """Optimistically batch-verify transfer signatures into the cache.

    A pure cache-warmer for the untrusted bulk paths (store revalidation,
    deep-sync block batches, mempool sync pages): transactions whose
    Ed25519 proof checks out are recorded in ``sig_cache`` so the
    per-block ``check_block`` that follows hits instead of paying the
    backend; everything that does NOT check out here (bad signature,
    foreign tag, fingerprint mismatch) is simply left uncached, and the
    consensus path re-derives its exact serial verdict.  Cannot change
    any outcome — only where the verify cost is paid.  Returns the
    number of signatures proven (cache hits don't count).
    """
    if sig_cache is None:
        sig_cache = _sigcache.DEFAULT
    candidates = []
    for tx in txs:
        if (
            tx.is_coinbase
            or tx.chain != chain_tag
            or tx.sender != _keys.account_id_or_none(tx.pubkey)
        ):
            continue  # structurally doomed or unsigned: not our problem
        if not sig_cache.hit(tx.txid(), tx.pubkey, tx.sig):
            candidates.append(tx)
    proven = 0
    stack = [candidates] if candidates else []
    while stack:
        group = stack.pop()
        triples = [(tx.pubkey, tx.sig, tx.signing_bytes()) for tx in group]
        if _keys.verify_batch(triples):
            for tx in group:
                sig_cache.add(tx.txid(), tx.pubkey, tx.sig)
            proven += len(group)
        elif len(group) == 1:
            # Settled serially (a singleton batch IS the serial path —
            # size < BATCH_MIN), so an uncached leftover here really is
            # a serial reject, never a torsion false-negative.
            continue  # genuinely bad: leave uncached for the serial path
        else:
            # Bisect: cache the valid side(s), isolate the bad ones.
            mid = len(group) // 2
            stack.append(group[mid:])
            stack.append(group[:mid])
    return proven
