"""Segmented chain persistence: bounded segment files behind the
``ChainStore`` API (round 18 — archive-scale durability).

The single append-only log made chain length a *whole-file* problem:
one mid-log corruption heal or compaction rewrites the world, and the
blast radius of any disk fault is the entire archive.  This module
shards the log the Bitcoin-Core way (``blk*.dat`` lineage): records —
v3 CRC framing byte-identical to ``chain/store.py`` — land in bounded
``segNNNNN.p1s`` files under ``<store>.d/``, and the store path itself
becomes a small CRC-framed **manifest** mapping segments to their
height spans.  What that buys, per segment:

- **containment** — ``p1 fsck`` scans/salvages/quarantines ONE segment;
  mid-log corruption loses at most one segment's bad span, never the
  archive, and every other segment's bytes are untouched;
- **bounded compaction** — only segments holding reorged-away records
  are rewritten (tmp + rename + dir-fsync per segment), so compacting
  a 10M-block archive costs O(dirty), not O(chain);
- **pruning** — body segments wholly below a snapshot base can be
  discarded (``prune_below``) while their packed-header sidecar
  (chain/headerplane.py ``.hdrx``) keeps header/PoW service alive —
  the serve-only degradation the pruned node mode builds on.

Durability discipline, unchanged from round 7 but now per boundary:
segment rolls fsync the sealed file, then the new segment's first
bytes, then the directory, and only then rewrite the manifest via
tmp + rename + dir-fsync — a crash at ANY boundary leaves a layout the
next ``acquire`` recovers (stray segment files are adopted, a corrupt
or missing manifest is rebuilt from the directory listing; the
manifest is a cache of the segment set, never the only copy of it).

**Lossless upgrade**: a writer acquiring an old single-file v3 store
hard-links it into place as ``seg00000.p1s`` (same inode — the record
bytes are never copied, so the upgrade is byte-lossless by
construction) and replaces the path with a manifest.  Read-only
attaches of single-file stores keep working everywhere — readers sniff
the magic.

The writer lock moves to a stable ``<store>.lock`` sidecar (the
manifest inode is replaced on every roll, so a flock on it would
protect nothing); during the upgrade the old single file is ALSO
flocked so a legacy writer can never race the conversion.
"""

from __future__ import annotations

import dataclasses
import fcntl
import json
import os
import struct
import zlib
from pathlib import Path

from p1_tpu.chain.store import (
    _CRC,
    _LEN,
    _MAX_RECORD,
    MAGIC,
    V2_MAGIC,
    ChainStore,
    StoreScan,
)

#: Manifest format tag (the store path's new magic).  Sniffable by every
#: reader: single-file stores start ``P1TPUCH*``, segmented ones this.
SEG_MAGIC = b"P1TPUSG1"

#: Default segment bound.  Small enough that a heal/compaction rewrite
#: is a sub-second local event, large enough that a 100k-block store is
#: a handful of files, not thousands.
DEFAULT_SEGMENT_BYTES = 64 << 20

#: Span packing: ``(seg_id << _SEG_SHIFT) | (offset << _SPAN_SHIFT) |
#: length``.  Offset gets 30 bits, so a segment file may not exceed
#: 1 GiB — enforced against ``segment_bytes`` at construction (the
#: record that OVERFLOWS the bound still lands in the old segment, so
#: the true file cap is ``segment_bytes + _MAX_RECORD`` and the bound
#: check leaves headroom).
_SPAN_SHIFT = 26
_SEG_SHIFT = 56
_MAX_SEGMENT_BYTES = (1 << (_SEG_SHIFT - _SPAN_SHIFT)) - _MAX_RECORD - 64

#: Bound on cached per-segment read fds (pread plane).  Evicts oldest;
#: a 10M-block archive at default bounds is ~40 segments, well under.
_MAX_READ_FDS = 64


@dataclasses.dataclass
class SegmentInfo:
    """One segment's manifest row."""

    seg_id: int
    sealed: bool = False
    pruned: bool = False
    records: int = 0
    bytes: int = 0
    #: Height span of the records inside (maintained by ``append``'s
    #: ``height`` hint).  None = unknown (adopted/rebuilt/foreign
    #: segments) — unknown spans are never prunable, by design.
    min_height: int | None = None
    max_height: int | None = None

    @property
    def name(self) -> str:
        return f"seg{self.seg_id:05d}.p1s"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, d: dict) -> "SegmentInfo":
        return cls(**{f.name: d.get(f.name) for f in dataclasses.fields(cls)})


def _torn_magic(data: bytes) -> bool:
    """True for a file holding a strict PREFIX of the v3 magic — the
    on-disk shape of a crash that tore the new segment's very first
    write mid-roll.  Recovers as an empty segment (no record ever
    landed there)."""
    return len(data) < len(MAGIC) and MAGIC.startswith(data)


def read_manifest(path) -> dict | None:
    """Parse the manifest at ``path`` (None when missing/corrupt) —
    shared by the store and lock-free readers (the query plane's
    ReplicaView re-reads it on every roll)."""
    try:
        data = Path(path).read_bytes()
    except OSError:
        return None
    if not data.startswith(SEG_MAGIC):
        return None
    off = len(SEG_MAGIC)
    if off + _LEN.size + _CRC.size > len(data):
        return None
    (n,) = _LEN.unpack_from(data, off)
    end = off + _LEN.size + n
    if end + _CRC.size > len(data):
        return None
    body = data[off:end]
    if zlib.crc32(body) != _CRC.unpack_from(data, end)[0]:
        return None
    try:
        return json.loads(data[off + _LEN.size : end].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError):
        return None


def is_segmented(path) -> bool:
    """True when ``path`` holds a segment manifest (vs a single-file
    log, a v2 store, or nothing)."""
    try:
        with open(path, "rb") as f:
            return f.read(len(SEG_MAGIC)) == SEG_MAGIC
    except OSError:
        return False


def open_store(path, fsync: bool = True, segment_bytes: int = 0):
    """The layout-sniffing store factory: an existing segmented store
    (or an explicit ``segment_bytes`` request) opens as a
    ``SegmentedStore``; everything else keeps the single-file
    ``ChainStore``.  This is what lets a node config say nothing and
    still reopen whatever layout it shut down with."""
    if segment_bytes > 0 or is_segmented(path):
        return SegmentedStore(
            path,
            fsync=fsync,
            segment_bytes=segment_bytes or DEFAULT_SEGMENT_BYTES,
        )
    return ChainStore(path, fsync=fsync)


class SegmentedStore(ChainStore):
    """A ``ChainStore`` whose log is sharded into bounded segment
    files.  Same API, same per-record framing, same durability
    contract; ``append`` additionally takes a ``height`` hint so the
    manifest can map height spans to segments (what pruning and the
    archive boot consult)."""

    def __init__(
        self,
        path,
        fsync: bool = True,
        segment_bytes: int = DEFAULT_SEGMENT_BYTES,
    ):
        super().__init__(path, fsync=fsync)
        if segment_bytes > _MAX_SEGMENT_BYTES:
            raise ValueError(
                f"segment_bytes {segment_bytes} over the "
                f"{_MAX_SEGMENT_BYTES}-byte span-packing bound"
            )
        self.segment_bytes = max(segment_bytes, 1)
        self.seg_dir = self.path.with_name(self.path.name + ".d")
        self.lock_path = self.path.with_name(self.path.name + ".lock")
        self._segments: list[SegmentInfo] = []
        self._lock_fh = None
        self._active: SegmentInfo | None = None
        #: Current byte size of the active segment (None after a failed
        #: write — same unknown-tail discipline as the base class).
        self._active_size: int | None = None
        self._read_fds: dict[int, int] = {}
        #: Height floor below which body segments were discarded
        #: (``prune_below``); 0 = archive (nothing pruned).
        self.pruned_below = 0
        #: seg_id -> the acquire-time ``StoreScan`` (fsck surface).
        self.segment_scans: dict[int, StoreScan] = {}
        #: Segments whose pread plane returned an I/O error — the node's
        #: serve-only degradation reads this (bodies there are
        #: unavailable until the disk recovers and spans reindex).
        self.read_failed_segments: set[int] = set()
        self.healed.setdefault("lost_segments", 0)
        self.healed.setdefault("hdrx_failures", 0)
        self.healed.setdefault("sdx_failures", 0)
        #: One-shot failure seam (chaos plane, ``seal_sidecar_crash``):
        #: the next seal-time state-delta sidecar write raises OSError.
        #: Exercises the derivable-cache tolerance — the roll must
        #: survive, the counter must tick, the plane must rebuild.
        self.fail_next_sidecar = False
        #: One-shot failure seam (chaos plane, ``online_compact_crash``):
        #: the next ``plan_compaction`` fails mid-tmp-write.  The live
        #: segment files must be untouched afterwards.
        self.fail_next_compact = False

    # -- layout helpers ---------------------------------------------------

    def _seg_path(self, seg: SegmentInfo) -> Path:
        return self.seg_dir / seg.name

    def _seg_by_id(self, seg_id: int) -> SegmentInfo | None:
        for seg in self._segments:
            if seg.seg_id == seg_id:
                return seg
        return None

    def hdrx_path(self, seg: SegmentInfo) -> Path:
        return self.seg_dir / f"seg{seg.seg_id:05d}.hdrx"

    def sdx_path(self, seg: SegmentInfo) -> Path:
        return self.seg_dir / f"seg{seg.seg_id:05d}.sdx"

    @property
    def segments(self) -> tuple[SegmentInfo, ...]:
        return tuple(self._segments)

    # -- manifest ---------------------------------------------------------

    def _parse_manifest(self) -> dict | None:
        """The manifest's payload, or None when missing/corrupt — a
        corrupt manifest is NOT fatal: the segment set rebuilds from
        the directory listing (the manifest is a cache, the segments
        are the data)."""
        return read_manifest(self.path)

    def _write_manifest(self) -> None:
        """Atomically rewrite the manifest: tmp + rename + dir-fsync —
        a crash leaves either the old manifest or the new one, and
        either recovers (stray segments adopt, missing ones rebuild)."""
        payload = json.dumps(
            {
                "version": 1,
                "segment_bytes": self.segment_bytes,
                "pruned_below": self.pruned_below,
                "segments": [s.to_json() for s in self._segments],
            },
            sort_keys=True,
        ).encode("utf-8")
        body = _LEN.pack(len(payload)) + payload
        blob = SEG_MAGIC + body + _CRC.pack(zlib.crc32(body))
        tmp = self.path.with_name(f"{self.path.name}.mf.{os.getpid()}")
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._fsync_dir_path(self.path.parent)

    # -- writer lifecycle -------------------------------------------------

    def acquire(self, allow_v2: bool = False, heal: bool = True) -> None:
        """Lock + open the segmented store (idempotent; see the base
        class for the contract).  Ordering: the stable lock sidecar
        first, then layout recovery (upgrade / manifest rebuild / stray
        adoption), then the per-segment scan+heal — all strictly under
        the lock, exactly as the single-file acquire runs its heal."""
        if self._fh is not None:
            return
        self.path.parent.mkdir(parents=True, exist_ok=True)
        lf = open(self.lock_path, "a+b")
        try:
            fcntl.flock(lf, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError as e:
            lf.close()
            raise RuntimeError(
                f"{self.path} is locked by another process (a running node?)"
            ) from e
        try:
            self._setup_layout(allow_v2=allow_v2)
            if heal:
                self._heal_segments()
            active = [s for s in self._segments if not s.pruned][-1]
            path = self._seg_path(active)
            fh = self._open_fh_path(path)
            try:
                size = path.stat().st_size
            except OSError:
                size = 0
            if size == 0:
                fh.write(MAGIC)
                fh.flush()
                size = len(MAGIC)
            self._fh = fh
            self._active = active
            self._active_size = size
            active.bytes = size
        except ValueError as e:
            lf.close()
            raise RuntimeError(str(e)) from e
        except Exception:
            lf.close()
            raise
        self._lock_fh = lf

    def _setup_layout(self, allow_v2: bool) -> None:
        head = b""
        if self.path.exists() and self.path.stat().st_size > 0:
            with open(self.path, "rb") as f:
                head = f.read(len(SEG_MAGIC))
        if head == V2_MAGIC:
            raise ValueError(
                f"{self.path}: v2 chain store (records carry no "
                "checksums) — run `p1 fsck` or `p1 compact` to "
                "upgrade before segmenting"
            )
        if head and head != SEG_MAGIC:
            if not head.startswith(MAGIC):
                # Unknown/old magic: same message family as the base.
                ChainStore._check_magic(head, str(self.path))
            self._upgrade_single_file()
        manifest = self._parse_manifest()
        self._segments = []
        self.pruned_below = 0
        dirty = manifest is None
        if manifest is not None:
            self.pruned_below = int(manifest.get("pruned_below", 0))
            for row in manifest.get("segments", []):
                try:
                    self._segments.append(SegmentInfo.from_json(row))
                except TypeError:
                    dirty = True
        # Reconcile against the directory — the segments are the data.
        on_disk: set[int] = set()
        self.seg_dir.mkdir(parents=True, exist_ok=True)
        for stale in self.seg_dir.glob("seg*.p1s.cmp.*"):
            # A crashed online compaction leaves tmp replacements; the
            # originals were never touched, so the tmps are pure waste
            # (and can never adopt — the glob below requires ``.p1s``).
            stale.unlink(missing_ok=True)
        for f in sorted(self.seg_dir.glob("seg*.p1s")):
            try:
                on_disk.add(int(f.name[3:8]))
            except ValueError:
                continue
        known = {s.seg_id for s in self._segments}
        for seg_id in sorted(on_disk - known):
            # Stray file: a roll or upgrade crashed between creating the
            # segment and rewriting the manifest.  Adopt it; its height
            # span is unknown (never prunable) until records say more.
            self._segments.append(SegmentInfo(seg_id=seg_id))
            dirty = True
        for seg in list(self._segments):
            if seg.seg_id not in on_disk and not seg.pruned:
                # Manifest names a segment the disk no longer holds —
                # a lying medium or a crashed compaction.  Drop the row
                # (the records are gone; peers re-serve) and count it.
                self._segments.remove(seg)
                self.healed["lost_segments"] += 1
                dirty = True
        self._segments.sort(key=lambda s: s.seg_id)
        # Everything but the last live segment is sealed by definition.
        live = [s for s in self._segments if not s.pruned]
        for seg in live[:-1]:
            if not seg.sealed:
                seg.sealed = True
                dirty = True
        if not live:
            next_id = (
                self._segments[-1].seg_id + 1 if self._segments else 0
            )
            seg = SegmentInfo(seg_id=next_id)
            fh = self._open_fh_path(self._seg_path(seg))
            fh.write(MAGIC)
            fh.flush()
            self._fsync_file(fh)
            fh.close()
            self._fsync_dir_path(self.seg_dir)
            self._segments.append(seg)
            dirty = True
        if dirty:
            self._write_manifest()

    def _upgrade_single_file(self) -> None:
        """Lossless single-file v3 → segmented conversion, under BOTH
        locks (the sidecar is already held; the old file's own flock
        excludes a legacy writer).  The record bytes are hard-linked
        into place — same inode, zero copies — so the upgrade cannot
        lose or alter a byte; the round-trip digest test pins it."""
        old = open(self.path, "r+b")
        try:
            try:
                fcntl.flock(old, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError as e:
                raise RuntimeError(
                    f"{self.path} is locked by another process "
                    "(a running node?)"
                ) from e
            self.seg_dir.mkdir(parents=True, exist_ok=True)
            for stale in self.seg_dir.iterdir():
                # A crashed validation-flip rewrite (node/_rewrite_store
                # replaces the manifest with a fresh single-file store)
                # leaves the previous layout's segments behind: clear
                # them BEFORE linking, or they would adopt as live data.
                stale.unlink()
            seg0 = self.seg_dir / "seg00000.p1s"
            os.link(self.path, seg0)
            self._fsync_dir_path(self.seg_dir)
            self._segments = [SegmentInfo(seg_id=0)]
            self.pruned_below = 0
            self._write_manifest()
        finally:
            old.close()

    def _heal_segments(self) -> None:
        """The round-7 scan+heal, per segment: torn tails truncate,
        mid-segment corruption quarantines to the SEGMENT's sidecar and
        rebuilds only that file — every other segment's bytes are
        untouched (containment is the whole point)."""
        self.segment_scans = {}
        for seg in self._segments:
            if seg.pruned:
                continue
            path = self._seg_path(seg)
            for attempt in (0, 1):
                data = self._read_bytes_path(path)
                if not data or _torn_magic(data):
                    if data:  # torn first write: reset to empty
                        os.truncate(path, 0)
                    scan = StoreScan(3, [], [], None, 0)
                    break
                if not data.startswith(MAGIC):
                    raise ValueError(
                        f"{path}: segment is not a v3 chain store"
                    )
                scan = ChainStore.scan(data)
                if not scan.bad_spans:
                    break
                if attempt == 1:
                    raise ValueError(
                        f"{path}: {len(scan.bad_spans)} corrupt span(s) "
                        "persist after heal — refusing writer; run `p1 fsck`"
                    )
                self._heal_segment(path, data, scan)
            if scan.torn_tail is not None:
                self.healed["truncated_bytes"] += len(data) - scan.torn_tail
                os.truncate(path, scan.torn_tail)
                scan = dataclasses.replace(
                    scan, torn_tail=None, size=scan.torn_tail
                )
            self.segment_scans[seg.seg_id] = scan
            seg.records = len(scan.spans)
            seg.bytes = scan.size
            self.last_scan = scan

    def _heal_segment(self, path: Path, data: bytes, scan: StoreScan) -> None:
        """Quarantine + rebuild ONE segment (sidecar first, durably;
        then tmp + rename + dir-fsync — the base class's discipline,
        scoped to this file)."""
        qpath = path.with_name(path.name + ".quarantine")
        with open(qpath, "ab") as qf:
            for s, e in scan.bad_spans:
                qf.write(struct.pack(">QI", s, e - s))
                qf.write(data[s:e])
            qf.flush()
            os.fsync(qf.fileno())
        tmp = path.with_name(f"{path.name}.heal.{os.getpid()}")
        with open(tmp, "wb") as out:
            out.write(MAGIC)
            for off, n in scan.spans:
                out.write(data[off - _LEN.size : off + n + _CRC.size])
            out.flush()
            os.fsync(out.fileno())
        os.replace(tmp, path)
        self._fsync_dir_path(self.seg_dir)
        self.healed["quarantined_records"] += len(scan.bad_spans)
        self.healed["quarantined_bytes"] += scan.quarantined_bytes

    def quarantine_path(self) -> Path:
        """The ACTIVE segment's quarantine sidecar (single-file callers
        use this for evidence paths; per-segment sidecars sit next to
        their segment)."""
        if self._active is not None:
            p = self._seg_path(self._active)
            return p.with_name(p.name + ".quarantine")
        return super().quarantine_path()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        self._active = None
        self._active_size = None
        self._append_off = None
        with self._fd_lock:
            for fd in self._read_fds.values():
                os.close(fd)
            self._read_fds.clear()
            if self._read_fd is not None:
                os.close(self._read_fd)
                self._read_fd = None
        if self._lock_fh is not None:
            self._lock_fh.close()
            self._lock_fh = None

    # -- appends + rolls --------------------------------------------------

    def append(self, block, height: int | None = None) -> None:
        self.append_raw(
            block.serialize(), height=height, block_hash=block.block_hash()
        )

    def append_raw(
        self,
        raw: bytes,
        height: int | None = None,
        block_hash: bytes | None = None,
    ) -> None:
        """Append one pre-serialized record (the bulk-ingest /
        synthetic-archive path: benchmarks craft record bytes directly
        and skip the object layer entirely).  ``block_hash`` registers
        the body span when given; without it the record is simply not
        refetchable until the next reindex."""
        self.acquire()
        if len(raw) > _MAX_RECORD:
            raise ValueError(
                f"block serializes to {len(raw)} bytes, over the "
                f"{_MAX_RECORD}-byte record limit"
            )
        rec_len = _LEN.size + len(raw) + _CRC.size
        if (
            self._active.records > 0
            and self._active_size is not None
            and self._active_size + rec_len > self.segment_bytes
        ):
            self._roll()
        prefix = _LEN.pack(len(raw))
        crc = zlib.crc32(raw, zlib.crc32(prefix))
        try:
            self._fh.write(prefix + raw + _CRC.pack(crc))
            self._fh.flush()
        except OSError:
            # Unknown tail: stop registering spans until re-acquire —
            # the base class's post-incident discipline, per segment.
            self._active_size = None
            raise
        if self._active_size is not None:
            if block_hash is not None:
                self._body_spans[block_hash] = (
                    (self._active.seg_id << _SEG_SHIFT)
                    | ((self._active_size + _LEN.size) << _SPAN_SHIFT)
                    | len(raw)
                )
            self._active_size += rec_len
            self._active.bytes = self._active_size
        self._active.records += 1
        if height is not None:
            if self._active.min_height is None or height < self._active.min_height:
                self._active.min_height = height
            if self._active.max_height is None or height > self._active.max_height:
                self._active.max_height = height
        if self.fsync:
            self._fsync_file(self._fh)

    def _roll(self) -> None:
        """Seal the active segment and open the next one.  Ordering per
        the module docstring: sealed bytes durable → header-plane
        sidecar → new segment's magic durable → directory → manifest.
        A crash between ANY two steps recovers at the next acquire
        (stray adoption / manifest rebuild); an OSError mid-roll leaves
        the OLD segment active so the caller's degradation path
        (node ``_store_fail``) sees one coherent store."""
        self._fh.flush()
        self._fsync_file(self._fh)
        active = self._active
        seg_bytes = None
        try:
            seg_bytes = self._read_bytes_path(self._seg_path(active))
        except OSError:
            # Neither sidecar can derive without the bytes; both planes
            # rebuild later (prune_below / ensure_sidecars re-derive).
            self.healed["hdrx_failures"] += 1
            self.healed["sdx_failures"] += 1
        if seg_bytes is not None:
            try:
                from p1_tpu.chain import headerplane

                headerplane.write_segment_index(
                    seg_bytes, self.hdrx_path(active)
                )
            except OSError:
                # The plane is derivable from the segment: losing the
                # sidecar costs a rebuild, never data.
                self.healed["hdrx_failures"] += 1
            try:
                from p1_tpu.chain import statedelta

                if self.fail_next_sidecar:
                    self.fail_next_sidecar = False
                    raise OSError("injected sidecar failure (chaos seam)")
                statedelta.write_segment_delta(
                    seg_bytes, self.sdx_path(active)
                )
            except OSError:
                # Same derivable-cache tolerance as the header plane:
                # the delta recomputes from the segment on demand.
                self.healed["sdx_failures"] += 1
        new = SegmentInfo(seg_id=active.seg_id + 1)
        path = self._seg_path(new)
        fh = self._open_fh_path(path)
        try:
            if path.stat().st_size == 0:
                fh.write(MAGIC)
                fh.flush()
            self._fsync_file(fh)
            self._fsync_dir_path(self.seg_dir)
            active.sealed = True
            self._segments.append(new)
            self._write_manifest()
        except OSError:
            fh.close()
            if self._segments and self._segments[-1] is new:
                self._segments.remove(new)
            active.sealed = False
            raise
        old = self._fh
        self._fh = fh
        old.close()
        self._active = new
        self._active_size = len(MAGIC)
        new.bytes = len(MAGIC)

    def roll_segment(self) -> None:
        """Force a segment roll (chaos events and tests; production
        rolls happen at the size bound)."""
        self.acquire()
        if self._active.records > 0:
            self._roll()

    # -- readers ----------------------------------------------------------

    def _live_segments(self) -> list[SegmentInfo]:
        return [s for s in self._segments_for_read() if not s.pruned]

    def _segments_for_read(self) -> list[SegmentInfo]:
        """Reader-side segment set: the acquired writer's in-memory
        list, or a fresh manifest parse for lock-free readers (tooling
        attach before acquire)."""
        if self._segments:
            return self._segments
        manifest = self._parse_manifest()
        if manifest is None:
            return []
        return [
            SegmentInfo.from_json(row)
            for row in manifest.get("segments", [])
        ]

    def iter_blocks(self):
        from p1_tpu.core.block import Block

        for seg in self._live_segments():
            path = self._seg_path(seg)
            try:
                data = self._read_bytes_path(path)
            except FileNotFoundError:
                continue
            if not data or _torn_magic(data):
                continue
            if not data.startswith(MAGIC):
                raise ValueError(f"{path}: segment is not a v3 chain store")
            spans = ChainStore.scan(data).spans
            del data
            fd = self._seg_fd(seg.seg_id)
            for off, n in spans:
                raw = self._pread(fd, n, off)
                if len(raw) != n:
                    raise OSError(f"{path}: short record read at {off}")
                block = Block.deserialize(raw)
                self._body_spans[block.block_hash()] = (
                    (seg.seg_id << _SEG_SHIFT) | (off << _SPAN_SHIFT) | n
                )
                yield block

    def load_blocks(self):
        return list(self.iter_blocks())

    def first_header(self):
        from p1_tpu.core.header import HEADER_SIZE, BlockHeader

        for seg in self._segments_for_read():
            if seg.pruned:
                # Pruned bodies keep their packed-header sidecar: the
                # chain's first header is still knowable.
                from p1_tpu.chain import headerplane

                try:
                    idx = headerplane.SegmentIndex(self.hdrx_path(seg))
                except (OSError, ValueError):
                    continue
                if idx.count:
                    return BlockHeader.deserialize(idx.header_at(0))
                continue
            try:
                data = self._read_bytes_path(self._seg_path(seg))
            except FileNotFoundError:
                continue
            if not data.startswith(MAGIC):
                continue
            for off, _ in ChainStore.scan(data).spans:
                return BlockHeader.deserialize(data[off : off + HEADER_SIZE])
        return None

    def packed_headers(self) -> tuple[bytes, int]:
        from p1_tpu.core.header import HEADER_SIZE

        parts: list[bytes] = []
        count = 0
        for seg in self._segments_for_read():
            if seg.pruned:
                from p1_tpu.chain import headerplane

                idx = headerplane.SegmentIndex(self.hdrx_path(seg))
                parts.append(idx.headers_blob())
                count += idx.count
                continue
            try:
                data = self._read_bytes_path(self._seg_path(seg))
            except FileNotFoundError:
                continue
            if not data.startswith(MAGIC):
                continue
            for off, _ in ChainStore.scan(data).spans:
                parts.append(data[off : off + HEADER_SIZE])
                count += 1
        return b"".join(parts), count

    def reindex_spans(self) -> int:
        from p1_tpu.core.hashutil import sha256d
        from p1_tpu.core.header import HEADER_SIZE

        self._body_spans.clear()
        self.read_failed_segments.clear()
        with self._fd_lock:
            for fd in self._read_fds.values():
                os.close(fd)
            self._read_fds.clear()
        for seg in self._live_segments():
            try:
                data = self._read_bytes_path(self._seg_path(seg))
            except FileNotFoundError:
                continue
            if not data.startswith(MAGIC):
                continue
            for off, n in ChainStore.scan(data).spans:
                bhash = sha256d(data[off : off + HEADER_SIZE])
                self._body_spans[bhash] = (
                    (seg.seg_id << _SEG_SHIFT) | (off << _SPAN_SHIFT) | n
                )
        return len(self._body_spans)

    # -- body refetch ------------------------------------------------------

    def _seg_fd(self, seg_id: int) -> int:
        # Callers hold ``self._fd_lock`` (read-fd lifecycle guard for the
        # staged node — the eviction close below must not land under a
        # concurrent pread on the victim's fd).
        fd = self._read_fds.get(seg_id)
        if fd is None:
            seg = self._seg_by_id(seg_id)
            name = seg.name if seg else f"seg{seg_id:05d}.p1s"
            fd = os.open(self.seg_dir / name, os.O_RDONLY)
            if len(self._read_fds) >= _MAX_READ_FDS:
                victim = next(iter(self._read_fds))
                os.close(self._read_fds.pop(victim))
            self._read_fds[seg_id] = fd
        return fd

    def read_body(self, block_hash: bytes):
        from p1_tpu.core.block import Block

        span = self._body_spans[block_hash]
        seg_id = span >> _SEG_SHIFT
        off = (span >> _SPAN_SHIFT) & ((1 << (_SEG_SHIFT - _SPAN_SHIFT)) - 1)
        n = span & ((1 << _SPAN_SHIFT) - 1)
        try:
            with self._fd_lock:
                raw = self._pread(self._seg_fd(seg_id), n, off)
            if len(raw) != n:
                raise OSError(
                    f"{self.seg_dir}/seg{seg_id:05d}: short body read at {off}"
                )
        except OSError:
            # The segment's medium failed under us: drop its read fd
            # and remember — the node degrades to serve-only and the
            # recovery loop re-probes (bodies in OTHER segments keep
            # serving throughout).
            self.read_failed_segments.add(seg_id)
            with self._fd_lock:
                fd = self._read_fds.pop(seg_id, None)
                if fd is not None:
                    os.close(fd)
            raise
        block = Block.deserialize(raw)
        if block.block_hash() != block_hash:
            raise ValueError(
                f"{self.seg_dir}: body span for {block_hash.hex()[:16]} "
                "re-read as a different block"
            )
        return block

    # -- pruning -----------------------------------------------------------

    def prunable_segments(self, floor: int) -> list[SegmentInfo]:
        """Sealed, un-pruned segments whose every record sits strictly
        below ``floor`` — the discardable set.  Unknown height spans
        never qualify."""
        return [
            s
            for s in self._segments
            if s.sealed
            and not s.pruned
            and s.max_height is not None
            and s.max_height < floor
        ]

    def prune_below(self, floor: int) -> int:
        """Discard body segments wholly below height ``floor`` (the
        caller aligns ``floor`` to its snapshot base — bodies below it
        are re-derivable from any archive peer, and headers survive in
        the ``.hdrx`` plane, which is (re)written before the unlink so
        the header chain never has a hole).  Returns segments removed.
        Manifest updated last: a crash mid-prune leaves missing files
        the next acquire reconciles (``lost_segments`` stays 0 for
        rows already marked pruned)."""
        self.acquire()
        victims = self.prunable_segments(floor)
        if not victims:
            return 0
        from p1_tpu.chain import headerplane

        for seg in victims:
            hx = self.hdrx_path(seg)
            if not hx.exists():
                headerplane.write_segment_index(
                    self._read_bytes_path(self._seg_path(seg)), hx
                )
            sx = self.sdx_path(seg)
            if not sx.exists():
                # The state-delta sidecar is the only record of what
                # the discarded bodies did to the ledger — write it
                # before the unlink, tolerating failure (the prunebase
                # snapshot carries the state either way).
                try:
                    from p1_tpu.chain import statedelta

                    statedelta.write_segment_delta(
                        self._read_bytes_path(self._seg_path(seg)), sx
                    )
                except OSError:
                    self.healed["sdx_failures"] += 1
            os.unlink(self._seg_path(seg))
            seg.pruned = True
            with self._fd_lock:
                fd = self._read_fds.pop(seg.seg_id, None)
                if fd is not None:
                    os.close(fd)
            self.pruned_below = max(self.pruned_below, seg.max_height + 1)
        self._fsync_dir_path(self.seg_dir)
        self._write_manifest()
        pruned_ids = {s.seg_id for s in self._segments if s.pruned}
        self._body_spans = {
            h: sp
            for h, sp in self._body_spans.items()
            if (sp >> _SEG_SHIFT) not in pruned_ids
        }
        return len(victims)

    # -- always-on maintenance (round 20) ---------------------------------

    def ensure_sidecars(self) -> int:
        """Write any missing ``.hdrx``/``.sdx`` sidecars for sealed,
        un-pruned segments — the live re-base's spill step: before the
        chain drops its in-RAM header index below the new base, every
        sealed segment must carry its packed-header plane so the
        history stays servable/bootable from disk.  Returns sidecars
        written.  A header-plane failure RAISES (the caller's re-base
        depends on the plane existing and must abort cleanly); a
        state-delta failure is tolerated (``sdx_failures``) like
        everywhere else — it is an optimization cache, not the spill.
        """
        self.acquire()
        written = 0
        from p1_tpu.chain import headerplane, statedelta

        for seg in self._segments:
            if not seg.sealed or seg.pruned:
                continue
            data = None
            hx = self.hdrx_path(seg)
            if not hx.exists():
                data = self._read_bytes_path(self._seg_path(seg))
                headerplane.write_segment_index(data, hx)
                written += 1
            sx = self.sdx_path(seg)
            if not sx.exists():
                try:
                    if data is None:
                        data = self._read_bytes_path(self._seg_path(seg))
                    statedelta.write_segment_delta(data, sx)
                    written += 1
                except OSError:
                    self.healed["sdx_failures"] += 1
        return written

    def plan_compaction(self, drop: set[bytes]) -> list[dict]:
        """Off-loop half of ONLINE compaction (the node runs this on
        its store lane): for every sealed, un-pruned segment holding at
        least one record whose block hash is in ``drop``, build a
        compacted replacement under a tmp name — MAGIC + surviving
        frames, fsync'd, then self-checked with a fresh scan (a
        replacement that cannot prove itself byte-perfect is discarded
        and the original left untouched: OSError).  Returns one plan
        row per dirty segment for ``commit_compacted_segment``; the
        LIVE segment files are never touched here, so a crash or
        failure at any point inside this method costs only stray tmp
        files (reaped at the next acquire).

        ``drop`` must only ever name records the caller POSITIVELY
        knows are off the main chain — unknown hashes are kept, so
        compaction can never widen a prune's loss (chain/tooling.py's
        rule, enforced the same way: keep is the default)."""
        from p1_tpu.core.hashutil import sha256d
        from p1_tpu.core.header import HEADER_SIZE

        plans: list[dict] = []
        tmp: Path | None = None
        try:
            for seg in self._segments:
                if not seg.sealed or seg.pruned:
                    continue
                tmp = None
                path = self._seg_path(seg)
                data = self._read_bytes_path(path)
                if not data.startswith(MAGIC):
                    continue
                scan = ChainStore.scan(data)
                frames: list[bytes] = []
                spans: list[tuple[bytes, int, int]] = []
                pos = len(MAGIC)
                for off, n in scan.spans:
                    bhash = sha256d(data[off : off + HEADER_SIZE])
                    if bhash in drop:
                        continue
                    frames.append(
                        data[off - _LEN.size : off + n + _CRC.size]
                    )
                    spans.append((bhash, pos + _LEN.size, n))
                    pos += _LEN.size + n + _CRC.size
                if len(frames) == len(scan.spans):
                    continue  # clean segment: nothing to drop
                tmp = path.with_name(f"{path.name}.cmp.{os.getpid()}")
                if self.fail_next_compact:
                    self.fail_next_compact = False
                    # Fail AFTER a partial tmp lands — the worst-case
                    # interruption point the chaos plane exercises.
                    tmp.write_bytes(MAGIC + (frames[0] if frames else b""))
                    raise OSError("injected compaction failure (chaos seam)")
                with open(tmp, "wb") as out:
                    out.write(MAGIC)
                    for frame in frames:
                        out.write(frame)
                    out.flush()
                    os.fsync(out.fileno())
                vscan = ChainStore.scan(self._read_bytes_path(tmp))
                if not vscan.clean or len(vscan.spans) != len(frames):
                    raise OSError(
                        f"{tmp}: compacted segment fails self-check"
                    )
                plans.append(
                    {
                        "seg_id": seg.seg_id,
                        "tmp": str(tmp),
                        "records": len(frames),
                        "bytes": len(MAGIC)
                        + sum(len(f) for f in frames),
                        "spans": spans,
                        "dropped": len(scan.spans) - len(frames),
                        # Staleness pin for the commit half: the exact
                        # size this plan was derived from.
                        "orig_bytes": len(data),
                    }
                )
        except OSError:
            # Live failure: drop every replacement built so far,
            # including the one mid-write.  (A kill-9 leaves them
            # instead — the next acquire reaps stray ``.cmp.`` tmps.)
            if tmp is not None:
                tmp.unlink(missing_ok=True)
            self.discard_compaction(plans)
            raise
        return plans

    def commit_compacted_segment(self, plan: dict) -> int:
        """On-loop half: atomically swap ONE compacted segment into
        place and fix every in-RAM structure that referenced the old
        inode — the span map entries for this segment and its cached
        read fd — in one synchronous step.  The caller (node) runs
        this between awaits, so no reader can interleave between the
        replace and the span fixup; until then, readers holding the
        old cached fd kept reading the old (still-live) inode at the
        old offsets, which is consistent by construction.  Returns
        records dropped."""
        seg = self._seg_by_id(plan["seg_id"])
        if seg is None or seg.pruned:
            Path(plan["tmp"]).unlink(missing_ok=True)
            return 0
        path = self._seg_path(seg)
        try:
            current = path.stat().st_size
        except OSError:
            current = -1
        if not seg.sealed or current != plan["orig_bytes"]:
            # The segment changed since the plan was derived (a roll
            # raced the off-loop planner, or a failed roll re-activated
            # it).  Replacing now would lose records — skip; the next
            # compaction re-plans from current bytes.
            Path(plan["tmp"]).unlink(missing_ok=True)
            return 0
        os.replace(plan["tmp"], path)
        self._fsync_dir_path(self.seg_dir)
        with self._fd_lock:
            fd = self._read_fds.pop(seg.seg_id, None)
            if fd is not None:
                os.close(fd)
        sid = seg.seg_id
        self._body_spans = {
            h: sp
            for h, sp in self._body_spans.items()
            if (sp >> _SEG_SHIFT) != sid
        }
        for bhash, off, n in plan["spans"]:
            self._body_spans[bhash] = (
                (sid << _SEG_SHIFT) | (off << _SPAN_SHIFT) | n
            )
        seg.records = plan["records"]
        seg.bytes = plan["bytes"]
        return plan["dropped"]

    def flush_manifest(self) -> None:
        """Persist the in-RAM segment rows.  Appends and prunes write
        the manifest themselves; a compaction COMMIT changes a sealed
        segment's records/bytes without either, so the node calls this
        (off-loop, with the sidecar refresh) once a commit batch lands.
        Crash before it: the manifest's stale row sizes are healed by
        the next acquire's scan, costing an fsck repair, never data."""
        self._write_manifest()

    def discard_compaction(self, plans: list[dict]) -> None:
        """Abort path: drop any tmp replacements already built.  The
        live segments were never touched."""
        for plan in plans:
            Path(plan["tmp"]).unlink(missing_ok=True)

    def refresh_sidecars(self, seg_ids: list[int]) -> None:
        """Rewrite the ``.hdrx``/``.sdx`` sidecars for segments whose
        bytes just changed (post-compaction, on the store lane).
        Failures are tolerated and counted — both planes are derivable
        caches."""
        from p1_tpu.chain import headerplane, statedelta

        for seg_id in seg_ids:
            seg = self._seg_by_id(seg_id)
            if seg is None or seg.pruned:
                continue
            try:
                data = self._read_bytes_path(self._seg_path(seg))
            except OSError:
                self.healed["hdrx_failures"] += 1
                self.healed["sdx_failures"] += 1
                continue
            try:
                headerplane.write_segment_index(data, self.hdrx_path(seg))
            except OSError:
                self.healed["hdrx_failures"] += 1
            try:
                statedelta.write_segment_delta(data, self.sdx_path(seg))
            except OSError:
                self.healed["sdx_failures"] += 1

    # -- fsck surface ------------------------------------------------------

    def scan_segments(self) -> list[tuple[SegmentInfo, StoreScan | None]]:
        """Read-only per-segment framing verdicts (``p1 fsck``'s report
        pass): (info, scan) per segment, scan None for pruned bodies.
        Raises nothing — an unreadable or mis-tagged segment reports as
        a scan whose spans are empty and whose whole extent is one bad
        span (unrecoverable-at-segment-level, contained there)."""
        out: list[tuple[SegmentInfo, StoreScan | None]] = []
        for seg in self._segments_for_read():
            if seg.pruned:
                out.append((seg, None))
                continue
            path = self._seg_path(seg)
            try:
                data = self._read_bytes_path(path)
            except OSError:
                out.append((seg, StoreScan(3, [], [(0, 0)], None, 0)))
                continue
            if not data.startswith(MAGIC):
                out.append(
                    (seg, StoreScan(3, [], [(0, len(data))], None, len(data)))
                )
                continue
            out.append((seg, ChainStore.scan(data)))
        return out
