"""SPV transaction-inclusion proofs (light-client verification).

Capability parity: a "Bitcoin-like toy cryptocurrency" (BASELINE.json:5)
whose wallets already query balance/nonce over the wire (GETACCOUNT) also
owes them the other classic light-client primitive: *prove that my
transaction is confirmed* without downloading blocks.  A ``TxProof`` is the
standard SPV bundle — the transaction, its block header, and the merkle
sibling path — verified client-side with three checks that need no chain
state at all:

1. the header carries real proof-of-work at the chain's difficulty,
2. the merkle branch links the txid to that header's commitment, and
3. the transaction itself is well-formed for this chain (Ed25519 ownership
   proof, chain-bound signature, coinbase subsidy rules).

Honesty about the trust model (documented, not hidden): this is
*one-header* SPV.  The proof pins the transaction to **a** valid
proof-of-work block, but whether that block is on the current best chain is
attested only by the serving peer (``tip_height`` → ``confirmations`` is
the peer's claim).  Lying costs the attacker a real block's worth of work —
the same bar Bitcoin SPV sets per header — and a client that wants more can
cross-check several peers or replay the full header chain with
``p1_tpu.chain.replay`` (the header-chain verifier a full light client
would run; ``replay_host`` takes the chain's ``RetargetRule`` and
recomputes the contextual difficulty schedule, so it works on retargeting
chains too — ``p1 replay --method host``).  The serving side computes proofs from a txid index maintained
at the tip (``Chain.tx_proof``), so queries are O(block size), not
O(chain).
"""

from __future__ import annotations

import dataclasses

from p1_tpu.core.block import verify_merkle_branch
from p1_tpu.core.header import BlockHeader, meets_target
from p1_tpu.core.tx import BLOCK_REWARD, Transaction


class SPVError(Exception):
    """A transaction-inclusion proof failed verification."""


@dataclasses.dataclass(frozen=True)
class TxProof:
    """Everything a light client needs to check one confirmed transaction."""

    tx: Transaction
    header: BlockHeader  # the block that confirmed it
    height: int  # that block's main-chain height (server's view)
    tip_height: int  # server's tip height when the proof was cut
    index: int  # tx position in the block
    branch: tuple[bytes, ...]  # merkle sibling path, leaf-to-root

    @property
    def confirmations(self) -> int:
        return self.tip_height - self.height + 1


def verify_tx_proof(
    proof: TxProof,
    difficulty: int,
    chain_tag: bytes,
    txid: bytes | None = None,
    retarget=None,
) -> None:
    """Raise ``SPVError`` unless ``proof`` checks out for the chain whose
    base difficulty, genesis hash (``chain_tag``) and optional
    ``RetargetRule`` are given.

    Pure function of its arguments — this is the *client* side, run by
    wallets that hold no chain.  ``txid`` pins the proof to the transaction
    the caller asked about (a peer answering with a different, valid proof
    must not pass).

    Work-bar honesty on retargeting chains: the difficulty consensus
    required at the proof's height is contextual (a function of the whole
    ancestor chain — chain/chain.py), which a stateless verifier cannot
    recompute.  So with ``retarget`` set, the check is proof-of-work at
    the header's claimed difficulty, **floored by what the rule could
    legitimately have reached by the claimed height**: difficulty moves
    at most ``max_adjust`` bits per completed window, so a proof at
    height h may claim no less than ``base - max_adjust * (h // window)``
    bits.  Be clear about what that buys: ``height`` and ``tip_height``
    are themselves peer claims, so a forger willing to claim a height of
    ``~window * (base-1) / max_adjust`` blocks (where the floor decays to
    1) still gets ~2-hash evidence past this check, with a plausible
    confirmation count — the floor only forces the lie into the height
    field, it cannot price it.  Stateless one-header SPV fundamentally
    cannot do better on a retargeting chain; clients that need the real
    bar MUST anchor against a locally verified header chain (``p1 proof
    --headers``), which checks the claimed height against real blocks and
    recomputes confirmations locally.  Fixed-difficulty chains (every
    benchmark config) keep the strict equality check.
    """
    header = proof.header
    have_txid = proof.tx.txid()
    if txid is not None and have_txid != txid:
        raise SPVError("proof is for a different transaction")
    if proof.tip_height < proof.height:
        # Both are peer-claimed u32s; a tip below the confirming height is
        # internally inconsistent evidence (and would print negative
        # confirmations to wallet scripts).
        raise SPVError(
            f"tip height {proof.tip_height} below confirming height "
            f"{proof.height}"
        )
    if retarget is None:
        if header.difficulty != difficulty:
            raise SPVError(
                f"header difficulty {header.difficulty} != chain "
                f"difficulty {difficulty}"
            )
    else:
        # The schedule floor: per-window drift is clamped to max_adjust
        # bits, so 2-hash evidence (difficulty 1) requires claiming
        # enough elapsed windows to have legitimately drifted that far.
        floor = max(
            1,
            difficulty
            - retarget.max_adjust * (proof.height // retarget.window),
        )
        if header.difficulty < floor:
            raise SPVError(
                f"claimed difficulty {header.difficulty} below the "
                f"schedule floor {floor} for height {proof.height} "
                f"(base {difficulty}, ≤{retarget.max_adjust} bits per "
                f"{retarget.window}-block window)"
            )
    if proof.height == 0:
        # Genesis anchors by identity, not work (core/genesis.py) — the
        # only height-0 header a client accepts is the chain tag itself.
        if header.block_hash() != chain_tag:
            raise SPVError("height-0 header is not this chain's genesis")
    elif not meets_target(header.block_hash(), header.difficulty):
        raise SPVError("header does not meet proof-of-work target")
    if not verify_merkle_branch(
        have_txid, proof.index, proof.branch, header.merkle_root
    ):
        raise SPVError("merkle branch does not link txid to header")
    tx = proof.tx
    if tx.is_coinbase:
        # Mirror consensus' stateless coinbase rules (chain/validate.py):
        # first position, exact subsidy, unsigned.
        if proof.index != 0:
            raise SPVError("coinbase proven at index > 0")
        if tx.amount != BLOCK_REWARD:
            raise SPVError(f"coinbase mints {tx.amount}, subsidy is {BLOCK_REWARD}")
        if not tx.verify_signature():
            raise SPVError("coinbase must be unsigned")
    else:
        if tx.chain != chain_tag:
            raise SPVError("transaction signed for a different chain")
        if not tx.verify_signature():
            raise SPVError("bad transaction signature")
