"""SPV transaction-inclusion proofs (light-client verification).

Capability parity: a "Bitcoin-like toy cryptocurrency" (BASELINE.json:5)
whose wallets already query balance/nonce over the wire (GETACCOUNT) also
owes them the other classic light-client primitive: *prove that my
transaction is confirmed* without downloading blocks.  A ``TxProof`` is the
standard SPV bundle — the transaction, its block header, and the merkle
sibling path — verified client-side with three checks that need no chain
state at all:

1. the header carries real proof-of-work at the chain's difficulty,
2. the merkle branch links the txid to that header's commitment, and
3. the transaction itself is well-formed for this chain (Ed25519 ownership
   proof, chain-bound signature, coinbase subsidy rules).

Honesty about the trust model (documented, not hidden): this is
*one-header* SPV.  The proof pins the transaction to **a** valid
proof-of-work block, but whether that block is on the current best chain is
attested only by the serving peer (``tip_height`` → ``confirmations`` is
the peer's claim).  Lying costs the attacker a real block's worth of work —
the same bar Bitcoin SPV sets per header — and a client that wants more can
cross-check several peers or replay the full header chain with
``p1_tpu.chain.replay`` (the header-chain verifier a full light client
would run; ``replay_host`` takes the chain's ``RetargetRule`` and
recomputes the contextual difficulty schedule, so it works on retargeting
chains too — ``p1 replay --method host``).  The serving side computes proofs from a txid index maintained
at the tip (``Chain.tx_proof``), so queries are O(block size), not
O(chain).
"""

from __future__ import annotations

import collections
import dataclasses

from p1_tpu.core.block import verify_merkle_branch
from p1_tpu.core.header import BlockHeader, meets_target
from p1_tpu.core.tx import BLOCK_REWARD, Transaction


class SPVError(Exception):
    """A transaction-inclusion proof failed verification."""


@dataclasses.dataclass(frozen=True)
class TxProof:
    """Everything a light client needs to check one confirmed transaction."""

    tx: Transaction
    header: BlockHeader  # the block that confirmed it
    height: int  # that block's main-chain height (server's view)
    tip_height: int  # server's tip height when the proof was cut
    index: int  # tx position in the block
    branch: tuple[bytes, ...]  # merkle sibling path, leaf-to-root

    @property
    def confirmations(self) -> int:
        return self.tip_height - self.height + 1


def verify_tx_proof(
    proof: TxProof,
    difficulty: int,
    chain_tag: bytes,
    txid: bytes | None = None,
    retarget=None,
) -> None:
    """Raise ``SPVError`` unless ``proof`` checks out for the chain whose
    base difficulty, genesis hash (``chain_tag``) and optional
    ``RetargetRule`` are given.

    Pure function of its arguments — this is the *client* side, run by
    wallets that hold no chain.  ``txid`` pins the proof to the transaction
    the caller asked about (a peer answering with a different, valid proof
    must not pass).

    Work-bar honesty on retargeting chains: the difficulty consensus
    required at the proof's height is contextual (a function of the whole
    ancestor chain — chain/chain.py), which a stateless verifier cannot
    recompute.  So with ``retarget`` set, the check is proof-of-work at
    the header's claimed difficulty, **floored by what the rule could
    legitimately have reached by the claimed height**: difficulty moves
    at most ``max_adjust`` bits per completed window, so a proof at
    height h may claim no less than ``base - max_adjust * (h // window)``
    bits.  Be clear about what that buys: ``height`` and ``tip_height``
    are themselves peer claims, so a forger willing to claim a height of
    ``~window * (base-1) / max_adjust`` blocks (where the floor decays to
    1) still gets ~2-hash evidence past this check, with a plausible
    confirmation count — the floor only forces the lie into the height
    field, it cannot price it.  Stateless one-header SPV fundamentally
    cannot do better on a retargeting chain; clients that need the real
    bar MUST anchor against a locally verified header chain (``p1 proof
    --headers``), which checks the claimed height against real blocks and
    recomputes confirmations locally.  Fixed-difficulty chains (every
    benchmark config) keep the strict equality check.
    """
    header = proof.header
    have_txid = proof.tx.txid()
    if txid is not None and have_txid != txid:
        raise SPVError("proof is for a different transaction")
    if proof.tip_height < proof.height:
        # Both are peer-claimed u32s; a tip below the confirming height is
        # internally inconsistent evidence (and would print negative
        # confirmations to wallet scripts).
        raise SPVError(
            f"tip height {proof.tip_height} below confirming height "
            f"{proof.height}"
        )
    if retarget is None:
        if header.difficulty != difficulty:
            raise SPVError(
                f"header difficulty {header.difficulty} != chain "
                f"difficulty {difficulty}"
            )
    else:
        # The schedule floor: per-window drift is clamped to max_adjust
        # bits, so 2-hash evidence (difficulty 1) requires claiming
        # enough elapsed windows to have legitimately drifted that far.
        floor = max(
            1,
            difficulty
            - retarget.max_adjust * (proof.height // retarget.window),
        )
        if header.difficulty < floor:
            raise SPVError(
                f"claimed difficulty {header.difficulty} below the "
                f"schedule floor {floor} for height {proof.height} "
                f"(base {difficulty}, ≤{retarget.max_adjust} bits per "
                f"{retarget.window}-block window)"
            )
    if proof.height == 0:
        # Genesis anchors by identity, not work (core/genesis.py) — the
        # only height-0 header a client accepts is the chain tag itself.
        if header.block_hash() != chain_tag:
            raise SPVError("height-0 header is not this chain's genesis")
    elif not meets_target(header.block_hash(), header.difficulty):
        raise SPVError("header does not meet proof-of-work target")
    if not verify_merkle_branch(
        have_txid, proof.index, proof.branch, header.merkle_root
    ):
        raise SPVError("merkle branch does not link txid to header")
    tx = proof.tx
    if tx.is_coinbase:
        # Mirror consensus' stateless coinbase rules (chain/validate.py):
        # first position, exact subsidy, unsigned.
        if proof.index != 0:
            raise SPVError("coinbase proven at index > 0")
        if tx.amount != BLOCK_REWARD:
            raise SPVError(f"coinbase mints {tx.amount}, subsidy is {BLOCK_REWARD}")
        if not tx.verify_signature():
            raise SPVError("coinbase must be unsigned")
    else:
        if tx.chain != chain_tag:
            raise SPVError("transaction signed for a different chain")
        if not tx.verify_signature():
            raise SPVError("bad transaction signature")


# -- the serving plane's proof cache (round 9) ---------------------------


class CachedProof:
    """One cached inclusion proof: the reorg-STABLE part of a ``TxProof``.

    Everything here — the transaction, its block's header, the block's
    height (a pure function of its ancestor chain, immutable however
    fork choice moves), the tx index, the merkle branch — is fixed the
    moment the block exists.  The one field that moves with every new
    block, ``tip_height``, is deliberately NOT cached: the serving path
    stamps the current tip into a ``dataclasses.replace`` (object path)
    or patches four bytes of the memoized wire payload (hot path), so a
    cache entry stays byte-correct across any number of tip advances.

    ``payload`` is a slot the WIRE layer fills lazily (the serialized
    PROOF frame with tip_height zeroed — node/protocol.py owns the
    encoding; this module stays protocol-free).  ``ProofCache`` charges
    it to the entry's size when notified.
    """

    __slots__ = ("proof", "payload")

    def __init__(self, proof: TxProof):
        self.proof = proof  # tip_height == 0 template
        self.payload: bytes | None = None

    def at_tip(self, tip_height: int) -> TxProof:
        return dataclasses.replace(self.proof, tip_height=tip_height)

    def approx_bytes(self) -> int:
        p = self.proof
        return (
            len(p.tx.serialize())
            + 80  # header
            + 32 * len(p.branch)
            + 96  # object/key overhead estimate
            + (len(self.payload) if self.payload is not None else 0)
        )


class ProofCache:
    """Bounded LRU of ``CachedProof`` entries keyed ``(block hash, txid)``.

    Reorg safety has two independent layers:

    - the LOOKUP layer: ``Chain.tx_proof`` resolves txid → containing
      main-chain block through ``_tx_index``, which every tip move
      rewrites — so a cached proof for an orphaned block is unreachable
      the instant the reorg lands, whatever this cache holds;
    - the INVALIDATION layer: the chain's reorg event path
      (``add_block``'s removed list) explicitly drops every entry for
      each abandoned block (``invalidate_block``), so stale entries
      also stop costing memory — and the "never served stale" property
      does not depend on a single index staying coherent (tested:
      tests/test_queryplane.py's reorg case asserts both layers).

    Bounded by bytes, LRU evicted; ``bytes_used`` is charged to the
    node's accounted memory gauge (node/node.py ``_memory_gauge``) like
    every other cache the governor watches.
    """

    def __init__(self, max_bytes: int = 8 << 20):
        self.max_bytes = int(max_bytes)
        self._lru: "collections.OrderedDict[tuple[bytes, bytes], CachedProof]" = (
            collections.OrderedDict()
        )
        #: block hash -> set of txids cached under it (O(block) reorg
        #: invalidation without scanning the whole LRU).
        self._by_block: dict[bytes, set[bytes]] = {}
        self.bytes_used = 0
        self.hits = 0
        self.misses = 0
        self.invalidated = 0

    def __len__(self) -> int:
        return len(self._lru)

    def get(self, block_hash: bytes, txid: bytes) -> CachedProof | None:
        entry = self._lru.get((block_hash, txid))
        if entry is None:
            self.misses += 1
            return None
        self._lru.move_to_end((block_hash, txid))
        self.hits += 1
        return entry

    def add(self, block_hash: bytes, txid: bytes, proof: TxProof) -> CachedProof:
        """Cache ``proof`` (tip_height is zeroed here — templates never
        embed a tip) and return the entry."""
        key = (block_hash, txid)
        entry = self._lru.get(key)
        if entry is not None:
            self._lru.move_to_end(key)
            return entry
        if proof.tip_height:
            proof = dataclasses.replace(proof, tip_height=0)
        entry = CachedProof(proof)
        self._lru[key] = entry
        self._by_block.setdefault(block_hash, set()).add(txid)
        self.bytes_used += entry.approx_bytes()
        self._evict()
        return entry

    def note_payload(self, entry: CachedProof, payload: bytes) -> None:
        """The wire layer memoized ``entry``'s serialized form — account
        for the extra bytes (and re-run eviction against the budget)."""
        if entry.payload is None:
            entry.payload = payload
            self.bytes_used += len(payload)
            self._evict()

    def _evict(self) -> None:
        while self.bytes_used > self.max_bytes and len(self._lru) > 1:
            (bhash, txid), entry = self._lru.popitem(last=False)
            self.bytes_used -= entry.approx_bytes()
            txids = self._by_block.get(bhash)
            if txids is not None:
                txids.discard(txid)
                if not txids:
                    del self._by_block[bhash]

    def invalidate_block(self, block_hash: bytes) -> int:
        """Drop every entry for ``block_hash`` (the reorg event path);
        returns how many were dropped."""
        txids = self._by_block.pop(block_hash, None)
        if not txids:
            return 0
        n = 0
        for txid in txids:
            entry = self._lru.pop((block_hash, txid), None)
            if entry is not None:
                self.bytes_used -= entry.approx_bytes()
                n += 1
        self.invalidated += n
        return n

    def snapshot(self) -> dict:
        return {
            "entries": len(self._lru),
            "bytes": self.bytes_used,
            "hits": self.hits,
            "misses": self.misses,
            "invalidated": self.invalidated,
        }


def build_block_proofs(
    block, height: int, txids: list[bytes] | None = None
) -> dict[bytes, TxProof]:
    """Tip-height-0 proof templates for EVERY transaction in ``block`` —
    the batch primitive: one ``merkle_levels`` tree construction
    amortized across all of the block's proofs (vs one O(ntx) hashing
    pass per proof on the serial path).  ``txids`` may carry the
    precomputed txid list when the caller already has it."""
    from p1_tpu.core.block import branch_from_levels, merkle_levels

    if txids is None:
        txids = [tx.txid() for tx in block.txs]
    levels = merkle_levels(txids)
    return {
        txid: TxProof(
            tx=block.txs[i],
            header=block.header,
            height=height,
            tip_height=0,
            index=i,
            branch=branch_from_levels(levels, i),
        )
        for i, txid in enumerate(txids)
    }
