"""Fast host-side SHA-256d for the validation path.

Block ids, txids and merkle trees sit on the chain-sync/gossip hot path, so
they use ``hashlib`` directly.  The pure-Python implementation in
``p1_tpu.hashx.sha256_ref`` stays the *ground truth* for tests and the
midstate computation only.
"""

from __future__ import annotations

import hashlib

_sha256 = hashlib.sha256  # bound once: this runs several times per block


def sha256d(data: bytes) -> bytes:
    return _sha256(_sha256(data).digest()).digest()
