"""Native Ed25519 — the ctypes seam over native/ed25519.cpp.

The middle tier of the signature-backend ladder (core/keys.py: wheel >
native > pure-Python): when the ``cryptography`` wheel is absent but a
C++ toolchain exists (or a cached build does), this module loads the
shared object ``hashx/native_build.py`` compiles from the native/ tree
and exposes the SAME call surface as the pure-Python fallback
(``verify`` / ``verify_batch``), with the same non-negotiable
semantics:

- ``verify`` is the serial cofactorless RFC 8032 check, bit-identical
  to ``core/_ed25519.py::verify`` on every input — length checks,
  s < q range check, non-canonical-y rejection, and k reduced mod q
  happen HERE (CPython's hashlib/long arithmetic is already C-speed);
  only the curve arithmetic crosses the ctypes boundary.
- ``verify_batch`` is the subgroup-gated random-linear-combination
  batch: every A (deduplicated per call) and every R is exactly gated
  ([q]·P == identity) in C, then one Pippenger MSM settles the
  combination — batch acceptance implies serial acceptance (2⁻¹²⁸),
  batch False is NOT a serial verdict, exactly the
  ``core/_ed25519.py::verify_batch`` contract.  The per-batch random
  coefficients come from ``secrets`` on the Python side, so the C
  engine is deterministic and RNG-free.

Degradation is graceful and memoized: if the toolchain is missing, the
build fails, or the .so will not load, ``available()`` turns False for
the life of the process (one log line, no retry storm) and keys.py
keeps the pure-Python tier.  Nothing in this module raises at import.
"""

from __future__ import annotations

import ctypes
import logging
import secrets

from p1_tpu.core._ed25519 import _Q, _sha512

log = logging.getLogger(__name__)

_LIB = None
_LOAD_FAILED = False


def _bind(lib) -> None:
    lib.p1_ed25519_impl.argtypes = []
    lib.p1_ed25519_impl.restype = ctypes.c_char_p
    lib.p1_ed25519_verify.argtypes = [ctypes.c_char_p] * 4
    lib.p1_ed25519_verify.restype = ctypes.c_int
    lib.p1_ed25519_in_subgroup.argtypes = [ctypes.c_char_p]
    lib.p1_ed25519_in_subgroup.restype = ctypes.c_int
    lib.p1_ed25519_batch.argtypes = [
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_uint64,
    ]
    lib.p1_ed25519_batch.restype = ctypes.c_int


def load():
    """The loaded shared library, or None (memoized either way).

    First call on a cold cache pays one g++ invocation
    (hashx/native_build.py, content-addressed); every failure mode —
    no compiler, build error, unloadable object — is caught, logged
    once, and remembered, so a compiler-less image costs one attempt
    and then behaves exactly like a pure-Python-only install.
    """
    global _LIB, _LOAD_FAILED
    if _LIB is not None or _LOAD_FAILED:
        return _LIB
    try:
        from p1_tpu.hashx.native_build import build_lib

        lib = ctypes.CDLL(str(build_lib()))
        _bind(lib)
        # One end-to-end probe before trusting the object: a known-good
        # RFC 8032-shaped check must pass, or the build is treated as
        # absent (a half-linked or ABI-drifted .so must never become
        # the consensus backend).
        if not _selfcheck(lib):
            raise OSError("native ed25519 self-check failed")
        _LIB = lib
    except Exception as exc:  # NativeBuildError, OSError, AttributeError
        _LOAD_FAILED = True
        log.info("native Ed25519 engine unavailable (%s); using fallback", exc)
        return None
    return _LIB


def _selfcheck(lib) -> bool:
    from p1_tpu.core import _ed25519 as _py

    seed = b"\x00" * 32
    pub = _py.public_key(seed)
    sig = _py.sign(seed, b"p1-native-selfcheck")
    k = (
        int.from_bytes(_sha512(sig[:32] + pub + b"p1-native-selfcheck"), "little")
        % _Q
    )
    good = lib.p1_ed25519_verify(
        pub, sig[:32], sig[32:], k.to_bytes(32, "little")
    )
    bad = lib.p1_ed25519_verify(
        pub, sig[:32], (_Q - 1).to_bytes(32, "little"), k.to_bytes(32, "little")
    )
    return good == 1 and bad == 0


def available() -> bool:
    return load() is not None


def impl() -> str | None:
    """The C engine's arithmetic tag (telemetry), or None if absent."""
    lib = load()
    return lib.p1_ed25519_impl().decode() if lib is not None else None


def in_subgroup(enc: bytes) -> bool | None:
    """Exact prime-subgroup gate on one compressed point — the C
    engine's answer (test hook; None = undecodable)."""
    lib = load()
    if lib is None:
        raise RuntimeError("native ed25519 engine not loaded")
    r = lib.p1_ed25519_in_subgroup(bytes(enc))
    return None if r < 0 else bool(r)


def verify(pubkey: bytes, sig: bytes, message: bytes) -> bool:
    """Serial cofactorless verification — ``_ed25519.verify`` semantics,
    native curve arithmetic.  Caller guarantees the engine loaded."""
    lib = load()
    if lib is None:
        raise RuntimeError("native ed25519 engine not loaded")
    if len(pubkey) != 32 or len(sig) != 64:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= _Q:
        return False
    k = int.from_bytes(_sha512(sig[:32] + pubkey + message), "little") % _Q
    return bool(
        lib.p1_ed25519_verify(
            bytes(pubkey), sig[:32], sig[32:], k.to_bytes(32, "little")
        )
    )


def verify_batch(triples) -> bool:
    """Subgroup-gated batch verification — ``_ed25519.verify_batch``
    semantics, native gates + Pippenger MSM.

    The Python side does everything CPython is already fast at: length
    and s-range checks, SHA-512 challenges, mod-q scalar products, the
    128-bit random coefficients, and pubkey deduplication (the C engine
    gates each UNIQUE pubkey once — block windows repeat senders, so
    this is the same economy _ed25519's per-pubkey lru_cache buys).
    """
    lib = load()
    if lib is None:
        raise RuntimeError("native ed25519 engine not loaded")
    triples = list(triples)
    n = len(triples)
    if n == 0:
        return True
    uniq: dict[bytes, int] = {}
    idx = []
    r_encs = []
    zr = []
    za = []
    s_total = 0
    for pubkey, sig, message in triples:
        if len(pubkey) != 32 or len(sig) != 64:
            return False
        s = int.from_bytes(sig[32:], "little")
        if s >= _Q:
            return False
        pubkey = bytes(pubkey)
        slot = uniq.setdefault(pubkey, len(uniq))
        idx.append(slot)
        k = int.from_bytes(_sha512(sig[:32] + pubkey + message), "little") % _Q
        # Unpredictable per-batch coefficients: an adversary must not
        # be able to craft signatures whose errors cancel in the sum.
        z = secrets.randbits(128) | 1
        s_total = (s_total + z * s) % _Q
        r_encs.append(sig[:32])
        zr.append(z.to_bytes(32, "little"))
        # z·k mod q is exact only because the C engine PROVES A has
        # order q before the term enters the sum (gate-first contract).
        za.append((z * k % _Q).to_bytes(32, "little"))
    sb = ((_Q - s_total) % _Q).to_bytes(32, "little")
    pub_idx = (ctypes.c_uint32 * n)(*idx)
    return bool(
        lib.p1_ed25519_batch(
            b"".join(uniq),
            len(uniq),
            pub_idx,
            b"".join(r_encs),
            b"".join(zr),
            b"".join(za),
            sb,
            n,
        )
    )
