from p1_tpu.core.header import (
    HEADER_SIZE,
    NONCE_OFFSET,
    BlockHeader,
    target_from_difficulty,
    target_to_words,
    meets_target,
)
from p1_tpu.core.tx import Transaction
from p1_tpu.core.block import (
    Block,
    merkle_branch,
    merkle_root,
    verify_merkle_branch,
)
from p1_tpu.core.genesis import GENESIS_TIMESTAMP, make_genesis
from p1_tpu.core.retarget import RetargetRule

__all__ = [
    "HEADER_SIZE",
    "NONCE_OFFSET",
    "BlockHeader",
    "target_from_difficulty",
    "target_to_words",
    "meets_target",
    "Transaction",
    "Block",
    "merkle_branch",
    "merkle_root",
    "verify_merkle_branch",
    "GENESIS_TIMESTAMP",
    "make_genesis",
    "RetargetRule",
]
