"""Difficulty retargeting: the consensus rule that keeps block spacing.

Capability parity: BASELINE.json's configs pin difficulty per run (16..28),
so fixed difficulty stays the default everywhere — but a "Bitcoin-like toy
cryptocurrency" (BASELINE.json:5) whose difficulty can never move is only
half a consensus engine, so retargeting ships as an **opt-in chain
parameter**.  Design (Bitcoin's shape, bit-granular):

- Every ``window`` blocks, compare the observed span of the last window
  against ``spacing * (window - 1)`` (window blocks bound window-1
  intervals — honoring, not repeating, Bitcoin's famous 2015/2016
  off-by-one) and move the difficulty by whole bits: one bit per 2x
  deviation, clamped to ``max_adjust`` bits per retarget (Bitcoin clamps
  the timespan 4x = our default 2 bits).  Difficulty here is "required
  leading zero bits" (core/header.py), so ±1 bit is exactly ±2x work —
  integer comparisons only, no floats anywhere near consensus.
- The rule's parameters are **committed into the genesis block**
  (core/genesis.py): two chains with different rules have different chain
  ids, so the HELLO handshake and chain-bound transaction signatures
  enforce rule agreement with no extra protocol surface.
- Timestamps must strictly increase on retargeting chains (enforced at
  connect time in chain/chain.py) so the observed span is positive and a
  miner cannot freeze time to farm easy blocks.  There is deliberately no
  wall-clock future bound: consensus stays a pure function of the block
  DAG (SURVEY §5 determinism), and backdating is already unprofitable —
  claiming a shorter span only *raises* the difficulty.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class RetargetRule:
    """Opt-in difficulty adjustment parameters (a chain-identity field)."""

    window: int  # blocks per retarget period
    spacing: int  # target seconds between blocks
    max_adjust: int = 2  # max bits moved per retarget (2 bits = Bitcoin's 4x)

    def __post_init__(self) -> None:
        if self.window < 2:
            raise ValueError("retarget window must be >= 2 blocks")
        if self.spacing < 1:
            raise ValueError("target spacing must be >= 1 second")
        if not 1 <= self.max_adjust <= 8:
            raise ValueError("max_adjust must be in 1..8 bits")

    @classmethod
    def from_params(
        cls, window: int, spacing: int
    ) -> "RetargetRule | None":
        """The ONE home of flag/config-pair validation: both must be set
        together; (0, 0) selects fixed difficulty (None).  CLI and
        NodeConfig both delegate here so the wallet and node paths can
        never diverge on what chain a flag pair names."""
        if bool(window) != bool(spacing):
            raise ValueError(
                "--retarget-window and --target-spacing must be set together"
            )
        return cls(window, spacing) if window else None

    @property
    def expected_span(self) -> int:
        """Target seconds for one whole window (window-1 intervals)."""
        return self.spacing * (self.window - 1)

    def adjusted(self, parent_difficulty: int, span: int) -> int:
        """The difficulty for the block that opens a new window, given the
        observed ``span`` of the window just closed.  Integer-only: one
        bit harder per halving of the expected span, one bit easier per
        doubling, clamped to ``max_adjust`` and to the 1..255 range the
        header can express (difficulty 0 would make every hash valid)."""
        span = max(1, span)
        adj = 0
        while adj < self.max_adjust and span * (2 << adj) <= self.expected_span:
            adj += 1
        if adj == 0:
            while adj > -self.max_adjust and span >= (2 << (-adj)) * self.expected_span:
                adj -= 1
        return min(255, max(1, parent_difficulty + adj))
