"""Difficulty retargeting: the consensus rule that keeps block spacing.

Capability parity: BASELINE.json's configs pin difficulty per run (16..28),
so fixed difficulty stays the default everywhere — but a "Bitcoin-like toy
cryptocurrency" (BASELINE.json:5) whose difficulty can never move is only
half a consensus engine, so retargeting ships as an **opt-in chain
parameter**.  Design (Bitcoin's shape, bit-granular):

- Every ``window`` blocks, compare the observed span of the last window
  against ``spacing * (window - 1)`` (window blocks bound window-1
  intervals — honoring, not repeating, Bitcoin's famous 2015/2016
  off-by-one) and move the difficulty by whole bits: one bit per 2x
  deviation, clamped to ``max_adjust`` bits per retarget (Bitcoin clamps
  the timespan 4x = our default 2 bits).  Difficulty here is "required
  leading zero bits" (core/header.py), so ±1 bit is exactly ±2x work —
  integer comparisons only, no floats anywhere near consensus.
- The rule's parameters are **committed into the genesis block**
  (core/genesis.py): two chains with different rules have different chain
  ids, so the HELLO handshake and chain-bound transaction signatures
  enforce rule agreement with no extra protocol surface.
- Timestamp rules, both directions (enforced at connect time in
  chain/chain.py), with consensus kept a pure function of the block DAG
  (SURVEY §5 determinism — no wall-clock future bound anywhere):

  * **Backward**: timestamps must strictly increase, so the observed
    span is positive — and backdating is unprofitable anyway, since
    claiming a shorter span only *raises* the difficulty.
  * **Forward**: a block may claim at most ``max_step * spacing``
    seconds above its parent.  Without this cap, forward-dating is the
    profitable direction: a miner closing a window with one inflated
    timestamp claims an arbitrarily long span and buys ``max_adjust``
    bits of easier difficulty, and doing it repeatedly ratchets the
    difficulty to 1 (VERDICT r4 — the attack simulation in
    tests/test_retarget.py reproduces the collapse at 10% hashrate
    uncapped).  With the cap, fake time must be accumulated block by
    block.  The honest-contribution subtlety (measured in the same
    simulation, and the reason the naive threshold is wrong): once any
    inflated stamp lands, strict-increase forces every later honest
    block to stamp parent+1, so honest blocks stop contributing real
    time to spans entirely — the attacker's own surplus must carry the
    whole forgery, ~alpha * window * max_step * spacing per window,
    and holding even one easier bit needs that to exceed ~2x the
    expected span: **sustained-forgery threshold alpha* ~= 2 /
    max_step of the hashrate**.  At the default ``max_step=4`` the
    simulation shows a 25% attacker held to the honest equilibrium
    (time-average within a bit) while collapse requires ~40%+ —
    near-majority hashrate, where the chain is already reorg-attackable
    and no timestamp rule can save it.  Honest cost of the cap: a block
    that genuinely took > 4x spacing gets a truncated stamp
    (probability e^-4 ~= 1.8% at equilibrium, negligible span effect),
    and a dormant chain's difficulty decays toward a returning
    hashrate at max_adjust bits per window instead of instantly.

  This is the strongest bound a WALL-CLOCK-FREE rule can offer: with
  consensus a pure function of the block DAG, "time" ultimately IS
  what the majority of stamps say (Bitcoin bounds forward-dating with
  its +2h network-time rule — a wall clock — for exactly this reason).
  DAG-purity buys deterministic replay and testability at that price,
  and the cap prices the residual attack at near-majority hashrate.

Resolution floor, observed live: timestamps are integer seconds and
must strictly increase, so when real blocks arrive faster than 1/s the
chain clock advances +1 s per block regardless of real time — a window
of W blocks then spans ~W seconds and a rule with ``spacing`` near 1
reads perfect pacing forever, never adjusting.  Retargeting only
regulates block rates at or below ~1 block/second; pick ``spacing``
comfortably above 1 (and expect the rule to RAISE difficulty until real
spacing exceeds a second before it can see anything).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class RetargetRule:
    """Opt-in difficulty adjustment parameters (a chain-identity field)."""

    window: int  # blocks per retarget period
    spacing: int  # target seconds between blocks
    max_adjust: int = 2  # max bits moved per retarget (2 bits = Bitcoin's 4x)
    #: Per-block timestamp-increment cap, in multiples of ``spacing`` —
    #: the forward-dating bound (module docstring).  4 puts the
    #: sustained-forgery threshold at ~2/max_step = half the hashrate
    #: (simulation: 25% attackers held, collapse needs ~40%+) while
    #: truncating only e^-4 ≈ 1.8% of honest blocks.  Part of chain
    #: identity like the rest.
    max_step: int = 4

    def __post_init__(self) -> None:
        # Upper bounds are consensus sanity AND native-engine safety: the
        # C++ verifier ring-buffers `window` timestamps and does int64
        # span arithmetic (spacing * window * 2^max_adjust must not
        # overflow), and it is built -fno-exceptions, where a gigantic
        # allocation would abort the process instead of raising.
        if not 2 <= self.window <= 1_000_000:
            raise ValueError("retarget window must be in 2..1_000_000 blocks")
        if not 1 <= self.spacing <= 31_536_000:  # one year per block, max
            raise ValueError("target spacing must be in 1..31_536_000 seconds")
        if not 1 <= self.max_adjust <= 8:
            raise ValueError("max_adjust must be in 1..8 bits")
        if not 2 <= self.max_step <= 1024:
            raise ValueError("max_step must be in 2..1024 spacings")

    @property
    def max_increment(self) -> int:
        """Largest valid ``timestamp - parent.timestamp`` in seconds."""
        return self.max_step * self.spacing

    def timestamp_violation(
        self, parent_height: int, parent_ts: int, ts: int
    ) -> str | None:
        """The ONE home of the timestamp consensus rule (reason string,
        or None if valid) — connect-time validation, the light-client
        replay verifier, and the miner's clamp all delegate here so the
        three can never diverge (the from_params convention).

        Strict increase always; the forward cap from height 2 on.
        Height 1 is exempt: genesis carries a fixed timestamp (chain
        identity), so the first block must be free to anchor the chain
        clock at the real bootstrap time — see the module docstring and
        the MINING-POLICY guard in node.py that keeps a hostile anchor
        from being extended."""
        if ts <= parent_ts:
            return "timestamp does not increase over parent"
        delta = ts - parent_ts
        if parent_height >= 1 and delta > self.max_increment:
            return (
                f"timestamp advances {delta}s over parent, cap is "
                f"{self.max_increment}s"
            )
        return None

    def clamp_timestamp(
        self, parent_height: int, parent_ts: int, ts: int
    ) -> int:
        """The largest consensus-valid stamp not exceeding ``ts`` for a
        child of (parent_height, parent_ts) — what an honest assembler
        uses when its wall clock runs past the cap."""
        ts = max(ts, parent_ts + 1)
        if parent_height >= 1:
            ts = min(ts, parent_ts + self.max_increment)
        return ts

    @classmethod
    def from_params(
        cls, window: int, spacing: int
    ) -> "RetargetRule | None":
        """The ONE home of flag/config-pair validation: both must be set
        together; (0, 0) selects fixed difficulty (None).  CLI and
        NodeConfig both delegate here so the wallet and node paths can
        never diverge on what chain a flag pair names."""
        if bool(window) != bool(spacing):
            raise ValueError(
                "--retarget-window and --target-spacing must be set together"
            )
        return cls(window, spacing) if window else None

    @property
    def expected_span(self) -> int:
        """Target seconds for one whole window (window-1 intervals)."""
        return self.spacing * (self.window - 1)

    def adjusted(self, parent_difficulty: int, span: int) -> int:
        """The difficulty for the block that opens a new window, given the
        observed ``span`` of the window just closed.  Integer-only: one
        bit harder per halving of the expected span, one bit easier per
        doubling, clamped to ``max_adjust`` and to the 1..255 range the
        header can express (difficulty 0 would make every hash valid)."""
        span = max(1, span)
        adj = 0
        while adj < self.max_adjust and span * (2 << adj) <= self.expected_span:
            adj += 1
        if adj == 0:
            while adj > -self.max_adjust and span >= (2 << (-adj)) * self.expected_span:
                adj -= 1
        return min(255, max(1, parent_difficulty + adj))
