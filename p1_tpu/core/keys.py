"""Ed25519 account keys: ownership for the account model.

Capability parity: the reference is "a Bitcoin-like toy cryptocurrency"
(BASELINE.json:5) — "only the owner can spend" is the property that makes
a ledger mean anything.  Design (tpu rebuild, round 4):

- An **account id is a key fingerprint**: ``p1`` + first 16 hex chars of
  SHA-256(public key).  Any string can *receive* coins (miner ids stay
  free-form; coins sent to a non-fingerprint id are simply unspendable),
  but only a transaction carrying the matching public key and a valid
  Ed25519 signature can *spend* from a fingerprint account — enforced at
  mempool admission AND block validation (p1_tpu/chain/validate.py).
- Ed25519 via the ``cryptography`` package **when the wheel is present**,
  else the vendored pure-Python RFC 8032 implementation
  (core/_ed25519.py).  The wheel is an optional accelerator, never an
  import-time requirement: images without it (no egress to fetch one)
  still import, sign, and verify — byte-identically, just slower.
  Signatures are 64 bytes, public keys 32 — both fit the transaction's
  length-prefixed layout.
- Deterministic from a 32-byte seed, so tests can use fixed keys and the
  CLI can persist one JSON file per identity (``p1 keygen``).

Verification is memoized (bounded LRU): a transaction is typically seen
several times (gossip admission, block validation, reorg resurrection) and
Ed25519 verify costs ~100 µs native (a few ms pure-Python) — the cache
makes every re-check O(1).
"""

from __future__ import annotations

import functools
import hashlib
import json
import os

try:  # pragma: no cover - exercised implicitly by whichever env runs
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric import ed25519

    HAVE_CRYPTOGRAPHY = True
except ImportError:  # the wheel is optional; fall back to pure Python
    HAVE_CRYPTOGRAPHY = False

from p1_tpu.core import _ed25519 as _py_ed25519

#: Account-id prefix: distinguishes spendable (key-backed) accounts from
#: free-form receive-only ids at a glance.
ACCOUNT_PREFIX = "p1"
_FINGERPRINT_HEX = 16

PUBKEY_SIZE = 32
SIG_SIZE = 64
SEED_SIZE = 32


@functools.lru_cache(maxsize=65_536)
def account_id(pubkey: bytes) -> str:
    """The spendable account id owned by ``pubkey``.  Memoized: every
    ``verify_signature`` call derives the sender's fingerprint, and a
    node re-checks the same few senders' keys across gossip admission,
    block validation, and reorgs — pure function, bounded cache."""
    if len(pubkey) != PUBKEY_SIZE:
        raise ValueError(f"public key must be {PUBKEY_SIZE} bytes")
    return ACCOUNT_PREFIX + hashlib.sha256(pubkey).hexdigest()[:_FINGERPRINT_HEX]


def account_id_or_none(pubkey: bytes) -> str | None:
    """``account_id`` that maps a malformed key to None (never a valid
    sender id) instead of raising — for use in validation predicates."""
    return account_id(pubkey) if len(pubkey) == PUBKEY_SIZE else None


class Keypair:
    """One Ed25519 identity: seed -> (private, public, account id)."""

    def __init__(self, seed: bytes):
        if len(seed) != SEED_SIZE:
            raise ValueError(f"seed must be {SEED_SIZE} bytes")
        self._seed = seed
        if HAVE_CRYPTOGRAPHY:
            self._private = ed25519.Ed25519PrivateKey.from_private_bytes(seed)
            self.pubkey: bytes = self._private.public_key().public_bytes_raw()
        else:
            self._private = None
            self.pubkey = _py_ed25519.public_key(seed)
        self.account: str = account_id(self.pubkey)

    @classmethod
    def generate(cls) -> "Keypair":
        return cls(os.urandom(SEED_SIZE))

    @classmethod
    def from_seed_text(cls, text: str) -> "Keypair":
        """Deterministic keypair from any text label (tests/tools only —
        the seed is the SHA-256 of the label, so the 'secret' is public)."""
        return cls(hashlib.sha256(text.encode("utf-8")).digest())

    def sign(self, message: bytes) -> bytes:
        if self._private is not None:
            return self._private.sign(message)
        # Ed25519 signing is deterministic (RFC 8032): the fallback
        # produces the exact bytes the wheel would.
        return _py_ed25519.sign(self._seed, message)

    # -- persistence (p1 keygen / p1 tx --key) ---------------------------

    def save(self, path: str, overwrite: bool = False) -> None:
        """Write the key as JSON {seed_hex, pubkey_hex, account} with
        owner-only permissions (it contains the private seed).

        Refuses to clobber an existing file unless ``overwrite`` — a seed
        exists nowhere else, so silently truncating one would make every
        coin its fingerprint holds permanently unspendable.
        """
        payload = json.dumps(
            {
                "seed_hex": self._seed.hex(),
                "pubkey_hex": self.pubkey.hex(),
                "account": self.account,
            },
            indent=2,
        )
        flags = os.O_WRONLY | os.O_CREAT | (
            os.O_TRUNC if overwrite else os.O_EXCL
        )
        fd = os.open(path, flags, 0o600)
        # os.open's mode only applies at creation — an overwrite of a
        # pre-existing world-readable file must still end up owner-only.
        os.fchmod(fd, 0o600)
        with os.fdopen(fd, "w") as f:
            f.write(payload + "\n")

    @classmethod
    def load(cls, path: str) -> "Keypair":
        with open(path) as f:
            data = json.load(f)
        kp = cls(bytes.fromhex(data["seed_hex"]))
        if data.get("account") not in (None, kp.account):
            raise ValueError(
                f"key file {path} claims account {data['account']} but its "
                f"seed derives {kp.account}"
            )
        return kp


@functools.lru_cache(maxsize=65_536)
def _verify_cached(pubkey: bytes, sig: bytes, message: bytes) -> bool:
    if not HAVE_CRYPTOGRAPHY:
        return _py_ed25519.verify(pubkey, sig, message)
    try:
        ed25519.Ed25519PublicKey.from_public_bytes(pubkey).verify(sig, message)
        return True
    except (InvalidSignature, ValueError):
        return False


def verify(pubkey: bytes, sig: bytes, message: bytes) -> bool:
    """True iff ``sig`` is ``pubkey``'s valid Ed25519 signature over
    ``message``.  Memoized — safe because the answer is a pure function
    of the three byte strings."""
    if len(pubkey) != PUBKEY_SIZE or len(sig) != SIG_SIZE:
        return False
    return _verify_cached(pubkey, sig, message)
