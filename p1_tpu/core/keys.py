"""Ed25519 account keys: ownership for the account model.

Capability parity: the reference is "a Bitcoin-like toy cryptocurrency"
(BASELINE.json:5) — "only the owner can spend" is the property that makes
a ledger mean anything.  Design (tpu rebuild, round 4):

- An **account id is a key fingerprint**: ``p1`` + first 16 hex chars of
  SHA-256(public key).  Any string can *receive* coins (miner ids stay
  free-form; coins sent to a non-fingerprint id are simply unspendable),
  but only a transaction carrying the matching public key and a valid
  Ed25519 signature can *spend* from a fingerprint account — enforced at
  mempool admission AND block validation (p1_tpu/chain/validate.py).
- Ed25519 via the ``cryptography`` package **when the wheel is present**,
  else the vendored pure-Python RFC 8032 implementation
  (core/_ed25519.py).  The wheel is an optional accelerator, never an
  import-time requirement: images without it (no egress to fetch one)
  still import, sign, and verify — byte-identically, just slower.
  Signatures are 64 bytes, public keys 32 — both fit the transaction's
  length-prefixed layout.
- Deterministic from a 32-byte seed, so tests can use fixed keys and the
  CLI can persist one JSON file per identity (``p1 keygen``).

Validation fast lane (rounds 8 and 15).  Ed25519 verify costs ~100 µs
with the wheel and ~3 ms pure-Python, and it dominates every untrusted
validation path, so this module carries a backend LADDER plus three
speed layers on top of the plain ``verify``:

- **Backend ladder** (round 15): ``cryptography`` wheel > native C++
  engine (native/ed25519.cpp via core/_ed25519_native.py — built
  lazily, content-addressed, ~20× the pure-Python fallback on this
  host) > pure-Python ``core/_ed25519.py``.  Resolution is lazy and
  memoized (``backend()``); a missing wheel, missing compiler, or
  failed build degrades one rung with a single log line and identical
  semantics — every backend is pinned verdict- and error-text-
  equivalent on every input by the torsion/corruption equivalence
  matrix (tests/test_sigbatch.py, tests/test_native_ed25519.py).
  ``set_sig_backend`` / ``NodeConfig.sig_backend`` / ``--sig-backend``
  / ``P1_SIG_BACKEND`` force a rung (``fallback`` = pure-Python), or
  opt batches into the ``device`` tier — the JAX multi-scalar
  multiplication sharded over the chip mesh
  (hashx/ed25519_msm.py, a win on real TPU meshes, not on host CPUs).
- ``verify_batch(triples)`` — verify many (pubkey, sig, message) triples
  at once.  With the ``cryptography`` wheel the triples are chunked over
  a ``concurrent.futures`` thread pool (``set_verify_workers`` /
  ``config.verify_workers``; OpenSSL releases the GIL, so threads give
  real parallelism on multi-core) — the native engine's chunks use the
  same pool (ctypes releases the GIL during the C call).  On the
  pure-Python rung the fallback uses a genuine batch-verification
  equation — one multi-scalar multiplication for the whole window plus
  an exact prime-subgroup gate on every point
  (``_ed25519.verify_batch``), ~2× per signature at revalidation window
  sizes — run in the calling thread (it holds the GIL, so a pool would
  add overhead, not parallelism) and chunked so memory stays bounded.
  The native and device batches compute the SAME subgroup-gated
  equation.  Batch TRUE implies every triple is serially valid; batch
  FALSE is not yet a verdict (the gate also rejects torsion-crafted
  inputs the serial equation tolerates).
- ``first_invalid(triples)`` — serial-confirming locator used when a
  batch fails: sub-batches that pass are skipped (acceptance implies
  serial validity), everything else is settled by ``verify`` itself, so
  the REJECTED signature (and the error text consensus reports) — or
  the conclusion that there is none — is byte-identical to the serial
  path's.
- The verify-once signature cache lives one level up
  (core/sigcache.py, keyed by txid) — positive results are memoized
  there, never here.  ``verify`` keeps only a small bounded NEGATIVE
  memo (deterministic function, so semantics-free) to absorb peers
  replaying a known-bad signature; ``STATS`` counts how work reached
  the backend (serial vs batched) for ``status()["validation"]`` and
  the no-double-verify regression tests.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import hashlib
import json
import logging
import os
import threading

try:  # pragma: no cover - exercised implicitly by whichever env runs
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric import ed25519

    HAVE_CRYPTOGRAPHY = True
except ImportError:  # the wheel is optional; fall back to pure Python
    HAVE_CRYPTOGRAPHY = False

from p1_tpu.core import _ed25519 as _py_ed25519
from p1_tpu.core import _ed25519_native as _native_ed25519

#: Account-id prefix: distinguishes spendable (key-backed) accounts from
#: free-form receive-only ids at a glance.
ACCOUNT_PREFIX = "p1"
_FINGERPRINT_HEX = 16

PUBKEY_SIZE = 32
SIG_SIZE = 64
SEED_SIZE = 32


@functools.lru_cache(maxsize=65_536)
def account_id(pubkey: bytes) -> str:
    """The spendable account id owned by ``pubkey``.  Memoized: every
    ``verify_signature`` call derives the sender's fingerprint, and a
    node re-checks the same few senders' keys across gossip admission,
    block validation, and reorgs — pure function, bounded cache."""
    if len(pubkey) != PUBKEY_SIZE:
        raise ValueError(f"public key must be {PUBKEY_SIZE} bytes")
    return ACCOUNT_PREFIX + hashlib.sha256(pubkey).hexdigest()[:_FINGERPRINT_HEX]


def account_id_or_none(pubkey: bytes) -> str | None:
    """``account_id`` that maps a malformed key to None (never a valid
    sender id) instead of raising — for use in validation predicates."""
    return account_id(pubkey) if len(pubkey) == PUBKEY_SIZE else None


class Keypair:
    """One Ed25519 identity: seed -> (private, public, account id)."""

    def __init__(self, seed: bytes):
        if len(seed) != SEED_SIZE:
            raise ValueError(f"seed must be {SEED_SIZE} bytes")
        self._seed = seed
        if HAVE_CRYPTOGRAPHY:
            self._private = ed25519.Ed25519PrivateKey.from_private_bytes(seed)
            self.pubkey: bytes = self._private.public_key().public_bytes_raw()
        else:
            self._private = None
            self.pubkey = _py_ed25519.public_key(seed)
        self.account: str = account_id(self.pubkey)

    @classmethod
    def generate(cls) -> "Keypair":
        return cls(os.urandom(SEED_SIZE))

    @classmethod
    def from_seed_text(cls, text: str) -> "Keypair":
        """Deterministic keypair from any text label (tests/tools only —
        the seed is the SHA-256 of the label, so the 'secret' is public)."""
        return cls(hashlib.sha256(text.encode("utf-8")).digest())

    def sign(self, message: bytes) -> bytes:
        if self._private is not None:
            return self._private.sign(message)
        # Ed25519 signing is deterministic (RFC 8032): the fallback
        # produces the exact bytes the wheel would.
        return _py_ed25519.sign(self._seed, message)

    # -- persistence (p1 keygen / p1 tx --key) ---------------------------

    def save(self, path: str, overwrite: bool = False) -> None:
        """Write the key as JSON {seed_hex, pubkey_hex, account} with
        owner-only permissions (it contains the private seed).

        Refuses to clobber an existing file unless ``overwrite`` — a seed
        exists nowhere else, so silently truncating one would make every
        coin its fingerprint holds permanently unspendable.
        """
        payload = json.dumps(
            {
                "seed_hex": self._seed.hex(),
                "pubkey_hex": self.pubkey.hex(),
                "account": self.account,
            },
            indent=2,
        )
        flags = os.O_WRONLY | os.O_CREAT | (
            os.O_TRUNC if overwrite else os.O_EXCL
        )
        fd = os.open(path, flags, 0o600)
        # os.open's mode only applies at creation — an overwrite of a
        # pre-existing world-readable file must still end up owner-only.
        os.fchmod(fd, 0o600)
        with os.fdopen(fd, "w") as f:
            f.write(payload + "\n")

    @classmethod
    def load(cls, path: str) -> "Keypair":
        with open(path) as f:
            data = json.load(f)
        kp = cls(bytes.fromhex(data["seed_hex"]))
        if data.get("account") not in (None, kp.account):
            raise ValueError(
                f"key file {path} claims account {data['account']} but its "
                f"seed derives {kp.account}"
            )
        return kp


log = logging.getLogger(__name__)

#: Every signature backend this module can resolve, in ladder order.
#: ``device`` is batch-only (hashx/ed25519_msm.py) and never enters
#: auto-resolution — it is an explicit opt-in for real device meshes.
SIG_BACKENDS = ("cryptography", "native", "pure-python", "device")

#: Explicit backend override (``set_sig_backend``); None = auto ladder.
_sig_backend: str | None = None
#: Memoized auto/override resolution (native probing compiles once).
_resolved: str | None = None


def set_sig_backend(name: str | None) -> None:
    """Pin the signature backend: ``auto``/None resolves the ladder
    (wheel > native > pure-Python), ``cryptography``/``native`` force a
    rung (falling back down the ladder with one warning if the rung is
    unavailable), ``fallback``/``pure-python`` force the pure-Python
    tier, ``device`` routes BATCHES through the JAX mesh MSM (serial
    verifies keep the auto ladder — the device path only pays off at
    window sizes).  Unknown names raise (a typo must not silently
    change the validation cost model)."""
    global _sig_backend, _resolved
    if name in (None, "", "auto"):
        _sig_backend = None
    elif name == "fallback":
        _sig_backend = "pure-python"
    elif name in SIG_BACKENDS:
        _sig_backend = name
    else:
        raise ValueError(
            f"unknown signature backend {name!r} "
            f"(choose from auto/fallback/{'/'.join(SIG_BACKENDS)})"
        )
    _resolved = None


def backend() -> str:
    """The ACTIVE serial-verification backend name, resolved lazily.

    Resolution is memoized: probing the native rung may compile the
    shared object once (content-addressed cache), and a failed probe is
    remembered so a compiler-less image pays one attempt, not one per
    call.  ``device`` overrides report ``device`` (that is where batch
    work goes) while serial dispatch underneath keeps the auto ladder.
    """
    global _resolved
    if _resolved is not None:
        return _resolved
    want = _sig_backend
    if want is None:
        want = os.environ.get("P1_SIG_BACKEND") or "auto"
        if want == "fallback":
            want = "pure-python"
        if want not in SIG_BACKENDS and want != "auto":
            log.warning("P1_SIG_BACKEND=%r unknown; using auto", want)
            want = "auto"
    if want == "cryptography" and not HAVE_CRYPTOGRAPHY:
        log.warning(
            "signature backend 'cryptography' requested but the wheel is "
            "absent; resolving the auto ladder instead"
        )
        want = "auto"
    if want == "native" and not _native_ed25519.available():
        log.warning(
            "signature backend 'native' requested but the engine did not "
            "load (no compiler / build failure); resolving the auto ladder"
        )
        want = "auto"
    if want == "auto":
        if HAVE_CRYPTOGRAPHY:
            want = "cryptography"
        elif _native_ed25519.available():
            want = "native"
        else:
            want = "pure-python"
    _resolved = want
    return want


def backend_label() -> str:
    """The backend name WITHOUT resolving: the memoized rung if any
    verification already ran, else the pin/env request verbatim.

    Status planes read this instead of ``backend()`` because resolution
    may probe the native rung — a ctypes load that can compile the
    shared object once — and a GETSTATUS served from the node's event
    loop must never be the call that pays it.  The only divergence from
    ``backend()`` is a node that has verified nothing yet, which
    reports the request (``auto``/pin) rather than forcing the probe.
    """
    if _resolved is not None:
        return _resolved
    if _sig_backend is not None:
        return _sig_backend
    want = os.environ.get("P1_SIG_BACKEND") or "auto"
    return want if want in SIG_BACKENDS else "auto"


def _serial_backend() -> str:
    """Where one-at-a-time verifies go: the active backend, except that
    ``device`` is batch-only and serial work takes the ladder beneath."""
    b = backend()
    if b != "device":
        return b
    if HAVE_CRYPTOGRAPHY:
        return "cryptography"
    return "native" if _native_ed25519.available() else "pure-python"


@dataclasses.dataclass
class VerifyStats:
    """Process-wide backend-call accounting.  ``serial`` counts
    signatures that reached the backend one at a time, ``batched`` the
    ones that went through ``verify_batch`` — together they are the
    node's "how much Ed25519 did we actually pay for" figure, and the
    no-double-verify regression tests assert their deltas are zero on
    cache-hit paths (a cache hit touches neither counter).
    ``backends`` splits the same signature counts by the backend that
    did the work (``status()["validation"]["backends"]``, the
    MetricsRegistry export) — the key set is FIXED so the status wire
    contract stays byte-pinnable."""

    serial: int = 0
    batched: int = 0
    batches: int = 0
    pool_dispatches: int = 0
    backends: dict = dataclasses.field(
        default_factory=lambda: {name: 0 for name in SIG_BACKENDS}
    )

    def reset(self) -> None:
        self.serial = self.batched = self.batches = self.pool_dispatches = 0
        for name in self.backends:
            self.backends[name] = 0


STATS = VerifyStats()


def _backend_verify(pubkey: bytes, sig: bytes, message: bytes) -> bool:
    """THE single-signature backend dispatch — every serial verify in
    the process funnels through here (tests spy on it)."""
    STATS.serial += 1
    which = _serial_backend()
    STATS.backends[which] += 1
    if which == "cryptography":
        try:
            ed25519.Ed25519PublicKey.from_public_bytes(pubkey).verify(
                sig, message
            )
            return True
        except (InvalidSignature, ValueError):
            return False
    if which == "native":
        return _native_ed25519.verify(pubkey, sig, message)
    return _py_ed25519.verify(pubkey, sig, message)


#: Bounded negative-verify memo.  Positive results are memoized at the
#: transaction layer (core/sigcache.py, keyed by txid); without a
#: negative counterpart, a peer replaying the same invalid tx or block
#: forces a full backend verify every time (~3 ms on the pure-Python
#: backend) where the pre-round-8 lru_cache was O(1).  Failures only:
#: ``verify`` is a deterministic function of the three byte strings, so
#: memoizing a FALSE can never change an outcome, and the key is a
#: salted digest of the exact bytes so an entry can't shadow any other
#: (pubkey, sig, message).  Single-threaded by design, like sigcache:
#: consulted on the event-loop/serial paths only — pool workers go
#: through ``_verify_chunk``, which never touches it.
_NEG_CACHE_MAX = 4096
_neg_salt = os.urandom(16)
_neg_cache: collections.OrderedDict = collections.OrderedDict()


def _neg_key(pubkey: bytes, sig: bytes, message: bytes) -> bytes:
    h = hashlib.sha256(_neg_salt)
    h.update(pubkey)
    h.update(sig)
    h.update(message)
    return h.digest()[:16]


def verify(pubkey: bytes, sig: bytes, message: bytes) -> bool:
    """True iff ``sig`` is ``pubkey``'s valid Ed25519 signature over
    ``message``.  A deterministic function of the three byte strings;
    the verify-once memo for VALID signatures lives at the transaction
    layer (core/sigcache.py, keyed by txid), and known-bad triples are
    absorbed by the bounded negative memo above — a memo hit touches no
    STATS counter because no backend work happened."""
    if len(pubkey) != PUBKEY_SIZE or len(sig) != SIG_SIZE:
        return False
    key = _neg_key(pubkey, sig, message)
    if key in _neg_cache:
        _neg_cache.move_to_end(key)
        return False
    ok = _backend_verify(pubkey, sig, message)
    if not ok:
        _neg_cache[key] = None
        while len(_neg_cache) > _NEG_CACHE_MAX:
            _neg_cache.popitem(last=False)
    return ok


# -- batch verification (untrusted-path fast lane, round 8) --------------

#: Below this many cache-missing signatures a batch call just runs
#: serially: thread dispatch and the MSM setup both cost more than they
#: save on a handful of signatures.  A constant, NOT configuration —
#: validation behavior must not vary with local tuning.
BATCH_MIN = 8

#: Signatures per worker chunk (wheel path) / per MSM window (fallback).
#: Bounds both the pool task granularity and the fallback's per-window
#: memory; the MSM's per-signature gain is nearly flat past ~1k.
BATCH_CHUNK = 1024

_workers_lock = threading.Lock()
_workers: int | None = None  # explicit set_verify_workers override
_executor = None
_executor_size = 0
_fallback_warned = False


def set_verify_workers(n: int | None) -> None:
    """Pin the verification worker-pool size (None/0 = auto: the
    ``P1_VERIFY_WORKERS`` env var, else ``os.cpu_count()``).  Takes
    effect on the next batch; an existing pool of a different size is
    drained and replaced lazily."""
    global _workers
    _workers = int(n) if n else None


def verify_workers() -> int:
    """The resolved worker count batches will use."""
    if _workers is not None:
        return max(1, _workers)
    env = os.environ.get("P1_VERIFY_WORKERS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return max(1, os.cpu_count() or 1)


def shutdown_verify_pool(cancel: bool = False) -> None:
    """Tear down the lazy worker pool (tests, interpreter exit).  Safe
    to call any time: in-flight batches fall back to in-thread
    verification when their futures are cancelled."""
    global _executor, _executor_size
    with _workers_lock:
        ex, _executor, _executor_size = _executor, None, 0
    if ex is not None:
        ex.shutdown(wait=not cancel, cancel_futures=cancel)


def _pool(size: int):
    """The shared verification executor, (re)built at ``size``."""
    global _executor, _executor_size
    with _workers_lock:
        if _executor is None or _executor_size != size:
            old = _executor
            from concurrent.futures import ThreadPoolExecutor

            _executor = ThreadPoolExecutor(
                max_workers=size, thread_name_prefix="sigverify"
            )
            _executor_size = size
        else:
            old = None
    if old is not None:
        old.shutdown(wait=False, cancel_futures=True)
    return _executor


def _verify_chunk(triples) -> bool:
    """Serial chunk worker: exact single-signature semantics.  Used by
    the wheel path (OpenSSL releases the GIL, so chunks verify in
    parallel) and as the cancellation fallback everywhere."""
    for pubkey, sig, message in triples:
        if len(pubkey) != PUBKEY_SIZE or len(sig) != SIG_SIZE:
            return False
        if not HAVE_CRYPTOGRAPHY:
            if not _py_ed25519.verify(pubkey, sig, message):
                return False
            continue
        try:
            ed25519.Ed25519PublicKey.from_public_bytes(pubkey).verify(
                sig, message
            )
        except (InvalidSignature, ValueError):
            return False
    return True


def _batch_worker():
    """``(backend_name, callable)`` one batch CHUNK runs through —
    resolved per batch so ``set_sig_backend`` takes effect immediately.
    Every worker computes the same subgroup-gated contract (or, for the
    wheel, exact per-signature serial checks, which is strictly
    stronger than 'batch TRUE implies serial TRUE')."""
    b = backend()
    if b == "device":
        try:
            from p1_tpu.hashx import ed25519_msm

            return "device", ed25519_msm.verify_batch_device
        except Exception as exc:  # jax missing/misconfigured: degrade
            log.warning(
                "device signature backend unavailable (%s); using the "
                "host ladder for this process",
                exc,
            )
            set_sig_backend(None)
            b = backend()
    if b == "cryptography":
        return "cryptography", _verify_chunk
    if b == "native":
        return "native", _native_ed25519.verify_batch
    return "pure-python", _py_ed25519.verify_batch


def _use_pool(n_chunks: int) -> bool:
    """Whether a batch's chunks go to the thread pool.  The wheel and
    native paths benefit: OpenSSL releases the GIL inside each verify
    and ctypes releases it around the native batch call, so chunks
    genuinely overlap on multi-core.  The pure-Python fallback holds
    the GIL for its whole MSM — dispatching it to workers buys no
    parallelism, just executor overhead and pool churn — so fallback
    chunks run in the calling thread; the device path schedules its own
    mesh and must not be double-dispatched.  Tests monkeypatch this to
    force the pool and exercise its shutdown/cancellation machinery
    without the wheel."""
    return (
        backend() in ("cryptography", "native")
        and n_chunks > 1
        and verify_workers() > 1
    )


def _warn_fallback_once() -> None:
    """One-time cost-model warning when batches run on the pure-Python
    rung, naming the FASTEST backend this host could offer instead —
    so no-wheel numbers are never read as regressions, and an operator
    who merely lacks the toolchain learns the native rung exists."""
    global _fallback_warned
    if _fallback_warned:
        return
    _fallback_warned = True
    from p1_tpu.hashx.perf_record import RECORDED_SIG_NATIVE_MS

    if _sig_backend == "pure-python":
        fastest = (
            "the pure-Python fallback was FORCED via "
            "--sig-backend/P1_SIG_BACKEND; 'auto' would pick a faster rung"
        )
    elif _native_ed25519.available():
        # Reachable only by forcing pure-python off a native-capable
        # host, handled above — kept for the belt-and-braces case.
        fastest = "the native C++ engine is available on this host"
    else:
        fastest = (
            "fastest available here; the native C++ engine "
            f"(~{RECORDED_SIG_NATIVE_MS:.2f} ms/sig batched, recorded) "
            "needs only a C++ toolchain, and the `cryptography` wheel "
            "(~0.1 ms/sig) neither"
        )
    log.warning(
        "pure-Python Ed25519 fallback is the active backend for batch "
        "verification: ~%.1f ms/signature serial, ~%.2f ms batched "
        "(recorded on the 1-vCPU bench host) — %s.  Numbers measured on "
        "this rung are NOT comparable to the wheel- or native-based "
        "records in docs/PERF.md.",
        _py_ed25519.RECORDED_SERIAL_MS,
        _py_ed25519.RECORDED_BATCH_MS,
        fastest,
    )


def verify_batch(triples) -> bool:
    """True only if EVERY (pubkey, sig, message) triple is serially
    valid (batch acceptance implies serial acceptance).

    False means "not proven": usually a bad signature, but the gated
    batches also reject torsion-crafted inputs the serial equation
    tolerates (_ed25519.py's docstring) — use ``first_invalid`` to
    settle a failed batch with serial-identical semantics.
    Dispatch (``_batch_worker``): wheel → per-signature verifies
    chunked across the worker pool (exact serial semantics, parallel on
    multi-core); native → the C++ subgroup-gated batch per chunk, also
    pool-parallel (ctypes releases the GIL); pure-Python → the fallback
    MSM per chunk in the calling thread; device (opt-in) → the JAX mesh
    MSM (hashx/ed25519_msm.py).
    """
    triples = list(triples)
    if not triples:
        return True
    STATS.batches += 1
    STATS.batched += len(triples)
    which, worker = _batch_worker()
    if which == "pure-python":
        _warn_fallback_once()
    if len(triples) < BATCH_MIN:
        STATS.batched -= len(triples)  # accounted as serial below
        return _verify_serial_counted(triples)
    STATS.backends[which] += len(triples)
    chunks = [
        triples[i : i + BATCH_CHUNK]
        for i in range(0, len(triples), BATCH_CHUNK)
    ]
    if not _use_pool(len(chunks)):
        return all(worker(chunk) for chunk in chunks)
    n = verify_workers()
    from concurrent.futures import CancelledError

    STATS.pool_dispatches += 1
    pool = _pool(n)
    futures = []
    for chunk in chunks:
        try:
            futures.append(pool.submit(worker, chunk))
        except RuntimeError:
            # Pool shut down mid-submission: the rest verify in-thread.
            futures.append(None)
    ok = True
    for fut, chunk in zip(futures, chunks):
        if fut is None:
            ok &= worker(chunk)
            continue
        try:
            ok &= fut.result()
        except (CancelledError, RuntimeError):
            # Pool torn down mid-batch (shutdown, interpreter exit):
            # finish in this thread — the answer must not depend on
            # executor lifecycle.
            ok &= worker(chunk)
    return ok


def _verify_serial_counted(triples) -> bool:
    for pubkey, sig, message in triples:
        if not verify(pubkey, sig, message):
            return False
    return True


def first_invalid(triples) -> int | None:
    """Index of the FIRST triple serial verification rejects, or None.

    None after a failed ``verify_batch`` is a legitimate answer —
    batch False does not imply a serial reject (the fallback's subgroup
    gate also turns away torsion-crafted inputs the serial equation
    tolerates), so callers must treat None as "all serially valid".
    Left-first scan: a sub-batch that PASSES proves all its members
    serially valid and is skipped wholesale; a sub-batch that fails is
    split, and windows of ≤ BATCH_MIN are settled one by one with
    ``verify`` itself — so the identified signature (or the conclusion
    that none exists) is exactly what the serial path would produce.
    The old bisection assumed "batch failed ⇒ a serial reject inside",
    which the gate broke: a torsion reject in one half would steer the
    search away from a genuinely bad signature in the other.
    """
    triples = list(triples)

    def scan(lo: int, hi: int, known_failed: bool) -> int | None:
        if hi - lo <= BATCH_MIN:
            for i in range(lo, hi):
                if not verify(*triples[i]):
                    return i
            return None
        if not known_failed and verify_batch(triples[lo:hi]):
            return None
        mid = (lo + hi) // 2
        found = scan(lo, mid, False)
        return found if found is not None else scan(mid, hi, False)

    # Callers reach here right after a failed full batch: don't re-run it.
    return scan(0, len(triples), True)


def __getattr__(name: str):
    # Round-15 compat: ``BACKEND`` was a module constant when the
    # ladder had two fixed rungs; with lazy native resolution it is a
    # function (``backend()``).  Old importers keep working — the
    # attribute read resolves the ladder at that moment.
    if name == "BACKEND":
        return backend()
    raise AttributeError(name)
