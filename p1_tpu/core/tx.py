"""Minimal transaction type with deterministic serialization + ownership.

Capability parity: the reference has a mempool of pending transactions feeding
block assembly (BASELINE.json:5).  The exact reference tx format is unknown
(reference checkout unavailable — SURVEY.md §0), so this is a deliberately
simple account-model transfer: sender/recipient ids, amount, fee, and a
sender-sequence number for uniqueness.  Deterministic big-endian serialization
with length-prefixed ids; txid = SHA-256d of the serialization.

Ownership (round 4): a non-coinbase transaction carries the sender's Ed25519
public key and a signature over ``signing_bytes()`` — the five core fields
PLUS the ``chain`` tag (the target chain's genesis hash), so a signature
authorizes one spend on one chain: without the tag, a spend observed on a
difficulty-16 chain could be replayed byte-identically against the same
account's funds on a difficulty-20 chain.  Consensus
(p1_tpu/chain/validate.py) checks the tag against the chain's genesis and
the mempool against its configured chain, and both require
``verify_signature()`` — the sender id must be the key's fingerprint
(p1_tpu/core/keys.py) and the signature must check out, so only the key
holder can spend from a fingerprint account.  Coinbases stay unsigned (they
are minted by consensus per chain, not spent by an owner) and MUST carry
empty pubkey/sig/chain.  The txid commits to the signature too (like a
pre-segwit Bitcoin txid commits to scriptSig); Ed25519 signing is
deterministic, so an honest signer produces one txid per transaction.

Canonical-encoding cache: like ``BlockHeader``, the frozen instance
memoizes ``serialize()``, ``signing_bytes()`` and ``txid()`` via
``object.__setattr__`` (non-field slots — equality, hashing, and
``dataclasses.replace`` ignore them, so ``transfer()``'s replace-with-sig
starts clean), and ``deserialize``/``deserialize_prefix`` seed the cache
with the exact wire bytes.  The layout round-trips byte-identically
(length-prefixed fields, fixed-width integers — tested), so a transaction
is packed at most once per process no matter how many times gossip,
block assembly, persistence, and relay re-serialize it.
"""

from __future__ import annotations

import dataclasses
import struct

from p1_tpu.core import keys as _keys
from p1_tpu.core import sigcache as _sigcache

_MAX_ID_LEN = 255
_NUMS = struct.Struct(">QQQ")

#: Reserved sender id marking a block-reward (coinbase) transaction.  A
#: coinbase is what gives each miner's candidate block a distinct identity:
#: recipient = the miner's id, seq = the block height, so two miners working
#: on the same tip produce different merkle roots and therefore different
#: headers — concurrent mining yields genuinely competing blocks instead of
#: every node re-deriving the identical one.
COINBASE_SENDER = "coinbase"
BLOCK_REWARD = 50


@dataclasses.dataclass(frozen=True)
class Transaction:
    sender: str
    recipient: str
    amount: int
    fee: int
    seq: int  # per-sender sequence number (uniqueness / replay protection)
    pubkey: bytes = b""  # sender's Ed25519 public key (empty for coinbase)
    sig: bytes = b""  # Ed25519 signature over signing_bytes()
    chain: bytes = b""  # target chain's genesis hash (empty for coinbase)

    def __post_init__(self) -> None:
        for name in ("sender", "recipient"):
            raw = getattr(self, name).encode("utf-8")
            if not 0 < len(raw) <= _MAX_ID_LEN:
                raise ValueError(f"{name} must encode to 1..{_MAX_ID_LEN} bytes")
        for name in ("amount", "fee", "seq"):
            v = getattr(self, name)
            if not 0 <= v <= 0xFFFFFFFFFFFFFFFF:
                raise ValueError(f"{name}={v} out of uint64 range")
        for name in ("pubkey", "sig", "chain"):
            if len(getattr(self, name)) > _MAX_ID_LEN:
                raise ValueError(f"{name} exceeds {_MAX_ID_LEN} bytes")

    def signing_bytes(self) -> bytes:
        """What the sender signs: the five core fields plus the chain tag
        (everything except the proof itself) — signatures are chain-bound."""
        raw = self.__dict__.get("_signing")
        if raw is None:
            s = self.sender.encode("utf-8")
            r = self.recipient.encode("utf-8")
            raw = b"".join(
                (
                    struct.pack(">B", len(s)),
                    s,
                    struct.pack(">B", len(r)),
                    r,
                    struct.pack(">QQQ", self.amount, self.fee, self.seq),
                    struct.pack(">B", len(self.chain)),
                    self.chain,
                )
            )
            object.__setattr__(self, "_signing", raw)
        return raw

    def serialize(self) -> bytes:
        raw = self.__dict__.get("_raw")
        if raw is None:
            raw = b"".join(
                (
                    self.signing_bytes(),
                    struct.pack(">B", len(self.pubkey)),
                    self.pubkey,
                    struct.pack(">B", len(self.sig)),
                    self.sig,
                )
            )
            object.__setattr__(self, "_raw", raw)
        return raw

    @classmethod
    def deserialize(cls, data: bytes) -> "Transaction":
        tx, rest = cls.deserialize_prefix(data)
        if rest:
            raise ValueError(f"{len(rest)} trailing bytes after transaction")
        return tx

    @classmethod
    def deserialize_prefix(cls, data: bytes) -> tuple["Transaction", bytes]:
        """Parse one transaction off the front of ``data``; return (tx, rest).

        Offset-walking hot path that builds the instance directly: the
        wire format structurally guarantees every ``__post_init__``
        constraint (one-byte length prefixes cap the variable fields at
        255, ``>QQQ`` caps the integers at uint64, utf-8 decode/encode
        round-trips byte-identically) except non-empty ids, which are
        checked explicitly — so gossip ingest never re-validates what
        the framing already proves.
        """
        buf = bytes(data)
        total = len(buf)
        off = 0
        try:
            n = buf[off]
            s = buf[off + 1 : off + 1 + n]
            off += 1 + n
            n = buf[off]
            r = buf[off + 1 : off + 1 + n]
            off += 1 + n
            amount, fee, seq = _NUMS.unpack_from(buf, off)
            off += 24
            n = buf[off]
            chain = buf[off + 1 : off + 1 + n]
            off += 1 + n
            signing_end = off
            n = buf[off]
            pubkey = buf[off + 1 : off + 1 + n]
            off += 1 + n
            n = buf[off]
            sig = buf[off + 1 : off + 1 + n]
            off += 1 + n
        except (IndexError, struct.error):
            raise ValueError("truncated transaction") from None
        if off > total:
            # A short final slice advances ``off`` past the end without
            # tripping the index probes above.
            raise ValueError("truncated transaction")
        if not s:
            raise ValueError("sender must encode to 1..255 bytes")
        if not r:
            raise ValueError("recipient must encode to 1..255 bytes")
        tx = object.__new__(cls)
        tx.__dict__.update(
            sender=s.decode("utf-8"),
            recipient=r.decode("utf-8"),
            amount=amount,
            fee=fee,
            seq=seq,
            pubkey=pubkey,
            sig=sig,
            chain=chain,
            # Seed the encoding caches with exactly the bytes consumed:
            # the layout round-trips byte-identically, so they ARE
            # canonical — and ``signing_bytes`` is by construction the
            # wire prefix through the chain tag, so signature checks on
            # ingested transactions never re-pack either.
            _raw=buf[:off] if off < total else buf,
            _signing=buf[:signing_end],
        )
        return tx, buf[off:]

    def txid(self) -> bytes:
        digest = self.__dict__.get("_txid")
        if digest is None:
            from p1_tpu.core.hashutil import sha256d

            digest = sha256d(self.serialize())
            object.__setattr__(self, "_txid", digest)
        return digest

    @property
    def is_coinbase(self) -> bool:
        return self.sender == COINBASE_SENDER

    def verify_signature(self, cache=None) -> bool:
        """True iff this transaction proves ownership of its sender account.

        Coinbase: must be bare (no pubkey/sig/chain) — minted, not spent.
        Transfer: sender id must be the carried pubkey's fingerprint and the
        signature must verify over ``signing_bytes()`` (which commits to the
        ``chain`` tag — whether the tag names the RIGHT chain is the
        caller's contextual check).  Memoized through the verify-once
        signature cache (core/sigcache.py — ``cache`` names one
        explicitly, None uses the process default) so gossip admission +
        block validation + resurrection re-checks are O(1) after the
        first; the txid key commits to every byte the check depends on.
        """
        if self.is_coinbase:
            return not self.pubkey and not self.sig and not self.chain
        if self.sender != _keys.account_id_or_none(self.pubkey):
            return False
        if cache is None:
            cache = _sigcache.DEFAULT
        txid = self.txid()
        if cache.hit(txid, self.pubkey, self.sig):
            return True
        if not _keys.verify(self.pubkey, self.sig, self.signing_bytes()):
            return False
        cache.add(txid, self.pubkey, self.sig)
        return True

    @classmethod
    def transfer(
        cls,
        key: "_keys.Keypair",
        recipient: str,
        amount: int,
        fee: int,
        seq: int,
        chain: bytes = b"",
    ) -> "Transaction":
        """Build and sign a spend from ``key``'s account, bound to the
        chain whose genesis hash is ``chain`` (consensus rejects transfers
        whose tag names a different chain)."""
        unsigned = cls(key.account, recipient, amount, fee, seq, chain=chain)
        sig = key.sign(unsigned.signing_bytes())
        return dataclasses.replace(unsigned, pubkey=key.pubkey, sig=sig)

    @classmethod
    def coinbase(
        cls, miner_id: str, height: int, reward: int = BLOCK_REWARD
    ) -> "Transaction":
        """The block-reward transaction for ``miner_id`` at ``height``.

        seq = height makes the coinbase (and with it the merkle root) unique
        per height even for the same miner; miner_id distinguishes miners.
        """
        return cls(COINBASE_SENDER, miner_id, reward, 0, height)
