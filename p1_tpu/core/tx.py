"""Minimal transaction type with deterministic serialization.

Capability parity: the reference has a mempool of pending transactions feeding
block assembly (BASELINE.json:5).  The exact reference tx format is unknown
(reference checkout unavailable — SURVEY.md §0), so this is a deliberately
simple account-model transfer: sender/recipient ids, amount, fee, and a
sender-sequence number for uniqueness.  Deterministic big-endian serialization
with length-prefixed ids; txid = SHA-256d of the serialization.
"""

from __future__ import annotations

import dataclasses
import struct

_MAX_ID_LEN = 255

#: Reserved sender id marking a block-reward (coinbase) transaction.  A
#: coinbase is what gives each miner's candidate block a distinct identity:
#: recipient = the miner's id, seq = the block height, so two miners working
#: on the same tip produce different merkle roots and therefore different
#: headers — concurrent mining yields genuinely competing blocks instead of
#: every node re-deriving the identical one.
COINBASE_SENDER = "coinbase"
BLOCK_REWARD = 50


@dataclasses.dataclass(frozen=True)
class Transaction:
    sender: str
    recipient: str
    amount: int
    fee: int
    seq: int  # per-sender sequence number (uniqueness / replay protection)

    def __post_init__(self) -> None:
        for name in ("sender", "recipient"):
            raw = getattr(self, name).encode("utf-8")
            if not 0 < len(raw) <= _MAX_ID_LEN:
                raise ValueError(f"{name} must encode to 1..{_MAX_ID_LEN} bytes")
        for name in ("amount", "fee", "seq"):
            v = getattr(self, name)
            if not 0 <= v <= 0xFFFFFFFFFFFFFFFF:
                raise ValueError(f"{name}={v} out of uint64 range")

    def serialize(self) -> bytes:
        s = self.sender.encode("utf-8")
        r = self.recipient.encode("utf-8")
        return b"".join(
            (
                struct.pack(">B", len(s)),
                s,
                struct.pack(">B", len(r)),
                r,
                struct.pack(">QQQ", self.amount, self.fee, self.seq),
            )
        )

    @classmethod
    def deserialize(cls, data: bytes) -> "Transaction":
        tx, rest = cls.deserialize_prefix(data)
        if rest:
            raise ValueError(f"{len(rest)} trailing bytes after transaction")
        return tx

    @classmethod
    def deserialize_prefix(cls, data: bytes) -> tuple["Transaction", bytes]:
        """Parse one transaction off the front of ``data``; return (tx, rest)."""

        def take(buf: bytes, n: int) -> tuple[bytes, bytes]:
            if len(buf) < n:
                raise ValueError("truncated transaction")
            return buf[:n], buf[n:]

        lb, data = take(data, 1)
        s, data = take(data, lb[0])
        lb, data = take(data, 1)
        r, data = take(data, lb[0])
        nums, data = take(data, 24)
        amount, fee, seq = struct.unpack(">QQQ", nums)
        return (
            cls(s.decode("utf-8"), r.decode("utf-8"), amount, fee, seq),
            data,
        )

    def txid(self) -> bytes:
        from p1_tpu.core.hashutil import sha256d

        return sha256d(self.serialize())

    @property
    def is_coinbase(self) -> bool:
        return self.sender == COINBASE_SENDER

    @classmethod
    def coinbase(
        cls, miner_id: str, height: int, reward: int = BLOCK_REWARD
    ) -> "Transaction":
        """The block-reward transaction for ``miner_id`` at ``height``.

        seq = height makes the coinbase (and with it the merkle root) unique
        per height even for the same miner; miner_id distinguishes miners.
        """
        return cls(COINBASE_SENDER, miner_id, reward, 0, height)
