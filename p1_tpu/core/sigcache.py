"""Verify-once signature cache: the tx-level Ed25519 memo (round 8).

Bitcoin Core's sigcache exists because a node verifies most signatures
TWICE on the happy path — once at mempool admission, once when the block
carrying the transaction connects.  Same here: relay validation, block
connect, reorg resurrection, and compact-block reconstruction all re-ask
the same question.  This cache answers it once per process:

- **Keyed by (txid, pubkey, sig)** — the txid already commits to the
  exact pubkey/sig/message bytes (SHA-256d over the full serialization),
  so a hit IS the transaction whose ownership proof was checked; pubkey
  and sig are folded in anyway so the key stands on collision resistance
  twice over.  Keys are 16-byte digests **salted per process**
  (``os.urandom``): an attacker who can predict cache keys could try to
  engineer digest collisions offline; with a salt the keyspace is fresh
  every boot (the same reason Bitcoin salts its sigcache).
- **Successes only.**  A negative result is never cached: failure is the
  rare hostile case, re-verifying it costs the attacker more than us,
  and a poisoned negative entry could censor a valid transaction.
- **Bounded LRU**, and the node charges ``bytes_used`` to the overload
  memory gauge (node/governor.py) so SHED accounting stays honest.

Single-threaded by design: consult/populate happens on the node's event
loop (admission, block connect); the batch-verification worker threads
never touch the cache — they hand results back to the loop thread, which
populates it.
"""

from __future__ import annotations

import collections
import hashlib
import os

#: Default capacity.  At ~120 bytes of accounted cost per entry this is
#: a ~7.9 MB ceiling — two orders of magnitude below the body-cache
#: terms the memory gauge tracks, but charged all the same.
DEFAULT_MAX_ENTRIES = 65_536

#: Accounted bytes per entry: 16-byte digest + bytes-object and
#: OrderedDict slot overhead, rounded up.  An estimate (CPython doesn't
#: expose exact dict internals), kept deliberately pessimistic so the
#: gauge never under-charges.
ENTRY_COST = 120


class SignatureCache:
    """Bounded, salted, verify-once cache for transaction signatures."""

    def __init__(self, max_entries: int = DEFAULT_MAX_ENTRIES):
        self.max_entries = int(max_entries)
        self._salt = os.urandom(16)
        self._entries: collections.OrderedDict[bytes, None] = (
            collections.OrderedDict()
        )
        self.hits = 0
        self.misses = 0

    def _key(self, txid: bytes, pubkey: bytes, sig: bytes) -> bytes:
        h = hashlib.sha256(self._salt)
        h.update(txid)
        h.update(pubkey)
        h.update(sig)
        return h.digest()[:16]

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def bytes_used(self) -> int:
        """What this cache charges the node's accounted memory gauge."""
        return len(self._entries) * ENTRY_COST

    def hit(self, txid: bytes, pubkey: bytes, sig: bytes) -> bool:
        """True iff this exact signature was proven valid earlier this
        process (LRU-refreshes the entry); counts a miss otherwise."""
        key = self._key(txid, pubkey, sig)
        if key in self._entries:
            self._entries.move_to_end(key)
            self.hits += 1
            return True
        self.misses += 1
        return False

    def add(self, txid: bytes, pubkey: bytes, sig: bytes) -> None:
        """Record a PROVEN-VALID signature (callers only ever add after
        a successful backend verify or batch membership)."""
        key = self._key(txid, pubkey, sig)
        self._entries[key] = None
        self._entries.move_to_end(key)
        while len(self._entries) > self.max_entries:
            self._entries.popitem(last=False)

    def snapshot(self) -> dict:
        """The ``status()["validation"]`` cache block."""
        return {
            "hits": self.hits,
            "misses": self.misses,
            "entries": len(self._entries),
            "bytes": self.bytes_used,
        }


#: Process-default cache: what ``Transaction.verify_signature`` uses
#: when no explicit cache is wired in (standalone tools, light clients,
#: tests building bare Chains).  A Node owns its OWN instance so its
#: hit/miss telemetry isn't polluted by co-resident nodes in one
#: process (multi-node tests, `p1 net`).
DEFAULT = SignatureCache()
