"""Deterministic genesis block.

Capability parity: "genesis block, difficulty=16" (BASELINE.json:7).  The
genesis block is fixed per chain configuration: zero prev-hash, no
transactions, a fixed timestamp, nonce 0.  Genesis is exempt from the PoW
check (it anchors the chain by identity, not by work) — validation in
``p1_tpu.chain`` special-cases height 0.

Chain identity = genesis hash.  A fixed-difficulty chain's genesis is a
pure function of the difficulty; a retargeting chain (core/retarget.py)
additionally **commits the rule's parameters into the genesis merkle
field**, so two nodes that disagree on (window, spacing, max_adjust) are
simply on different chains — the HELLO handshake refuses the connection
and chain-bound signatures refuse the replay, with no extra protocol
surface.  (The genesis block has no transactions, so its merkle field is
free to carry the commitment; height-0 blocks are validated by identity,
never by ``check_block``'s merkle recomputation.)
"""

from __future__ import annotations

import functools
import struct

from p1_tpu.core.block import EMPTY_MERKLE_ROOT, Block
from p1_tpu.core.header import BlockHeader
from p1_tpu.core.retarget import RetargetRule

GENESIS_VERSION = 1
GENESIS_TIMESTAMP = 1735689600  # 2025-01-01T00:00:00Z, fixed forever
#: v2: the commitment gained max_step (the forward-dating bound) — a
#: chain with a different cap is a different chain.
_RETARGET_TAG = b"p1-retarget-v2"


@functools.lru_cache(maxsize=256)
def make_genesis(
    difficulty: int, retarget: RetargetRule | None = None
) -> Block:
    if retarget is None:
        merkle = EMPTY_MERKLE_ROOT
    else:
        from p1_tpu.core.hashutil import sha256d

        merkle = sha256d(
            _RETARGET_TAG
            + struct.pack(
                ">IIII",
                retarget.window,
                retarget.spacing,
                retarget.max_adjust,
                retarget.max_step,
            )
        )
    header = BlockHeader(
        version=GENESIS_VERSION,
        prev_hash=bytes(32),
        merkle_root=merkle,
        timestamp=GENESIS_TIMESTAMP,
        difficulty=difficulty,
        nonce=0,
    )
    return Block(header, ())


@functools.lru_cache(maxsize=256)
def genesis_hash(
    difficulty: int, retarget: RetargetRule | None = None
) -> bytes:
    """The chain id: genesis block hash for a chain configuration
    (memoized — it is the signing-domain tag of every transfer, checked
    per tx)."""
    return make_genesis(difficulty, retarget).block_hash()
