"""Deterministic genesis block.

Capability parity: "genesis block, difficulty=16" (BASELINE.json:7).  The
genesis block is fixed per (difficulty,) chain configuration: zero prev-hash,
no transactions, a fixed timestamp, nonce 0.  Genesis is exempt from the PoW
check (it anchors the chain by identity, not by work) — validation in
``p1_tpu.chain`` special-cases height 0.
"""

from __future__ import annotations

import functools

from p1_tpu.core.block import EMPTY_MERKLE_ROOT, Block
from p1_tpu.core.header import BlockHeader

GENESIS_VERSION = 1
GENESIS_TIMESTAMP = 1735689600  # 2025-01-01T00:00:00Z, fixed forever


def make_genesis(difficulty: int) -> Block:
    header = BlockHeader(
        version=GENESIS_VERSION,
        prev_hash=bytes(32),
        merkle_root=EMPTY_MERKLE_ROOT,
        timestamp=GENESIS_TIMESTAMP,
        difficulty=difficulty,
        nonce=0,
    )
    return Block(header, ())


@functools.lru_cache(maxsize=256)
def genesis_hash(difficulty: int) -> bytes:
    """The chain id: genesis block hash for a difficulty (memoized — it is
    the signing-domain tag of every transfer, checked per tx)."""
    return make_genesis(difficulty).block_hash()
