"""Pure-Python RFC 8032 Ed25519 — the no-wheel fallback for core/keys.py.

The ``cryptography`` package is an *optional* accelerator: some
deployment images (including CI sandboxes with no egress) don't carry
the wheel, and a missing optional dependency must never make the core
package unimportable.  This module is the slow-but-correct substitute:
a direct transcription of RFC 8032 §5.1 (edwards25519, SHA-512,
cofactored equation checked in the cofactorless form ``[S]B = R + [k]A``
that both OpenSSL and the RFC test vectors accept), producing
byte-identical keys and signatures to the wheel — Ed25519 signing is
fully deterministic, so the two backends are interchangeable per key.

Performance: a few milliseconds per sign/verify (extended-coordinate
double-and-add over Python ints) vs ~100 µs native.  Two things keep
that affordable on the hot paths: the tx-level verify-once cache
(core/sigcache.py) pays the cost once per transaction per process, and
``verify_batch`` below amortizes what DOES have to be verified —
untrusted-path validation (`--revalidate-store`, foreign stores, deep
sync) verifies whole windows of signatures in one multi-scalar
multiplication instead of one double-and-add ladder each (measured
7.4–8.4× per signature at window sizes 256–4096 on the 1-vCPU bench
host; benchmarks/sig_verify.py).

Batch semantics, stated precisely (the "Taming the many EdDSAs"
trade-off): the batch checks the COFACTORED equation ``[8][Σ z_i s_i]B
= [8]Σ z_i R_i + [8]Σ z_i k_i A_i`` with per-process-random 128-bit
coefficients ``z_i`` — the only linear form that is sound to batch.
Every signature the serial (cofactorless) check accepts also passes the
batch, and any signature failing the cofactored equation makes the
batch fail with probability 1 − 2⁻¹²⁸, after which callers bisect down
to the serial verdict (``keys.first_invalid``) — so accept/reject and
error text match the serial path for every honestly-generated or
randomly-corrupted input (property-tested at every position,
tests/test_sigbatch.py).  The one reachable divergence: a signer who
deliberately crafts a small-order torsion component into their OWN
public key or nonce point can make a signature the serial check rejects
and the batch accepts.  Honest keys are torsion-free by construction
(clamped scalars are ≡ 0 mod 8), the craft risks only the crafter's own
account, and random corruption lands there with probability ~2⁻²⁵⁰ —
the same superset Zcash consensus standardized on when it adopted
batched Ed25519.
"""

from __future__ import annotations

import functools
import hashlib
import secrets

#: Recorded fallback verify costs on the 1-vCPU bench host (2026-08-04,
#: benchmarks/sig_verify.py) — what keys.py's one-time "fallback active
#: for a batch path" warning names, so CI-without-wheel numbers are
#: never mistaken for regressions against the wheel-based records.
RECORDED_SERIAL_MS = 3.1
RECORDED_BATCH_MS = 0.36

_P = 2**255 - 19  # field prime
_Q = 2**252 + 27742317777372353535851937790883648493  # group order
_D = (-121665 * pow(121666, _P - 2, _P)) % _P  # curve constant

# Base point B (RFC 8032 §5.1), extended homogeneous (X, Y, Z, T).
_BY = (4 * pow(5, _P - 2, _P)) % _P
_BX = 15112221349535400772501151409588531511454012693041857206046113283949847762202
_B = (_BX, _BY, 1, (_BX * _BY) % _P)
_IDENT = (0, 1, 1, 0)

# sqrt(-1) mod p, for point decompression (p ≡ 5 mod 8).
_SQRT_M1 = pow(2, (_P - 1) // 4, _P)


def _sha512(data: bytes) -> bytes:
    return hashlib.sha512(data).digest()


def _pt_add(a, b):
    x1, y1, z1, t1 = a
    x2, y2, z2, t2 = b
    aa = (y1 - x1) * (y2 - x2) % _P
    bb = (y1 + x1) * (y2 + x2) % _P
    cc = 2 * t1 * t2 * _D % _P
    dd = 2 * z1 * z2 % _P
    e, f, g, h = bb - aa, dd - cc, dd + cc, bb + aa
    return (e * f % _P, g * h % _P, f * g % _P, e * h % _P)


def _pt_double(a):
    x1, y1, z1, _ = a
    aa = x1 * x1 % _P
    bb = y1 * y1 % _P
    cc = 2 * z1 * z1 % _P
    h = aa + bb
    e = (h - (x1 + y1) * (x1 + y1)) % _P
    g = aa - bb
    f = cc + g
    return (e * f % _P, g * h % _P, f * g % _P, e * h % _P)


def _pt_mul(s: int, pt):
    out = _IDENT
    while s > 0:
        if s & 1:
            out = _pt_add(out, pt)
        pt = _pt_double(pt)
        s >>= 1
    return out


def _pt_equal(a, b) -> bool:
    # Cross-multiply to compare projective points without inversions.
    x1, y1, z1, _ = a
    x2, y2, z2, _ = b
    return (x1 * z2 - x2 * z1) % _P == 0 and (y1 * z2 - y2 * z1) % _P == 0


def _pt_compress(pt) -> bytes:
    x, y, z, _ = pt
    zinv = pow(z, _P - 2, _P)
    x, y = x * zinv % _P, y * zinv % _P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def _recover_x(y: int, sign: int) -> int | None:
    if y >= _P:
        return None
    # RFC 8032 §5.1.3's combined inversion+square-root: x² = u/v with
    # u = y²−1, v = d·y²+1, and x = u·v³·(u·v⁷)^((p−5)/8) — ONE modular
    # exponentiation where the naive u·v⁻¹ then sqrt pays two.  Point
    # decompression is the per-signature fixed cost of batch
    # verification (R is unique per signature), so this halves its floor.
    y2 = y * y % _P
    u = (y2 - 1) % _P
    v = (_D * y2 + 1) % _P
    if u == 0:
        return None if sign else 0
    v3 = v * v % _P * v % _P
    x = u * v3 % _P * pow(u * v3 % _P * v3 % _P * v % _P, (_P - 5) // 8, _P) % _P
    vx2 = v * x % _P * x % _P
    if vx2 != u:
        if vx2 != _P - u:
            return None
        x = x * _SQRT_M1 % _P
    if x == 0 and sign:
        return None
    if (x & 1) != sign:
        x = _P - x
    return x


def _pt_decompress(raw: bytes):
    if len(raw) != 32:
        return None
    y = int.from_bytes(raw, "little")
    sign = y >> 255
    y &= (1 << 255) - 1
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % _P)


def _secret_expand(seed: bytes) -> tuple[int, bytes]:
    h = _sha512(seed)
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


def public_key(seed: bytes) -> bytes:
    """The 32-byte public key for a 32-byte private seed."""
    a, _ = _secret_expand(seed)
    return _pt_compress(_pt_mul(a, _B))


def sign(seed: bytes, message: bytes) -> bytes:
    """Deterministic RFC 8032 signature (64 bytes) over ``message``."""
    a, prefix = _secret_expand(seed)
    pub = _pt_compress(_pt_mul(a, _B))
    r = int.from_bytes(_sha512(prefix + message), "little") % _Q
    big_r = _pt_compress(_pt_mul(r, _B))
    k = int.from_bytes(_sha512(big_r + pub + message), "little") % _Q
    s = (r + k * a) % _Q
    return big_r + s.to_bytes(32, "little")


def verify(pubkey: bytes, sig: bytes, message: bytes) -> bool:
    """True iff ``sig`` is ``pubkey``'s valid signature over ``message``."""
    if len(pubkey) != 32 or len(sig) != 64:
        return False
    a_pt = _pt_decompress(pubkey)
    if a_pt is None:
        return False
    r_pt = _pt_decompress(sig[:32])
    if r_pt is None:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= _Q:
        return False
    k = int.from_bytes(_sha512(sig[:32] + pubkey + message), "little") % _Q
    return _pt_equal(_pt_mul(s, _B), _pt_add(r_pt, _pt_mul(k, a_pt)))


# -- batch verification (untrusted-path fast lane) -----------------------
#
# One multi-scalar multiplication over all (R_i, A_i, B) replaces 2n
# double-and-add ladders: Pippenger's bucket method costs roughly
# (bits/c)·(n + 2^c) point additions for the whole batch vs ~770
# additions per signature serially, so per-signature cost falls from
# ~3.1 ms to ~360–420 µs at window sizes 256–4096 on this host (the
# remaining floor is one R-point decompression per signature).  The
# equation checked and its exact relationship to serial verification
# are documented in the module docstring above.


@functools.lru_cache(maxsize=4096)
def _pubkey_point(pubkey: bytes):
    """Decompressed public-key point, cached: senders repeat across the
    transactions of a window (one account signs many spends), and a
    decompression costs two ~250-bit modular exponentiations.  R points
    are unique per signature and are never cached."""
    return _pt_decompress(pubkey)


def _msm(pairs) -> tuple:
    """Σ scalar·point over ``pairs`` (Pippenger bucket method).

    Scalars are plain non-negative integers — deliberately NOT reduced
    mod the group order by this function: R and A points supplied by a
    hostile signer may carry 8-torsion components, where arithmetic
    mod q is invalid.  The caller multiplies the result by the cofactor
    before comparing, which is what makes the mixed-width scalars here
    sound.
    """
    pairs = [(s, p) for s, p in pairs if s > 0]
    if not pairs:
        return _IDENT
    maxbits = max(s.bit_length() for s, _ in pairs)
    n = len(pairs)
    # Window width: minimize (maxbits/c)·(n + 2^(c+1)) — the point pass
    # is n adds per window, the running-sum bucket aggregation 2·2^c.
    c = min(
        range(2, 16),
        key=lambda w: -(-maxbits // w) * (n + (2 << w)),
    )
    nbuckets = 1 << c
    mask = nbuckets - 1
    result = _IDENT
    for shift in range(((maxbits + c - 1) // c) - 1, -1, -1):
        if result is not _IDENT:
            for _ in range(c):
                result = _pt_double(result)
        buckets = [None] * nbuckets
        base = shift * c
        for s, p in pairs:
            idx = (s >> base) & mask
            if idx:
                b = buckets[idx]
                buckets[idx] = p if b is None else _pt_add(b, p)
        # Running-sum aggregation: Σ idx·bucket[idx] with 2·(2^c) adds.
        running = None
        acc = None
        for idx in range(nbuckets - 1, 0, -1):
            b = buckets[idx]
            if b is not None:
                running = b if running is None else _pt_add(running, b)
            if running is not None:
                acc = running if acc is None else _pt_add(acc, running)
        if acc is not None:
            result = acc if result is _IDENT else _pt_add(result, acc)
    return result


def verify_batch(triples) -> bool:
    """True iff every ``(pubkey, sig, message)`` triple verifies, checked
    as ONE cofactored random-linear-combination equation (module
    docstring).  False means at least one signature is bad (up to the
    2⁻¹²⁸ soundness bound) — callers bisect to find which, so the
    per-signature verdict and error reporting stay the serial path's.
    """
    pairs = []  # (scalar, point) terms of the combination
    s_total = 0  # coefficient of the base point, mod Q (B has order Q)
    for pubkey, sig, message in triples:
        if len(pubkey) != 32 or len(sig) != 64:
            return False
        a_pt = _pubkey_point(bytes(pubkey))
        if a_pt is None:
            return False
        r_pt = _pt_decompress(sig[:32])
        if r_pt is None:
            return False
        s = int.from_bytes(sig[32:], "little")
        if s >= _Q:
            return False
        k = int.from_bytes(_sha512(sig[:32] + pubkey + message), "little") % _Q
        # Unpredictable per-batch coefficients: an adversary must not be
        # able to craft signatures whose errors cancel in the sum.
        z = secrets.randbits(128) | 1
        s_total = (s_total + z * s) % _Q
        pairs.append((z, r_pt))
        # z·k reduced mod Q: for a torsioned A the reduction perturbs the
        # sum only by a multiple of Q·A — a pure torsion term, which the
        # final cofactor multiplication clears anyway.  Keeps every MSM
        # scalar ≤ 253 bits instead of ~381.
        pairs.append((z * k % _Q, a_pt))
    if not pairs:
        return True
    # Check  Σ z_i·R_i + Σ z_i·k_i·A_i − (Σ z_i·s_i)·B == torsion,
    # i.e. the cofactor-cleared sum is the identity.
    if s_total:
        pairs.append((_Q - s_total, _B))
    total = _msm(pairs)
    for _ in range(3):  # multiply by the cofactor (8 = 2³)
        total = _pt_double(total)
    return _pt_equal(total, _IDENT)
