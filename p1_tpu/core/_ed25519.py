"""Pure-Python RFC 8032 Ed25519 — the no-wheel fallback for core/keys.py.

The ``cryptography`` package is an *optional* accelerator: some
deployment images (including CI sandboxes with no egress) don't carry
the wheel, and a missing optional dependency must never make the core
package unimportable.  This module is the slow-but-correct substitute:
a direct transcription of RFC 8032 §5.1 (edwards25519, SHA-512,
cofactored equation checked in the cofactorless form ``[S]B = R + [k]A``
that both OpenSSL and the RFC test vectors accept), producing
byte-identical keys and signatures to the wheel — Ed25519 signing is
fully deterministic, so the two backends are interchangeable per key.

Performance: a few milliseconds per sign/verify (extended-coordinate
double-and-add over Python ints) vs ~100 µs native.  Two things keep
that affordable on the hot paths: the tx-level verify-once cache
(core/sigcache.py) pays the cost once per transaction per process, and
``verify_batch`` below amortizes what DOES have to be verified —
untrusted-path validation (`--revalidate-store`, foreign stores, deep
sync) verifies whole windows of signatures in one multi-scalar
multiplication instead of one double-and-add ladder each
(benchmarks/sig_verify.py; see RECORDED_* below for the measured
per-signature costs on the 1-vCPU bench host).

Batch semantics, stated precisely: the batch accepts iff (a) every
``R_i`` and ``A_i`` point lies in the prime-order subgroup — checked
EXACTLY via ``[q]·point == identity`` (``_in_prime_subgroup``), cached
per pubkey, per signature for the unique ``R_i`` — and (b) the random
linear combination ``Σ z_i·([s_i]B − R_i − [k_i]A_i)`` with 128-bit
per-batch-random coefficients ``z_i`` is the identity.  With every
point torsion-free, each term of (b) is exactly the serial
(cofactorless) equation ``[S]B = R + [k]A``, so **batch acceptance
implies serial acceptance** of every triple up to the 2⁻¹²⁸ soundness
bound of the coefficients.  The converse does NOT hold: a signer who
plants a small-order torsion component in their own public key or
nonce point can build a signature the serial equation tolerates (the
torsion terms cancel) which the subgroup gate here rejects — callers
recover the exact serial verdict through ``keys.first_invalid``'s
serial confirmation, so the OUTCOME of every validation path is
byte-identical to serial verification for every input, honest or
crafted (property-tested, tests/test_sigbatch.py).

The subgroup gate is what keeps ONE validity rule on every node: the
cofactored-only batch (the "Taming the many EdDSAs" superset this
module previously shipped) accepts torsion-crafted signatures the
serial path — and every node running the ``cryptography`` wheel —
rejects, which a hostile signer could use to split wheel-less nodes
from wheel nodes deterministically.  The gate costs one scalar
multiplication by q per signature (the dominant per-signature batch
cost; windowed, ``_in_prime_subgroup``), which is why the fallback
batch gain is ~2× rather than the ~8× the ungated equation measured.
Honest signatures are torsion-free by construction, so the gate never
rejects honest input.
"""

from __future__ import annotations

import functools
import hashlib
import secrets

#: Recorded fallback verify costs on the 1-vCPU bench host (2026-08-04,
#: benchmarks/sig_verify.py) — what keys.py's one-time "fallback active
#: for a batch path" warning names, so CI-without-wheel numbers are
#: never mistaken for regressions against the wheel-based records.
RECORDED_SERIAL_MS = 3.2
RECORDED_BATCH_MS = 1.45

_P = 2**255 - 19  # field prime
_Q = 2**252 + 27742317777372353535851937790883648493  # group order
_D = (-121665 * pow(121666, _P - 2, _P)) % _P  # curve constant

# Base point B (RFC 8032 §5.1), extended homogeneous (X, Y, Z, T).
_BY = (4 * pow(5, _P - 2, _P)) % _P
_BX = 15112221349535400772501151409588531511454012693041857206046113283949847762202
_B = (_BX, _BY, 1, (_BX * _BY) % _P)
_IDENT = (0, 1, 1, 0)

# sqrt(-1) mod p, for point decompression (p ≡ 5 mod 8).
_SQRT_M1 = pow(2, (_P - 1) // 4, _P)


def _sha512(data: bytes) -> bytes:
    return hashlib.sha512(data).digest()


def _pt_add(a, b):
    x1, y1, z1, t1 = a
    x2, y2, z2, t2 = b
    aa = (y1 - x1) * (y2 - x2) % _P
    bb = (y1 + x1) * (y2 + x2) % _P
    cc = 2 * t1 * t2 * _D % _P
    dd = 2 * z1 * z2 % _P
    e, f, g, h = bb - aa, dd - cc, dd + cc, bb + aa
    return (e * f % _P, g * h % _P, f * g % _P, e * h % _P)


def _pt_double(a):
    x1, y1, z1, _ = a
    aa = x1 * x1 % _P
    bb = y1 * y1 % _P
    cc = 2 * z1 * z1 % _P
    h = aa + bb
    e = (h - (x1 + y1) * (x1 + y1)) % _P
    g = aa - bb
    f = cc + g
    return (e * f % _P, g * h % _P, f * g % _P, e * h % _P)


def _pt_mul(s: int, pt):
    out = _IDENT
    while s > 0:
        if s & 1:
            out = _pt_add(out, pt)
        pt = _pt_double(pt)
        s >>= 1
    return out


def _pt_equal(a, b) -> bool:
    # Cross-multiply to compare projective points without inversions.
    x1, y1, z1, _ = a
    x2, y2, z2, _ = b
    return (x1 * z2 - x2 * z1) % _P == 0 and (y1 * z2 - y2 * z1) % _P == 0


def _pt_compress(pt) -> bytes:
    x, y, z, _ = pt
    zinv = pow(z, _P - 2, _P)
    x, y = x * zinv % _P, y * zinv % _P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def _recover_x(y: int, sign: int) -> int | None:
    if y >= _P:
        return None
    # RFC 8032 §5.1.3's combined inversion+square-root: x² = u/v with
    # u = y²−1, v = d·y²+1, and x = u·v³·(u·v⁷)^((p−5)/8) — ONE modular
    # exponentiation where the naive u·v⁻¹ then sqrt pays two.  Point
    # decompression is the per-signature fixed cost of batch
    # verification (R is unique per signature), so this halves its floor.
    y2 = y * y % _P
    u = (y2 - 1) % _P
    v = (_D * y2 + 1) % _P
    if u == 0:
        return None if sign else 0
    v3 = v * v % _P * v % _P
    x = u * v3 % _P * pow(u * v3 % _P * v3 % _P * v % _P, (_P - 5) // 8, _P) % _P
    vx2 = v * x % _P * x % _P
    if vx2 != u:
        if vx2 != _P - u:
            return None
        x = x * _SQRT_M1 % _P
    if x == 0 and sign:
        return None
    if (x & 1) != sign:
        x = _P - x
    return x


def _pt_decompress(raw: bytes):
    if len(raw) != 32:
        return None
    y = int.from_bytes(raw, "little")
    sign = y >> 255
    y &= (1 << 255) - 1
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % _P)


def _secret_expand(seed: bytes) -> tuple[int, bytes]:
    h = _sha512(seed)
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


def public_key(seed: bytes) -> bytes:
    """The 32-byte public key for a 32-byte private seed."""
    a, _ = _secret_expand(seed)
    return _pt_compress(_pt_mul(a, _B))


def sign(seed: bytes, message: bytes) -> bytes:
    """Deterministic RFC 8032 signature (64 bytes) over ``message``."""
    a, prefix = _secret_expand(seed)
    pub = _pt_compress(_pt_mul(a, _B))
    r = int.from_bytes(_sha512(prefix + message), "little") % _Q
    big_r = _pt_compress(_pt_mul(r, _B))
    k = int.from_bytes(_sha512(big_r + pub + message), "little") % _Q
    s = (r + k * a) % _Q
    return big_r + s.to_bytes(32, "little")


def verify(pubkey: bytes, sig: bytes, message: bytes) -> bool:
    """True iff ``sig`` is ``pubkey``'s valid signature over ``message``."""
    if len(pubkey) != 32 or len(sig) != 64:
        return False
    a_pt = _pt_decompress(pubkey)
    if a_pt is None:
        return False
    r_pt = _pt_decompress(sig[:32])
    if r_pt is None:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= _Q:
        return False
    k = int.from_bytes(_sha512(sig[:32] + pubkey + message), "little") % _Q
    return _pt_equal(_pt_mul(s, _B), _pt_add(r_pt, _pt_mul(k, a_pt)))


# -- batch verification (untrusted-path fast lane) -----------------------
#
# One multi-scalar multiplication over all (R_i, A_i, B) replaces 2n
# double-and-add ladders: Pippenger's bucket method costs roughly
# (bits/c)·(n + 2^c) point additions for the whole batch vs ~770
# additions per signature serially.  The per-signature floor is the
# exact prime-subgroup check on R (one windowed scalar multiplication
# by q) plus one R-point decompression — see the module docstring for
# why the subgroup gate is not optional.

#: q in 4-bit windows, most-significant first, for ``_in_prime_subgroup``.
_Q_WINDOWS = tuple(
    (_Q >> (4 * i)) & 15 for i in reversed(range((_Q.bit_length() + 3) // 4))
)


def _in_prime_subgroup(pt) -> bool:
    """True iff ``pt`` lies in the prime-order subgroup, i.e. carries no
    small-order torsion component: ``[q]·pt == identity``, computed
    exactly (no probabilistic shortcut exists — the torsion group is
    Z/8, far too small for random-linear-combination soundness).  Fixed
    4-bit windows: 14 setup additions buy ~¼ the adds of plain
    double-and-add over the 253-bit q."""
    mults = [_IDENT, pt]
    for _ in range(14):
        mults.append(_pt_add(mults[-1], pt))
    acc = _IDENT
    for w in _Q_WINDOWS:
        for _ in range(4):
            acc = _pt_double(acc)
        if w:
            acc = _pt_add(acc, mults[w])
    return _pt_equal(acc, _IDENT)


@functools.lru_cache(maxsize=4096)
def _pubkey_point(pubkey: bytes):
    """``(point, in_prime_subgroup)`` for a compressed public key —
    ``(None, False)`` when undecodable.  Cached: senders repeat across
    the transactions of a window (one account signs many spends), and
    the subgroup check costs a scalar multiplication by q on top of the
    two ~250-bit exponentiations of decompression.  R points are unique
    per signature, so their checks are paid per signature, uncached."""
    pt = _pt_decompress(pubkey)
    if pt is None:
        return None, False
    return pt, _in_prime_subgroup(pt)


def _msm(pairs) -> tuple:
    """Σ scalar·point over ``pairs`` (Pippenger bucket method).

    Scalars are plain non-negative integers of any width; the caller
    may reduce them mod the group order q only where the paired point
    is proven to lie in the prime-order subgroup (``verify_batch``
    checks exactly that before building its pairs — for a point with a
    torsion component, mod-q scalar arithmetic would be invalid).
    """
    pairs = [(s, p) for s, p in pairs if s > 0]
    if not pairs:
        return _IDENT
    maxbits = max(s.bit_length() for s, _ in pairs)
    n = len(pairs)
    # Window width: minimize (maxbits/c)·(n + 2^(c+1)) — the point pass
    # is n adds per window, the running-sum bucket aggregation 2·2^c.
    c = min(
        range(2, 16),
        key=lambda w: -(-maxbits // w) * (n + (2 << w)),
    )
    nbuckets = 1 << c
    mask = nbuckets - 1
    result = _IDENT
    for shift in range(((maxbits + c - 1) // c) - 1, -1, -1):
        if result is not _IDENT:
            for _ in range(c):
                result = _pt_double(result)
        buckets = [None] * nbuckets
        base = shift * c
        for s, p in pairs:
            idx = (s >> base) & mask
            if idx:
                b = buckets[idx]
                buckets[idx] = p if b is None else _pt_add(b, p)
        # Running-sum aggregation: Σ idx·bucket[idx] with 2·(2^c) adds.
        running = None
        acc = None
        for idx in range(nbuckets - 1, 0, -1):
            b = buckets[idx]
            if b is not None:
                running = b if running is None else _pt_add(running, b)
            if running is not None:
                acc = running if acc is None else _pt_add(acc, running)
        if acc is not None:
            result = acc if result is _IDENT else _pt_add(result, acc)
    return result


def verify_batch(triples) -> bool:
    """True only if every ``(pubkey, sig, message)`` triple passes the
    SERIAL check (up to the 2⁻¹²⁸ soundness bound): subgroup-gated
    points plus one random-linear-combination equation (module
    docstring).  False does NOT imply a serial reject — the gate also
    rejects torsion-crafted inputs the serial equation tolerates — so
    callers settle a failed batch with ``keys.first_invalid``'s serial
    confirmation, keeping per-signature verdicts and error reporting
    exactly the serial path's.
    """
    pairs = []  # (scalar, point) terms of the combination
    s_total = 0  # coefficient of the base point, mod Q (B has order Q)
    for pubkey, sig, message in triples:
        if len(pubkey) != 32 or len(sig) != 64:
            return False
        a_pt, a_in_subgroup = _pubkey_point(bytes(pubkey))
        if not a_in_subgroup:
            return False
        r_pt = _pt_decompress(sig[:32])
        if r_pt is None or not _in_prime_subgroup(r_pt):
            return False
        s = int.from_bytes(sig[32:], "little")
        if s >= _Q:
            return False
        k = int.from_bytes(_sha512(sig[:32] + pubkey + message), "little") % _Q
        # Unpredictable per-batch coefficients: an adversary must not be
        # able to craft signatures whose errors cancel in the sum.
        z = secrets.randbits(128) | 1
        s_total = (s_total + z * s) % _Q
        pairs.append((z, r_pt))
        # z·k reduced mod Q is exact here: A is proven to have order q,
        # so the reduction shifts the term by a multiple of [q]A = O.
        # Keeps every MSM scalar ≤ 253 bits instead of ~381.
        pairs.append((z * k % _Q, a_pt))
    if not pairs:
        return True
    # Check  Σ z_i·R_i + Σ z_i·k_i·A_i − (Σ z_i·s_i)·B == identity.
    # Every point in the sum is proven torsion-free, so this cofactorless
    # comparison is exactly the serial equation's linear combination —
    # no cofactor clearing, nothing for torsion to hide in.
    if s_total:
        pairs.append((_Q - s_total, _B))
    return _pt_equal(_msm(pairs), _IDENT)
