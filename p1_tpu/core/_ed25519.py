"""Pure-Python RFC 8032 Ed25519 — the no-wheel fallback for core/keys.py.

The ``cryptography`` package is an *optional* accelerator: some
deployment images (including CI sandboxes with no egress) don't carry
the wheel, and a missing optional dependency must never make the core
package unimportable.  This module is the slow-but-correct substitute:
a direct transcription of RFC 8032 §5.1 (edwards25519, SHA-512,
cofactored equation checked in the cofactorless form ``[S]B = R + [k]A``
that both OpenSSL and the RFC test vectors accept), producing
byte-identical keys and signatures to the wheel — Ed25519 signing is
fully deterministic, so the two backends are interchangeable per key.

Performance: a few milliseconds per sign/verify (extended-coordinate
double-and-add over Python ints) vs ~100 µs native.  That is fine where
this runs: ``keys.verify`` memoizes verification per (pubkey, sig,
message), so each transaction pays the cost once per process no matter
how many times gossip, block validation, and reorg resurrection
re-check it.
"""

from __future__ import annotations

import hashlib

_P = 2**255 - 19  # field prime
_Q = 2**252 + 27742317777372353535851937790883648493  # group order
_D = (-121665 * pow(121666, _P - 2, _P)) % _P  # curve constant

# Base point B (RFC 8032 §5.1), extended homogeneous (X, Y, Z, T).
_BY = (4 * pow(5, _P - 2, _P)) % _P
_BX = 15112221349535400772501151409588531511454012693041857206046113283949847762202
_B = (_BX, _BY, 1, (_BX * _BY) % _P)
_IDENT = (0, 1, 1, 0)

# sqrt(-1) mod p, for point decompression (p ≡ 5 mod 8).
_SQRT_M1 = pow(2, (_P - 1) // 4, _P)


def _sha512(data: bytes) -> bytes:
    return hashlib.sha512(data).digest()


def _pt_add(a, b):
    x1, y1, z1, t1 = a
    x2, y2, z2, t2 = b
    aa = (y1 - x1) * (y2 - x2) % _P
    bb = (y1 + x1) * (y2 + x2) % _P
    cc = 2 * t1 * t2 * _D % _P
    dd = 2 * z1 * z2 % _P
    e, f, g, h = bb - aa, dd - cc, dd + cc, bb + aa
    return (e * f % _P, g * h % _P, f * g % _P, e * h % _P)


def _pt_double(a):
    x1, y1, z1, _ = a
    aa = x1 * x1 % _P
    bb = y1 * y1 % _P
    cc = 2 * z1 * z1 % _P
    h = aa + bb
    e = (h - (x1 + y1) * (x1 + y1)) % _P
    g = aa - bb
    f = cc + g
    return (e * f % _P, g * h % _P, f * g % _P, e * h % _P)


def _pt_mul(s: int, pt):
    out = _IDENT
    while s > 0:
        if s & 1:
            out = _pt_add(out, pt)
        pt = _pt_double(pt)
        s >>= 1
    return out


def _pt_equal(a, b) -> bool:
    # Cross-multiply to compare projective points without inversions.
    x1, y1, z1, _ = a
    x2, y2, z2, _ = b
    return (x1 * z2 - x2 * z1) % _P == 0 and (y1 * z2 - y2 * z1) % _P == 0


def _pt_compress(pt) -> bytes:
    x, y, z, _ = pt
    zinv = pow(z, _P - 2, _P)
    x, y = x * zinv % _P, y * zinv % _P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def _recover_x(y: int, sign: int) -> int | None:
    if y >= _P:
        return None
    x2 = (y * y - 1) * pow(_D * y * y + 1, _P - 2, _P) % _P
    if x2 == 0:
        return None if sign else 0
    x = pow(x2, (_P + 3) // 8, _P)
    if (x * x - x2) % _P != 0:
        x = x * _SQRT_M1 % _P
    if (x * x - x2) % _P != 0:
        return None
    if (x & 1) != sign:
        x = _P - x
    return x


def _pt_decompress(raw: bytes):
    if len(raw) != 32:
        return None
    y = int.from_bytes(raw, "little")
    sign = y >> 255
    y &= (1 << 255) - 1
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % _P)


def _secret_expand(seed: bytes) -> tuple[int, bytes]:
    h = _sha512(seed)
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


def public_key(seed: bytes) -> bytes:
    """The 32-byte public key for a 32-byte private seed."""
    a, _ = _secret_expand(seed)
    return _pt_compress(_pt_mul(a, _B))


def sign(seed: bytes, message: bytes) -> bytes:
    """Deterministic RFC 8032 signature (64 bytes) over ``message``."""
    a, prefix = _secret_expand(seed)
    pub = _pt_compress(_pt_mul(a, _B))
    r = int.from_bytes(_sha512(prefix + message), "little") % _Q
    big_r = _pt_compress(_pt_mul(r, _B))
    k = int.from_bytes(_sha512(big_r + pub + message), "little") % _Q
    s = (r + k * a) % _Q
    return big_r + s.to_bytes(32, "little")


def verify(pubkey: bytes, sig: bytes, message: bytes) -> bool:
    """True iff ``sig`` is ``pubkey``'s valid signature over ``message``."""
    if len(pubkey) != 32 or len(sig) != 64:
        return False
    a_pt = _pt_decompress(pubkey)
    if a_pt is None:
        return False
    r_pt = _pt_decompress(sig[:32])
    if r_pt is None:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= _Q:
        return False
    k = int.from_bytes(_sha512(sig[:32] + pubkey + message), "little") % _Q
    return _pt_equal(_pt_mul(s, _B), _pt_add(r_pt, _pt_mul(k, a_pt)))
