"""Block = header + transactions, with a SHA-256d merkle root.

Capability parity: block assembly from the mempool and header-chain
validation (BASELINE.json:5).  The merkle tree is the classic construction:
leaves are txids, pairs are combined with SHA-256d, an odd node is paired
with itself, and an empty transaction list has an all-zeros root.
"""

from __future__ import annotations

import dataclasses
import struct

from p1_tpu.core.header import HEADER_SIZE, BlockHeader
from p1_tpu.core.tx import Transaction

EMPTY_MERKLE_ROOT = bytes(32)
_U32 = struct.Struct(">I")


def merkle_root(txids: list[bytes]) -> bytes:
    """Classic duplicate-last-odd-leaf merkle tree.

    The duplication means ``[t1,t2,t3]`` and ``[t1,t2,t3,t3]`` share a root
    (the CVE-2012-2459 malleability); chain validation therefore rejects
    blocks containing duplicate txids — see p1_tpu.chain.
    """
    if not txids:
        return EMPTY_MERKLE_ROOT
    from p1_tpu.core.hashutil import sha256d

    level = list(txids)
    while len(level) > 1:
        if len(level) % 2:
            level.append(level[-1])
        level = [
            sha256d(level[i] + level[i + 1]) for i in range(0, len(level), 2)
        ]
    return level[0]


def merkle_levels(txids: list[bytes]) -> list[list[bytes]]:
    """Every level of the merkle tree, leaves (level 0, with the odd-tail
    duplication applied per level) up to the root level.

    The batched-proof primitive (chain/proof.py): building the tree once
    costs the same ~2N hashes as one ``merkle_branch`` call, but with
    the levels held, EVERY transaction's branch is then O(log N) slice
    picks — amortizing the tree across all proofs for one block is what
    turns per-proof merkle reconstruction from the serving plane's
    dominant cost into noise (benchmarks/query_plane.py).
    """
    if not txids:
        raise ValueError("no txids")
    from p1_tpu.core.hashutil import sha256d

    level = list(txids)
    levels = []
    while len(level) > 1:
        if len(level) % 2:
            level.append(level[-1])
        levels.append(level)
        level = [
            sha256d(level[i] + level[i + 1]) for i in range(0, len(level), 2)
        ]
    levels.append(level)
    return levels


def branch_from_levels(levels: list[list[bytes]], index: int) -> tuple[bytes, ...]:
    """The sibling path for leaf ``index`` out of a prebuilt
    ``merkle_levels`` tree — one slice pick per level, no hashing."""
    branch: list[bytes] = []
    i = index
    for level in levels[:-1]:
        branch.append(level[i ^ 1])
        i //= 2
    return tuple(branch)


def merkle_branch(txids: list[bytes], index: int) -> tuple[bytes, ...]:
    """The sibling path proving ``txids[index]`` is under ``merkle_root(txids)``.

    One 32-byte sibling per tree level, leaf-to-root order — the compact
    inclusion proof an SPV client checks with ``verify_merkle_branch``
    without seeing the other transactions.  Built via ``merkle_levels``
    (ONE tree construction shared with the batched-proof path), so the
    root and branch functions agree for every (txids, index) by
    construction.
    """
    if not 0 <= index < len(txids):
        raise ValueError(f"index {index} out of range for {len(txids)} txids")
    return branch_from_levels(merkle_levels(txids), index)


def verify_merkle_branch(
    txid: bytes, index: int, branch: tuple[bytes, ...], root: bytes
) -> bool:
    """Does ``branch`` prove that leaf ``txid`` sits at ``index`` under
    ``root``?  Pure recombination — the verifier needs nothing but these
    arguments.  Soundness note: with the duplicate-odd-leaf construction a
    root does not uniquely determine the leaf *list* (CVE-2012-2459), but
    consensus rejects duplicate txids per block, so for valid blocks a
    verified (txid, index, root) triple pins a real on-chain transaction.
    """
    if index < 0:
        return False
    from p1_tpu.core.hashutil import sha256d

    cur = txid
    i = index
    for sib in branch:
        cur = sha256d(cur + sib) if i % 2 == 0 else sha256d(sib + cur)
        i //= 2
    # i must be exhausted: an index >= 2**depth cannot name a leaf of this
    # tree, and accepting one would let a prover relocate the transaction.
    return i == 0 and cur == root


@dataclasses.dataclass(frozen=True)
class Block:
    """Header + transactions.

    Canonical-encoding cache: ``serialize()`` memoizes its wire form and
    ``compute_merkle_root()`` its root (non-field slots via
    ``object.__setattr__`` — see BlockHeader's cache notes for why
    equality and ``dataclasses.replace`` stay unaffected).
    ``deserialize`` seeds the block's, header's, and every transaction's
    caches with the exact wire slices: one gossip frame is parsed once
    and its bytes then flow unchanged through validation digests, the
    store append, and relay re-encode — the zero-repack pipeline.
    """

    header: BlockHeader
    txs: tuple[Transaction, ...] = ()

    def block_hash(self) -> bytes:
        return self.header.block_hash()

    def compute_merkle_root(self) -> bytes:
        root = self.__dict__.get("_merkle")
        if root is None:
            root = merkle_root([tx.txid() for tx in self.txs])
            object.__setattr__(self, "_merkle", root)
        return root

    def merkle_ok(self) -> bool:
        return self.header.merkle_root == self.compute_merkle_root()

    def serialize(self) -> bytes:
        raw = self.__dict__.get("_raw")
        if raw is None:
            parts = [self.header.serialize(), _U32.pack(len(self.txs))]
            for tx in self.txs:
                tx_raw = tx.serialize()
                parts.append(_U32.pack(len(tx_raw)))
                parts.append(tx_raw)
            raw = b"".join(parts)
            object.__setattr__(self, "_raw", raw)
        return raw

    @classmethod
    def deserialize(cls, data: bytes) -> "Block":
        if len(data) < HEADER_SIZE + 4:
            raise ValueError("truncated block")
        header = BlockHeader.deserialize(data[:HEADER_SIZE])
        (ntx,) = _U32.unpack_from(data, HEADER_SIZE)
        off = HEADER_SIZE + 4
        total = len(data)
        txs = []
        for _ in range(ntx):
            if total < off + 4:
                raise ValueError("truncated block tx table")
            (txlen,) = _U32.unpack_from(data, off)
            off += 4
            if total < off + txlen:
                raise ValueError("truncated block tx")
            txs.append(Transaction.deserialize(data[off : off + txlen]))
            off += txlen
        if off != total:
            raise ValueError(f"{total - off} trailing bytes after block")
        # Direct construction (Block has no __post_init__ to honor); the
        # parse consumed data exactly (strict framing, per-field
        # round-trip identity), so these bytes are the canonical encoding.
        block = object.__new__(cls)
        block.__dict__.update(header=header, txs=tuple(txs), _raw=bytes(data))
        return block
