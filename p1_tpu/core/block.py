"""Block = header + transactions, with a SHA-256d merkle root.

Capability parity: block assembly from the mempool and header-chain
validation (BASELINE.json:5).  The merkle tree is the classic construction:
leaves are txids, pairs are combined with SHA-256d, an odd node is paired
with itself, and an empty transaction list has an all-zeros root.
"""

from __future__ import annotations

import dataclasses
import struct

from p1_tpu.core.header import HEADER_SIZE, BlockHeader
from p1_tpu.core.tx import Transaction

EMPTY_MERKLE_ROOT = bytes(32)


def merkle_root(txids: list[bytes]) -> bytes:
    """Classic duplicate-last-odd-leaf merkle tree.

    The duplication means ``[t1,t2,t3]`` and ``[t1,t2,t3,t3]`` share a root
    (the CVE-2012-2459 malleability); chain validation therefore rejects
    blocks containing duplicate txids — see p1_tpu.chain.
    """
    if not txids:
        return EMPTY_MERKLE_ROOT
    from p1_tpu.core.hashutil import sha256d

    level = list(txids)
    while len(level) > 1:
        if len(level) % 2:
            level.append(level[-1])
        level = [
            sha256d(level[i] + level[i + 1]) for i in range(0, len(level), 2)
        ]
    return level[0]


@dataclasses.dataclass(frozen=True)
class Block:
    header: BlockHeader
    txs: tuple[Transaction, ...] = ()

    def block_hash(self) -> bytes:
        return self.header.block_hash()

    def compute_merkle_root(self) -> bytes:
        return merkle_root([tx.txid() for tx in self.txs])

    def merkle_ok(self) -> bool:
        return self.header.merkle_root == self.compute_merkle_root()

    def serialize(self) -> bytes:
        parts = [self.header.serialize(), struct.pack(">I", len(self.txs))]
        for tx in self.txs:
            raw = tx.serialize()
            parts.append(struct.pack(">I", len(raw)))
            parts.append(raw)
        return b"".join(parts)

    @classmethod
    def deserialize(cls, data: bytes) -> "Block":
        if len(data) < HEADER_SIZE + 4:
            raise ValueError("truncated block")
        header = BlockHeader.deserialize(data[:HEADER_SIZE])
        (ntx,) = struct.unpack(">I", data[HEADER_SIZE : HEADER_SIZE + 4])
        off = HEADER_SIZE + 4
        txs = []
        for _ in range(ntx):
            if len(data) < off + 4:
                raise ValueError("truncated block tx table")
            (txlen,) = struct.unpack(">I", data[off : off + 4])
            off += 4
            if len(data) < off + txlen:
                raise ValueError("truncated block tx")
            txs.append(Transaction.deserialize(data[off : off + txlen]))
            off += txlen
        if off != len(data):
            raise ValueError(f"{len(data) - off} trailing bytes after block")
        return cls(header, tuple(txs))
