"""Block header: canonical 80-byte serialization and difficulty/target math.

Capability parity: the reference's ``BlockHeader`` with deterministic byte
serialization hashed by the miner (BASELINE.json:5 — "double-SHA-256 over a
serialized ``BlockHeader`` with an incrementing nonce").  This is a new design,
not a port: fields are fixed-width **big-endian** (network order) throughout,
which keeps the device-side word view trivial — the header is exactly twenty
uint32 words, and the nonce is word 19 (the last word of the second SHA-256
chunk), so a TPU kernel can vary the nonce without any byte shuffling.

Layout (80 bytes, the classic Bitcoin-style shape):

    offset  size  field
    0       4     version      (uint32 be)
    4       32    prev_hash    (raw SHA-256d digest bytes)
    36      32    merkle_root  (raw digest bytes)
    68      4     timestamp    (uint32 be, unix seconds)
    72      4     difficulty   (uint32 be — required leading zero bits, 0..255)
    76      4     nonce        (uint32 be)

Difficulty convention: an integer ``d`` meaning the block hash, read as a
big-endian 256-bit integer, must be strictly less than ``2**(256-d)`` —
i.e. it has at least ``d`` leading zero bits.  ``BASELINE.json:6-12`` sweeps
``d`` in 16..28.
"""

from __future__ import annotations

import dataclasses
import struct

HEADER_SIZE = 80
NONCE_OFFSET = 76
_PACK = struct.Struct(">I32s32sIII")
assert _PACK.size == HEADER_SIZE


@dataclasses.dataclass(frozen=True)
class BlockHeader:
    version: int
    prev_hash: bytes  # 32 raw bytes
    merkle_root: bytes  # 32 raw bytes
    timestamp: int
    difficulty: int  # required leading zero bits of the block hash
    nonce: int

    def __post_init__(self) -> None:
        if len(self.prev_hash) != 32:
            raise ValueError(f"prev_hash must be 32 bytes, got {len(self.prev_hash)}")
        if len(self.merkle_root) != 32:
            raise ValueError(
                f"merkle_root must be 32 bytes, got {len(self.merkle_root)}"
            )
        for name in ("version", "timestamp", "difficulty", "nonce"):
            v = getattr(self, name)
            if not 0 <= v <= 0xFFFFFFFF:
                raise ValueError(f"{name}={v} out of uint32 range")
        if self.difficulty > 255:
            raise ValueError(f"difficulty={self.difficulty} out of range (0..255)")

    def serialize(self) -> bytes:
        return _PACK.pack(
            self.version,
            self.prev_hash,
            self.merkle_root,
            self.timestamp,
            self.difficulty,
            self.nonce,
        )

    @classmethod
    def deserialize(cls, data: bytes) -> "BlockHeader":
        if len(data) != HEADER_SIZE:
            raise ValueError(f"header must be {HEADER_SIZE} bytes, got {len(data)}")
        version, prev_hash, merkle_root, timestamp, difficulty, nonce = _PACK.unpack(
            data
        )
        return cls(version, prev_hash, merkle_root, timestamp, difficulty, nonce)

    def with_nonce(self, nonce: int) -> "BlockHeader":
        return dataclasses.replace(self, nonce=nonce)

    def with_timestamp(self, timestamp: int) -> "BlockHeader":
        return dataclasses.replace(self, timestamp=timestamp)

    def mining_prefix(self) -> bytes:
        """The first 76 bytes — everything the nonce search holds constant."""
        return self.serialize()[:NONCE_OFFSET]

    def block_hash(self) -> bytes:
        """SHA-256d of the serialized header (the block id)."""
        from p1_tpu.core.hashutil import sha256d

        return sha256d(self.serialize())


def target_from_difficulty(difficulty: int) -> int:
    """Target threshold: hash (as a big-endian 256-bit int) must be < this."""
    if not 0 <= difficulty <= 255:
        raise ValueError(f"difficulty={difficulty} out of range (0..255)")
    return 1 << (256 - difficulty)


def target_to_words(target: int) -> tuple[int, ...]:
    """The 256-bit target as 8 big-endian uint32 words (device compare form)."""
    if not 0 < target <= 1 << 256:
        raise ValueError("target out of range")
    # A target of exactly 2**256 (difficulty 0) clamps to all-ones: every hash
    # is strictly below 2**256 anyway, and 8 words cannot represent 2**256.
    t = min(target, (1 << 256) - 1)
    return tuple((t >> (32 * (7 - i))) & 0xFFFFFFFF for i in range(8))


def meets_target(block_hash: bytes, difficulty: int) -> bool:
    """Host-side PoW check: does the hash have >= difficulty leading zero bits?"""
    if len(block_hash) != 32:
        raise ValueError("block hash must be 32 bytes")
    if difficulty == 0:
        return True
    return int.from_bytes(block_hash, "big") < target_from_difficulty(difficulty)
