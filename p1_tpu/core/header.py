"""Block header: canonical 80-byte serialization and difficulty/target math.

Capability parity: the reference's ``BlockHeader`` with deterministic byte
serialization hashed by the miner (BASELINE.json:5 — "double-SHA-256 over a
serialized ``BlockHeader`` with an incrementing nonce").  This is a new design,
not a port: fields are fixed-width **big-endian** (network order) throughout,
which keeps the device-side word view trivial — the header is exactly twenty
uint32 words, and the nonce is word 19 (the last word of the second SHA-256
chunk), so a TPU kernel can vary the nonce without any byte shuffling.

Layout (80 bytes, the classic Bitcoin-style shape):

    offset  size  field
    0       4     version      (uint32 be)
    4       32    prev_hash    (raw SHA-256d digest bytes)
    36      32    merkle_root  (raw digest bytes)
    68      4     timestamp    (uint32 be, unix seconds)
    72      4     difficulty   (uint32 be — required leading zero bits, 0..255)
    76      4     nonce        (uint32 be)

Difficulty convention: an integer ``d`` meaning the block hash, read as a
big-endian 256-bit integer, must be strictly less than ``2**(256-d)`` —
i.e. it has at least ``d`` leading zero bits.  ``BASELINE.json:6-12`` sweeps
``d`` in 16..28.

Canonical-encoding cache: the header is frozen, so its 80-byte wire form
and SHA-256d digest are constants of the instance — ``serialize()`` and
``block_hash()`` compute each once and memoize via ``object.__setattr__``
(cache slots are NOT dataclass fields: equality/hash ignore them, and
``dataclasses.replace`` — hence ``with_nonce``/``with_timestamp`` — builds
instances through ``__init__``, so derived headers start with *fresh,
empty* caches and can never inherit a stale encoding).  ``deserialize``
seeds the cache with the exact wire bytes, which is what makes the ingest
pipeline zero-repack: a header that arrived off the wire or disk is never
packed again for hashing, storing, or relay (docs/PERF.md "host ingest
plane").  The encoding is canonical — fixed-width fields — so the seeded
bytes are byte-identical to a recomputation (tested).
"""

from __future__ import annotations

import dataclasses
import struct

HEADER_SIZE = 80
NONCE_OFFSET = 76
_PACK = struct.Struct(">I32s32sIII")
assert _PACK.size == HEADER_SIZE


class _HeaderCache:
    """Slot home for the memoized encoding (``_raw``) and digest
    (``_hash``).  A separate base because ``dataclass(slots=True)``
    generates ``__slots__`` from the FIELDS only — the caches are not
    fields (equality/replace must ignore them) but still need slots, or
    the instance grows a dict and the whole point is lost."""

    __slots__ = ("_raw", "_hash")


@dataclasses.dataclass(frozen=True, slots=True)
class BlockHeader(_HeaderCache):
    version: int
    prev_hash: bytes  # 32 raw bytes
    merkle_root: bytes  # 32 raw bytes
    timestamp: int
    difficulty: int  # required leading zero bits of the block hash
    nonce: int

    def __post_init__(self) -> None:
        if len(self.prev_hash) != 32:
            raise ValueError(f"prev_hash must be 32 bytes, got {len(self.prev_hash)}")
        if len(self.merkle_root) != 32:
            raise ValueError(
                f"merkle_root must be 32 bytes, got {len(self.merkle_root)}"
            )
        for name in ("version", "timestamp", "difficulty", "nonce"):
            v = getattr(self, name)
            if not 0 <= v <= 0xFFFFFFFF:
                raise ValueError(f"{name}={v} out of uint32 range")
        if self.difficulty > 255:
            raise ValueError(f"difficulty={self.difficulty} out of range (0..255)")

    def serialize(self) -> bytes:
        raw = getattr(self, "_raw", None)
        if raw is None:
            raw = _PACK.pack(
                self.version,
                self.prev_hash,
                self.merkle_root,
                self.timestamp,
                self.difficulty,
                self.nonce,
            )
            object.__setattr__(self, "_raw", raw)
        return raw

    @classmethod
    def deserialize(cls, data: bytes) -> "BlockHeader":
        if len(data) != HEADER_SIZE:
            raise ValueError(f"header must be {HEADER_SIZE} bytes, got {len(data)}")
        version, prev_hash, merkle_root, timestamp, difficulty, nonce = _PACK.unpack(
            data
        )
        # The fixed-width unpack structurally guarantees every
        # ``__post_init__`` range rule (``>I`` yields uint32, ``32s``
        # yields 32 bytes) except the difficulty ceiling — check that one
        # and build the instance directly: this is the gossip/resume hot
        # path, and re-validating what the wire format already proves is
        # pure overhead.
        if difficulty > 255:
            raise ValueError(f"difficulty={difficulty} out of range (0..255)")
        header = object.__new__(cls)
        set_ = object.__setattr__
        set_(header, "version", version)
        set_(header, "prev_hash", prev_hash)
        set_(header, "merkle_root", merkle_root)
        set_(header, "timestamp", timestamp)
        set_(header, "difficulty", difficulty)
        set_(header, "nonce", nonce)
        # Seed the encoding cache with the exact wire bytes: fixed-width
        # fields make re-packing byte-identical, so these ARE the
        # canonical encoding and the header never repacks.
        set_(header, "_raw", bytes(data))
        return header

    def with_nonce(self, nonce: int) -> "BlockHeader":
        return dataclasses.replace(self, nonce=nonce)

    def with_timestamp(self, timestamp: int) -> "BlockHeader":
        return dataclasses.replace(self, timestamp=timestamp)

    def mining_prefix(self) -> bytes:
        """The first 76 bytes — everything the nonce search holds constant."""
        return self.serialize()[:NONCE_OFFSET]

    def block_hash(self) -> bytes:
        """SHA-256d of the serialized header (the block id) — computed
        once; gossip ingest, fork choice, and store resume all re-ask."""
        digest = getattr(self, "_hash", None)
        if digest is None:
            from p1_tpu.core.hashutil import sha256d

            digest = sha256d(self.serialize())
            object.__setattr__(self, "_hash", digest)
        return digest


def target_from_difficulty(difficulty: int) -> int:
    """Target threshold: hash (as a big-endian 256-bit int) must be < this."""
    if not 0 <= difficulty <= 255:
        raise ValueError(f"difficulty={difficulty} out of range (0..255)")
    return 1 << (256 - difficulty)


def target_to_words(target: int) -> tuple[int, ...]:
    """The 256-bit target as 8 big-endian uint32 words (device compare form)."""
    if not 0 < target <= 1 << 256:
        raise ValueError("target out of range")
    # A target of exactly 2**256 (difficulty 0) clamps to all-ones: every hash
    # is strictly below 2**256 anyway, and 8 words cannot represent 2**256.
    t = min(target, (1 << 256) - 1)
    return tuple((t >> (32 * (7 - i))) & 0xFFFFFFFF for i in range(8))


def meets_target(block_hash: bytes, difficulty: int) -> bool:
    """Host-side PoW check: does the hash have >= difficulty leading zero bits?"""
    if len(block_hash) != 32:
        raise ValueError("block hash must be 32 bytes")
    if difficulty == 0:
        return True
    return int.from_bytes(block_hash, "big") < target_from_difficulty(difficulty)
