"""The audited exceptions: rule -> file -> construct key -> REASON.

Every entry is a sentence a reviewer can audit, not a bare pass.  Both
directions are enforced by the engine (tests/test_analysis.py keeps
the tree settled in tier-1):

- a finding with no grant here fails the build;
- a grant here that no finding consumes fails the build too — stale
  grants rot into blanket permissions.

Wall-clock grants migrated verbatim (same files, same constructs) from
the retired tokenizer lint in tests/test_simlint.py; the reasons are
its audit comments.  Two historical notes that shaped that list and
still bind future edits:

- node/protocol.py held a ``time.time`` grant for encode_block's
  default send stamp until round 11: the codec now encodes 0.0 = "no
  stamp" and every caller stamps from its own transport clock — the
  stamp is INSIDE the frame bytes, so a codec-side host-clock read
  made simulated flood traces nondeterministic.  Do not re-grant it.
- chain/snapshot.py entered coverage clock-free with ZERO grants
  (round 12) and must stay that way: snapshot integrity checking and
  (de)serialization are pure functions of bytes, and granting the
  module a clock seam it does not need would only invite one
  (tests/test_simlint.py pins this by name).
- node/reconcile.py entered coverage with ZERO grants (round 23) and
  must stay that way: the sketch codec is pure GF(2^32) arithmetic
  over bytes — no clock, no rng, no loop — and every consumer-side
  timing decision (round cadence, stall aging, demotion windows)
  lives in node/node.py where the existing grants already cover it.

The four rules with no entries below — lost-task, unseeded-rng,
set-iteration, await-state — currently hold over the WHOLE package
with zero exceptions (round 13 fixed the two pre-existing findings:
chaos.py's set-literal probe iteration and supervision.py's
implicitly-seeded fallback rng rather than granting them).  Keep it
that way where possible: for these rules a fix is almost always
smaller than an audit-proof reason.
"""

from __future__ import annotations

#: rule name -> file (relative to p1_tpu/) -> grant key -> audited reason.
GRANTS: dict[str, dict[str, dict[str, str]]] = {
    "wall-clock": {
        # -- async product code running under the (possibly virtual)
        #    loop: asyncio.sleep is loop-relative, sim-compatible BY
        #    CONSTRUCTION — granted per file so a NEW module acquiring
        #    sleeps is a deliberate edit, not a silent pass.
        "node/node.py": {
            "asyncio.sleep": "node coroutines sleep on their own loop; "
            "the simulator virtualizes the loop itself",
        },
        "node/client.py": {
            "asyncio.sleep": "light-client backoff sleeps ride the "
            "caller's loop (virtual under netsim)",
        },
        "node/provision.py": {
            "asyncio.sleep": "UpstreamSync poll interval rides the "
            "caller's loop (virtual under netsim); bootstrap runs "
            "before the replica serves, outside any injected Clock",
        },
        # -- the simulator itself: sleeps are virtual here, and
        #    time.monotonic guards REAL wall budgets (SimWallTimeout)
        #    plus the scenario reports' wall_s — deliberate host reads.
        "node/netsim.py": {
            "time.monotonic": "SimWallTimeout real-wall budget + report "
            "wall_s: deliberate host-clock reads about the sim, not in it",
            "asyncio.sleep": "the virtual loop's own sleep primitive",
        },
        "node/scenarios.py": {
            "time.monotonic": "scenario wall_s reporting and wall "
            "budgets (same split as netsim.py)",
            "asyncio.sleep": "scenario driver sleeps on the virtual loop",
        },
        "node/chaos.py": {
            "time.monotonic": "chaos sweeps' SimWallTimeout budget and "
            "report wall_s (same split as scenarios.py)",
            "asyncio.sleep": "chaos schedules sleep on the virtual loop",
        },
        "node/farfield.py": {
            "time.monotonic": "the shard coordinator's real-wall "
            "budget guard and report wall_s — deliberate host-clock "
            "reads ABOUT the far-field run, never inside its "
            "integer-microsecond event time (same split as netsim.py); "
            "the engine itself is synchronous and clock-free",
        },
        # -- harness/tooling that drives REAL processes and sockets on
        #    the host clock by design (subprocess meshes, soak drivers,
        #    operator runners) — not part of the simulated node.
        "node/runner.py": {
            "time.time": "operator soak runner: wall-clock deadlines "
            "over real processes",
            "time.monotonic": "real elapsed/rate figures for the soak "
            "report",
            "asyncio.sleep": "paces a REAL node's status polling",
        },
        "node/netharness.py": {
            "time.time": "subprocess-mesh harness deadlines over real "
            "sockets",
            "asyncio.sleep": "real-socket settle/poll pacing",
        },
        "node/byzantine.py": {
            "asyncio.sleep": "attacker session pacing under the "
            "(possibly virtual) loop",
        },
        "node/testing.py": {
            "asyncio.sleep": "hostile/greedy peer harness pacing under "
            "the (possibly virtual) loop",
        },
        # -- the read-replica serving plane: a real-socket, separate-
        #    process tier (`p1 serve`) out of the simulator's scope.
        "node/queryplane.py": {
            "time.monotonic": "replica uptime/QPS windows on the host "
            "clock (separate process, never simulated)",
            "asyncio.sleep": "replica refresh pacing on its own real loop",
        },
        # -- benchmark timing, not node behavior.
        "chain/replay.py": {
            "time.perf_counter": "replay throughput figures (the "
            "benchmark IS a wall-clock measurement)",
        },
    },
    "lost-task": {},
    "unseeded-rng": {},
    "set-iteration": {},
    "blocking-in-async": {
        # Currently EMPTY: no direct blocking calls run on any async
        # loop today.  Store fsyncs, signature preverification, and
        # checkpoint writes all travel through NodePipeline.run_store /
        # run_validate (node/pipeline.py) — callables handed to a lane,
        # never called from the coroutine — so the house pattern for new
        # blocking work is "give it to the pipeline", not "grant it
        # here".  A grant added here is acknowledged debt: each one
        # names a call the staged pipeline has not absorbed yet.
    },
    "await-state": {},
    # -- transitive-blocking (round 16): THE ROADMAP-2 OFFLOAD WORK
    #    LIST.  Each grant is one call chain, found by the whole-
    #    package call graph, through which an async def blocks the
    #    consensus loop today.  The reason names the pipeline stage
    #    (wire framing → admission → validation → store → relay) the
    #    multi-core split must move it to.  Removing a grant here
    #    should mean the chain moved off-loop — not that the lint
    #    stopped seeing it.
    #
    #    Round 19 retired ten of the twelve node/node.py grants: the
    #    staged pipeline (node/pipeline.py) now owns every chain they
    #    named.  Per-retirement record, auditable against the round-16
    #    reasons above each key's old text (git log -p this file):
    #
    #    - Node._handle_block->ctypes.CDLL: wire blocks preverify
    #      signatures on the VALIDATE lane before add_block; the
    #      residual on-loop check_block verify is a sig-cache hit for
    #      every honestly-signed block (only invalid-signature blocks
    #      pay it, bounded by the ban that follows) and goes through
    #      Chain.check_block, an instance-attribute seam the call
    #      graph correctly no longer binds to the ctypes engine.
    #    - Node._handle_block->open: _store_append submits
    #      _store_flush_io to the STORE lane; append+fsync left the
    #      loop.
    #    - Node._dispatch->ctypes.CDLL: BLOCKS/MEMPOOL batch
    #      preverification runs on the VALIDATE lane.
    #    - Node._dispatch->os.fsync: the BLOCKS batch-close sync runs
    #      on the STORE lane (_store_sync_io).
    #    - Node._store_recovery_loop->open / ->os.fsync: degraded-mode
    #      flush retries and the recovery sync probe submit the same
    #      _io helpers to the STORE lane.
    #    - Node._adopt_snapshot->open / ->os.fsync: the snapshot
    #      sidecar write and the genesis-first store rewrite run on
    #      the STORE lane.
    #    - Node._snapshot_flip->os.fsync and
    #      Node._snapshot_diverged->os.fsync: _rewrite_store — the
    #      heaviest single blocking window in the node — runs on the
    #      STORE lane for both the flip and the quarantine path.
    #
    #    The two survivors are boundary cases by design, not misses:
    #    start() has no sessions to stall and stop() drains the
    #    pipeline BEFORE its final flush precisely so shutdown IO can
    #    stay synchronous.
    "transitive-blocking": {
        "node/node.py": {
            "Node.start->open": "startup-only: the resume path opens/"
            "locks/replays the store before the node serves a single "
            "frame — no session exists to stall; stays on-loop by "
            "design",
            "Node.stop->open": "shutdown-only: the final store flush "
            "runs after pipeline.drain_and_close() joined the store "
            "worker; a lane submit here would race its own teardown",
        },
        "node/queryplane.py": {
            "serve_replica->open": "replica attach (ReplicaView "
            "refresh: manifest read + per-segment mmap) runs once at "
            "worker startup before any session exists; steady-state "
            "refreshes only stat/remap the tail — stays on-loop by "
            "design",
        },
        "node/provision.py": {
            "bootstrap_store->open": "startup-only: cold start runs "
            "BEFORE the replica serves its first frame — no session "
            "exists to stall, and the store appends/syncs already "
            "ride asyncio.to_thread; the residual on-loop IO is the "
            "bootbase sidecar write and snapshot spool, once per "
            "bootstrap by design",
        },
    },
    # -- escaped-state (round 16): await-state folded one call level.
    "escaped-state": {
        "node/node.py": {
            "chain": "_handle_snapshot: the flagged pre-await read of "
            "self.chain sits in early-returning branches "
            "(_request_blocks fallbacks), and the post-await writer "
            "(_adopt_snapshot) RE-VALIDATES after the scheduling "
            "point — validation_state, _bg_chain, and snapshot-vs-"
            "height are all re-read before the install, the safe "
            "shape the rule's docstring names",
        },
    },
    "wire-contract": {
        # EMPTY and should stay so: a grant here would bless a frame
        # type with a hole in its encoder/decoder/dispatch/admission/
        # shed/version contract.  The only legitimate tenant is a
        # frame mid-introduction across a stacked PR, removed when the
        # second half lands.
    },
}
