"""The audited exceptions: rule -> file -> construct key -> REASON.

Every entry is a sentence a reviewer can audit, not a bare pass.  Both
directions are enforced by the engine (tests/test_analysis.py keeps
the tree settled in tier-1):

- a finding with no grant here fails the build;
- a grant here that no finding consumes fails the build too — stale
  grants rot into blanket permissions.

Wall-clock grants migrated verbatim (same files, same constructs) from
the retired tokenizer lint in tests/test_simlint.py; the reasons are
its audit comments.  Two historical notes that shaped that list and
still bind future edits:

- node/protocol.py held a ``time.time`` grant for encode_block's
  default send stamp until round 11: the codec now encodes 0.0 = "no
  stamp" and every caller stamps from its own transport clock — the
  stamp is INSIDE the frame bytes, so a codec-side host-clock read
  made simulated flood traces nondeterministic.  Do not re-grant it.
- chain/snapshot.py entered coverage clock-free with ZERO grants
  (round 12) and must stay that way: snapshot integrity checking and
  (de)serialization are pure functions of bytes, and granting the
  module a clock seam it does not need would only invite one
  (tests/test_simlint.py pins this by name).

The four rules with no entries below — lost-task, unseeded-rng,
set-iteration, await-state — currently hold over the WHOLE package
with zero exceptions (round 13 fixed the two pre-existing findings:
chaos.py's set-literal probe iteration and supervision.py's
implicitly-seeded fallback rng rather than granting them).  Keep it
that way where possible: for these rules a fix is almost always
smaller than an audit-proof reason.
"""

from __future__ import annotations

#: rule name -> file (relative to p1_tpu/) -> grant key -> audited reason.
GRANTS: dict[str, dict[str, dict[str, str]]] = {
    "wall-clock": {
        # -- async product code running under the (possibly virtual)
        #    loop: asyncio.sleep is loop-relative, sim-compatible BY
        #    CONSTRUCTION — granted per file so a NEW module acquiring
        #    sleeps is a deliberate edit, not a silent pass.
        "node/node.py": {
            "asyncio.sleep": "node coroutines sleep on their own loop; "
            "the simulator virtualizes the loop itself",
        },
        "node/client.py": {
            "asyncio.sleep": "light-client backoff sleeps ride the "
            "caller's loop (virtual under netsim)",
        },
        # -- the simulator itself: sleeps are virtual here, and
        #    time.monotonic guards REAL wall budgets (SimWallTimeout)
        #    plus the scenario reports' wall_s — deliberate host reads.
        "node/netsim.py": {
            "time.monotonic": "SimWallTimeout real-wall budget + report "
            "wall_s: deliberate host-clock reads about the sim, not in it",
            "asyncio.sleep": "the virtual loop's own sleep primitive",
        },
        "node/scenarios.py": {
            "time.monotonic": "scenario wall_s reporting and wall "
            "budgets (same split as netsim.py)",
            "asyncio.sleep": "scenario driver sleeps on the virtual loop",
        },
        "node/chaos.py": {
            "time.monotonic": "chaos sweeps' SimWallTimeout budget and "
            "report wall_s (same split as scenarios.py)",
            "asyncio.sleep": "chaos schedules sleep on the virtual loop",
        },
        # -- harness/tooling that drives REAL processes and sockets on
        #    the host clock by design (subprocess meshes, soak drivers,
        #    operator runners) — not part of the simulated node.
        "node/runner.py": {
            "time.time": "operator soak runner: wall-clock deadlines "
            "over real processes",
            "time.monotonic": "real elapsed/rate figures for the soak "
            "report",
            "asyncio.sleep": "paces a REAL node's status polling",
        },
        "node/netharness.py": {
            "time.time": "subprocess-mesh harness deadlines over real "
            "sockets",
            "asyncio.sleep": "real-socket settle/poll pacing",
        },
        "node/byzantine.py": {
            "asyncio.sleep": "attacker session pacing under the "
            "(possibly virtual) loop",
        },
        "node/testing.py": {
            "asyncio.sleep": "hostile/greedy peer harness pacing under "
            "the (possibly virtual) loop",
        },
        # -- the read-replica serving plane: a real-socket, separate-
        #    process tier (`p1 serve`) out of the simulator's scope.
        "node/queryplane.py": {
            "time.monotonic": "replica uptime/QPS windows on the host "
            "clock (separate process, never simulated)",
            "asyncio.sleep": "replica refresh pacing on its own real loop",
        },
        # -- benchmark timing, not node behavior.
        "chain/replay.py": {
            "time.perf_counter": "replay throughput figures (the "
            "benchmark IS a wall-clock measurement)",
        },
    },
    "lost-task": {},
    "unseeded-rng": {},
    "set-iteration": {},
    "blocking-in-async": {
        # Currently EMPTY: no direct blocking calls run on any async
        # loop today (store fsyncs go through sync helpers called from
        # sync paths or asyncio.to_thread — see node.py's
        # _checkpoint_mempool for the house pattern).  Grants added
        # here are acknowledged ROADMAP item-5 debt: each one names a
        # call the multi-core stage split must move off-loop.
    },
    "await-state": {},
}
