"""The unit of output: one structural violation at one source line."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True, order=True)
class Finding:
    """One rule hit.

    ``file`` is the path relative to the analyzed package root with
    POSIX separators ("node/node.py") — the same spelling the
    allowlists use.  ``key`` is the rule-defined grant key (the
    *construct*, not the instance): the wall-clock rule keys on the
    dotted callable ("time.monotonic"), the lost-task rule on the
    enclosing function, the await-state rule on the attribute name.
    Grants therefore survive line churn but never outlive the construct
    they bless — the stale-grant check fails any grant no finding
    consumes.
    """

    file: str
    line: int
    rule: str
    detail: str
    key: str

    def __str__(self) -> str:  # the human CLI line
        return f"{self.file}:{self.line}: [{self.rule}] {self.detail}"
