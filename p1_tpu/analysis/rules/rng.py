"""unseeded-rng: randomness in product code must carry a derived seed.

The simulator's reproducibility contract — same seed, byte-identical
trace, cross-process (tests/test_scenarios.py pins it) — holds only
while every random draw in the simulated world descends from the
scenario seed.  One ``random.Random()`` (seeded from OS entropy behind
your back) or one module-level ``random.random()`` (the interpreter's
shared ambient generator, reseeded by anyone) in a node/sim path and
same-seed runs silently diverge; the chaos plane's shrinker then
cannot reproduce the failure it just found.

Flagged:

- ``random.Random()`` with no arguments — if OS entropy is genuinely
  intended (production identity draws), write the intent down:
  ``random.Random(secrets.randbits(64))`` seeds explicitly and passes;
- any call on the ``random`` MODULE itself (``random.random()``,
  ``random.choice(...)``, ...) — ambient global state is never
  derivable from a scenario seed; draw from an injected
  ``random.Random`` instance instead.

``secrets`` is deliberately not matched: it is the explicit "I want OS
entropy" spelling, used for production identity (instance nonces, key
material) where determinism would be a bug — and sim paths already
inject seeded rngs past every one of those call sites.
"""

from __future__ import annotations

import ast
from typing import Iterator

from p1_tpu.analysis.base import Rule, dotted_name, register
from p1_tpu.analysis.findings import Finding

#: The ambient-global draw functions on the random module.
_MODULE_FNS = frozenset(
    {
        "betavariate", "choice", "choices", "expovariate", "gauss",
        "getrandbits", "lognormvariate", "normalvariate", "paretovariate",
        "randbytes", "randint", "random", "randrange", "sample", "seed",
        "shuffle", "triangular", "uniform", "vonmisesvariate",
        "weibullvariate",
    }
)


@register
class UnseededRngRule(Rule):
    name = "unseeded-rng"
    title = "randomness with no derived seed (sim-trace divergence)"
    scope = ()  # the whole package — tooling traces deserve replay too

    def check(self, tree: ast.Module, rel: str) -> Iterator[Finding]:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = dotted_name(node.func)
            if dotted == "random.Random" and not node.args and not node.keywords:
                yield self.finding(
                    rel,
                    node,
                    "random.Random() with no seed — derive one from the "
                    "scenario/node seed, or spell OS entropy explicitly "
                    "(random.Random(secrets.randbits(64)))",
                    "random.Random",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "random"
                and node.func.attr in _MODULE_FNS
            ):
                yield self.finding(
                    rel,
                    node,
                    f"module-level random.{node.func.attr}() draws from "
                    "the interpreter's shared generator — use an "
                    "injected random.Random instance",
                    f"random.{node.func.attr}",
                )
