"""set-iteration: consensus and sim paths must not iterate unordered.

Python sets iterate in hash-table order, which varies with insertion
history and (for str keys under hash randomization) across processes.
Round 7 fixed this class BY HAND twice to get byte-identical sim
traces: peer/address bookkeeping moved from ``set`` to insertion-
ordered ``dict[key, None]`` so relay fan-out and dial order stopped
depending on hash order.  Any *new* ``for x in some_set_expression``
in a covered path reintroduces trace divergence — and in consensus
code, ordering-dependent tie-breaks.

Flagged — direct iteration (for / async for / comprehension clauses)
over an expression that is structurally a set:

- a set literal or a ``set(...)``/``frozenset(...)`` call;
- a binary set operation (``-``/``|``/``&``/``^``) with such an
  operand, or with a ``.keys()`` view operand (the "dict-keys
  difference" shape: ``d.keys() - seen``);
- a ``.difference/.union/.intersection/.symmetric_difference`` call;
- since round 16, ONE dataflow hop: a bare local name every binding
  of which in the enclosing function is structurally a set
  (``pending = set(); ... for p in pending``) — the "through a
  variable" residue the round-13 docs conceded, closed with the call
  graph's local-binding summary (analysis/callgraph.py
  ``local_set_bindings``).  A single non-set rebinding (``pending =
  sorted(pending)``) takes the name out of the set class, so the
  normalize-then-iterate idiom stays clean.

Not flagged: ``sorted(set(...))`` (the sort normalizes the order —
and structurally the loop iterates the ``sorted`` call, not the set);
membership tests; iteration over a plain ``dict``/``.keys()`` view
(insertion-ordered by language guarantee); sets reaching the loop
through parameters, attributes, or across function boundaries (type
inference beyond one local hop stays out of scope — the fixture
corpus and review carry that residue).
"""

from __future__ import annotations

import ast
from typing import Iterator

from p1_tpu.analysis.base import (
    Rule,
    is_set_expr,
    register,
    walk_no_nested_defs,
)
from p1_tpu.analysis.callgraph import local_set_bindings
from p1_tpu.analysis.findings import Finding


@register
class SetIterationRule(Rule):
    name = "set-iteration"
    title = "iteration over an unordered set expression"
    #: The deterministic-trace product tree, same coverage as wall-clock.
    scope = ("node/", "chain/", "mempool/")

    def check(self, tree: ast.Module, rel: str) -> Iterator[Finding]:
        # module scope + every function scope, each with its own
        # local-binding summary (names are function-local facts).
        scopes: list[ast.AST] = [tree]
        scopes.extend(
            n
            for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        )
        for scope in scopes:
            set_locals = local_set_bindings(scope)
            for node in walk_no_nested_defs(scope):
                iters: list[ast.AST] = []
                if isinstance(node, (ast.For, ast.AsyncFor)):
                    iters.append(node.iter)
                elif isinstance(
                    node,
                    (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp),
                ):
                    iters.extend(gen.iter for gen in node.generators)
                for it in iters:
                    if is_set_expr(it):
                        yield self.finding(
                            rel,
                            it,
                            "iterating an unordered set expression — sort "
                            "it, or keep insertion order with "
                            "dict[key, None] (the round-7 "
                            "trace-determinism fix)",
                            "set-expr",
                        )
                    elif (
                        isinstance(it, ast.Name) and it.id in set_locals
                    ):
                        yield self.finding(
                            rel,
                            it,
                            f"iterating {it.id!r}, a local bound only to "
                            "set expressions in this scope — sort it "
                            "(or normalize with sorted() before the "
                            "loop); unordered iteration is the round-7 "
                            "trace-divergence class one variable away",
                            "set-local",
                        )
