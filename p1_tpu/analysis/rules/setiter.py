"""set-iteration: consensus and sim paths must not iterate unordered.

Python sets iterate in hash-table order, which varies with insertion
history and (for str keys under hash randomization) across processes.
Round 7 fixed this class BY HAND twice to get byte-identical sim
traces: peer/address bookkeeping moved from ``set`` to insertion-
ordered ``dict[key, None]`` so relay fan-out and dial order stopped
depending on hash order.  Any *new* ``for x in some_set_expression``
in a covered path reintroduces trace divergence — and in consensus
code, ordering-dependent tie-breaks.

Flagged — direct iteration (for / async for / comprehension clauses)
over an expression that is structurally a set:

- a set literal or a ``set(...)``/``frozenset(...)`` call;
- a binary set operation (``-``/``|``/``&``/``^``) with such an
  operand, or with a ``.keys()`` view operand (the "dict-keys
  difference" shape: ``d.keys() - seen``);
- a ``.difference/.union/.intersection/.symmetric_difference`` call.

Not flagged: ``sorted(set(...))`` (the sort normalizes the order —
and structurally the loop iterates the ``sorted`` call, not the set);
membership tests; iteration over a plain ``dict``/``.keys()`` view
(insertion-ordered by language guarantee); sets reaching the loop
through a variable (type inference is out of scope — the fixture
corpus and review carry that residue).
"""

from __future__ import annotations

import ast
from typing import Iterator

from p1_tpu.analysis.base import Rule, dotted_name, register
from p1_tpu.analysis.findings import Finding

_SET_METHODS = frozenset(
    {"difference", "union", "intersection", "symmetric_difference"}
)
_SET_OPS = (ast.Sub, ast.BitOr, ast.BitAnd, ast.BitXor)


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, ast.Set) or isinstance(node, ast.SetComp):
        return True
    if isinstance(node, ast.Call):
        dotted = dotted_name(node.func)
        if dotted in ("set", "frozenset"):
            return True
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _SET_METHODS
        ):
            return True
    if isinstance(node, ast.BinOp) and isinstance(node.op, _SET_OPS):
        return _is_set_expr(node.left) or _is_set_expr(node.right) or (
            _is_keys_view(node.left) or _is_keys_view(node.right)
        )
    return False


def _is_keys_view(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "keys"
        and not node.args
    )


@register
class SetIterationRule(Rule):
    name = "set-iteration"
    title = "iteration over an unordered set expression"
    #: The deterministic-trace product tree, same coverage as wall-clock.
    scope = ("node/", "chain/", "mempool/")

    def check(self, tree: ast.Module, rel: str) -> Iterator[Finding]:
        for node in ast.walk(tree):
            iters: list[ast.AST] = []
            if isinstance(node, (ast.For, ast.AsyncFor)):
                iters.append(node.iter)
            elif isinstance(
                node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
            ):
                iters.extend(gen.iter for gen in node.generators)
            for it in iters:
                if _is_set_expr(it):
                    yield self.finding(
                        rel,
                        it,
                        "iterating an unordered set expression — sort it, "
                        "or keep insertion order with dict[key, None] "
                        "(the round-7 trace-determinism fix)",
                        "set-expr",
                    )
