"""escaped-state: await-state with one level of call transparency.

``await-state`` flags read → await → write on consensus attributes
(``self.chain``/``ledger``/``store``/``mempool``) when all three sit
lexically in one ``async def``.  The documented residue: route either
endpoint through a method call — ``tip = self._read_tip()`` before
the await, ``self._install(tip)`` after it — and the race is
invisible, though the interleaving hazard is byte-for-byte the same
(the world still moves at the scheduling point; the helper just holds
the stale value one frame lower).

This rule folds ONE call level in, using the call graph's effect
summaries: for every call a coroutine makes to a resolvable helper
(``self.helper()``, a local function, an imported package function, a
``self.attr.meth()`` with a known attribute type), the helper's own
direct watched-state reads and writes are treated as happening at the
call site.  Then the same read → await → write scan runs over the
folded event sequence.  To stay disjoint from ``await-state`` (and
keep its grant table stable), a finding is emitted ONLY when at least
one endpoint — the pre-await read or the post-await write — came from
a folded helper; races fully visible in the caller's own body remain
await-state findings.

One level is deliberate: each fold is a concrete, auditable claim
("_install writes self.chain") a reviewer can check by opening one
function.  Deeper transitive folding multiplies false positives
without adding a bug class — the chaos sweeps hunt the rest
dynamically.

Grant key: the attribute name, same keying discipline as await-state;
the detail names the helper(s) that carry the escaped endpoints.
"""

from __future__ import annotations

from typing import Iterator

from p1_tpu.analysis.base import Rule, register
from p1_tpu.analysis.findings import Finding

#: (kind, attr, pos, via) events; via = helper name or None (direct).
_READ, _WRITE, _AWAIT = 0, 1, 2


@register
class EscapedStateRule(Rule):
    name = "escaped-state"
    title = "consensus read/write escaping into a helper across an await"
    scope = ("node/",)  # where the consensus loop and its state live
    package_rule = True

    def check_package(self, pkg) -> Iterator[Finding]:
        graph = pkg.graph
        for qual in sorted(graph.nodes):
            node = graph.nodes[qual]
            if not node.is_async or not self.applies_to(node.rel):
                continue
            events: list[tuple[tuple[int, int], int, str, str | None]] = []
            for attr, pos in node.state_reads:
                events.append((pos, _READ, attr, None))
            for attr, pos in node.state_writes:
                events.append((pos, _WRITE, attr, None))
            for pos in node.awaits:
                events.append((pos, _AWAIT, "", None))
            for call in node.calls:
                if call.target is None:
                    continue
                callee = graph.nodes[call.target]
                pos = (call.line, 0)
                for attr, _ in callee.state_reads:
                    events.append((pos, _READ, attr, callee.name))
                for attr, _ in callee.state_writes:
                    events.append((pos, _WRITE, attr, callee.name))
            events.sort(key=lambda e: (e[0], e[1]))
            yield from self._scan(node, events)

    def _scan(self, node, events) -> Iterator[Finding]:
        # first unconsumed read per attr: (pos, via)
        reads: dict[str, tuple[tuple[int, int], str | None]] = {}
        awaits: list[tuple[int, int]] = []
        flagged: set[str] = set()
        for pos, kind, attr, via in events:
            if kind == _AWAIT:
                awaits.append(pos)
            elif kind == _READ:
                reads.setdefault(attr, (pos, via))
            elif kind == _WRITE:
                first = reads.get(attr)
                if (
                    attr not in flagged
                    and first is not None
                    and any(first[0] < a < pos for a in awaits)
                    and (first[1] is not None or via is not None)
                ):
                    flagged.add(attr)
                    read_src = (
                        f"{first[1]}()" if first[1] else "this coroutine"
                    )
                    write_src = f"{via}()" if via else "this coroutine"
                    yield Finding(
                        file=node.rel,
                        line=pos[0],
                        rule=self.name,
                        detail=(
                            f"self.{attr} read via {read_src} before an "
                            f"await and written via {write_src} after it "
                            f"in {node.name}() — the helper carries the "
                            "stale value across the scheduling point; "
                            "re-validate before writing or grant with "
                            "the safety argument"
                        ),
                        key=attr,
                    )
                reads.pop(attr, None)
