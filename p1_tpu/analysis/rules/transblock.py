"""transitive-blocking: the loop is stalled through helpers too.

``blocking-in-async`` pins the DIRECT class: a ``time.sleep`` written
lexically inside an ``async def``.  But the incidents that motivated
it were never that polite — the fsync lives three sync helpers down
(``self.store.append`` → ``_write_record`` → ``os.fsync``), the
native crypto call hides behind ``keys.verify_batch``, and the
``async def`` at the top looks spotless.  docs/LINT.md conceded this
residue in round 13; ROADMAP item 2 (the multi-core stage split)
cannot start without closing it, because its whole premise is an
audited inventory of what actually blocks the consensus loop.

This rule rides the whole-package call graph (analysis/callgraph.py):
blocking-ness — direct primitives: ``time.sleep``, builtin ``open``,
``os.fsync``/``fdatasync``/``sync``, ``subprocess.*``, ctypes natives
— propagates up resolved call edges to a fixed point, and every
``async def`` whose own control flow reaches a primitive through ONE
OR MORE *sync* helpers is flagged with the full witness chain in the
detail (``_handle_block → _store_append → ChainStore.append →
os.fsync``).  Direct calls (zero hops) stay blocking-in-async's
findings; chains that pass through another ``async def`` are not
re-flagged here — the finding lands at the DEEPEST async frame, which
is where the offload fix goes.

The grant table for this rule IS ROADMAP item 2's work list: each
grant names one blocking chain still running on the loop, with the
stage (validate/store/...) it must move to written in the reason.
A callable merely passed to ``asyncio.to_thread``/an executor is not
an edge — the house off-load pattern stays clean without a grant.

Grant key: ``"{async fn}->{primitive}"`` — stable across line churn
and across refactors of the middle of the chain, but a new primitive
reached from the same coroutine is a NEW finding.
"""

from __future__ import annotations

from typing import Iterator

from p1_tpu.analysis.base import Rule, register
from p1_tpu.analysis.findings import Finding


@register
class TransitiveBlockingRule(Rule):
    name = "transitive-blocking"
    title = "async def reaches a blocking call through sync helpers"
    scope = ()  # every async def in the package runs on SOME loop
    package_rule = True

    def check_package(self, pkg) -> Iterator[Finding]:
        graph = pkg.graph
        witness = graph.blocking_paths()
        for qual in sorted(graph.nodes):
            node = graph.nodes[qual]
            if not node.is_async:
                continue
            seen_prims: set[str] = set()
            for call in node.calls:
                w = witness.get(call.target) if call.target else None
                if w is None:
                    continue
                callee = graph.nodes[call.target]
                if callee.is_async:
                    continue  # flagged at the deepest async frame
                chain = [node.name] + graph.witness_chain(
                    call.target, witness
                )
                prim = chain[-1]
                if prim in seen_prims:
                    continue  # one finding per (coroutine, primitive)
                seen_prims.add(prim)
                yield Finding(
                    file=node.rel,
                    line=call.line,
                    rule=self.name,
                    detail=(
                        f"async {node.name}() blocks the loop through "
                        + " -> ".join(chain)
                        + " — move the chain to a worker "
                        "(asyncio.to_thread / executor) or grant it as "
                        "acknowledged ROADMAP-2 offload debt"
                    ),
                    key=f"{node.name}->{prim}",
                )
