"""blocking-in-async: no synchronous stalls on the consensus loop.

Everything consensus-critical runs on ONE asyncio thread (ROADMAP item
5 is the refactor out of that).  A ``time.sleep``, a synchronous
``open``/read, an ``os.fsync``, or a ``subprocess`` call inside an
``async def`` stalls frame reads, ping deadlines, the governor tick,
and mining for its full duration — the sim can't see it (the virtual
clock doesn't advance during host-side blocking), so soaks meet it
only as unexplained tail latency.

This rule is also deliberately a MAP: the grants it forces are the
audited inventory of host-blocking work still running on the loop —
exactly the work list ROADMAP item 5's stage split (wire framing →
admission → validation → store → relay, with worker processes for the
CPU/IO-heavy stages) has to move off-thread.  A grant here is a known
debt with a written reason, not a blessing.

Flagged — direct calls lexically inside an ``async def`` body (a
nested ``def``/``lambda`` resets the context: its body runs whenever
it is CALLED, which ``asyncio.to_thread``/executors do off-loop):

- ``time.sleep`` (the loop-stalling sleep; ``asyncio.sleep`` is the
  loop-relative spelling and belongs to the wall-clock rule's domain);
- builtin ``open`` (sync file IO on the loop);
- ``os.fsync`` / ``os.fdatasync`` / ``os.sync`` (durability barriers —
  milliseconds to SECONDS on a busy disk);
- anything on the ``subprocess`` module (blocking process plumbing;
  ``asyncio.create_subprocess_*`` is the async spelling).

Indirect blocking (a sync helper that fsyncs inside, called from async
code) is beyond one-pass AST: the rule pins the direct class, the
grants document the known indirect sites.
"""

from __future__ import annotations

import ast
from typing import Iterator

from p1_tpu.analysis.base import Rule, dotted_name, register
from p1_tpu.analysis.findings import Finding

_BLOCKING_DOTTED = frozenset({"time.sleep", "os.fsync", "os.fdatasync", "os.sync"})


@register
class BlockingInAsyncRule(Rule):
    name = "blocking-in-async"
    title = "synchronous blocking call inside async def"
    scope = ()  # every async def in the package runs on SOME loop

    def check(self, tree: ast.Module, rel: str) -> Iterator[Finding]:
        yield from self._visit(tree, rel, in_async=False, fn="<module>")

    def _visit(
        self, node: ast.AST, rel: str, in_async: bool, fn: str
    ) -> Iterator[Finding]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.AsyncFunctionDef):
                yield from self._visit(child, rel, True, child.name)
                continue
            if isinstance(child, (ast.FunctionDef, ast.Lambda)):
                yield from self._visit(
                    child, rel, False, getattr(child, "name", "<lambda>")
                )
                continue
            if in_async and isinstance(child, ast.Call):
                hit = self._classify(child)
                if hit is not None:
                    yield self.finding(
                        rel,
                        child,
                        f"{hit} blocks the event loop inside async "
                        f"{fn}() — move it to a worker "
                        "(asyncio.to_thread / executor) or grant it as "
                        "acknowledged ROADMAP-5 debt",
                        hit,
                    )
            yield from self._visit(child, rel, in_async, fn)

    @staticmethod
    def _classify(call: ast.Call) -> str | None:
        dotted = dotted_name(call.func)
        if dotted is None:
            return None
        if dotted == "open":
            return "open"
        if dotted in _BLOCKING_DOTTED:
            return dotted
        if dotted.startswith("subprocess."):
            return "subprocess"
        return None
