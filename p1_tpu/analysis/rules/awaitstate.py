"""await-state: read → await → write on consensus state is a race.

Single-threaded asyncio removes data races but not INTERLEAVING races:
every ``await`` is a scheduling point where any other coroutine — a
frame handler accepting a block, the miner sealing one, a chaos crash
callback — may run and move the very state this coroutine read before
the await.  A method that reads a consensus attribute, awaits, and
then writes that attribute commits a decision computed against a
world that may no longer exist: the classic shape is the store-resume
path deciding ``self.chain`` from disk, awaiting IO, then installing
it over a tip that advanced meanwhile.  The chaos plane hunts this
class dynamically (crash/recover sweeps over the simulated mesh);
this rule pins it structurally.

Flagged: inside ONE ``async def``'s own control flow (nested defs
excluded — closures run on a different schedule), a Load of
``self.X``, then an ``await``, then a Store to ``self.X``, for X in
the consensus-state watchlist: ``chain`` (tip/fork-choice), ``ledger``,
``store``, ``mempool``.  The finding anchors at the write — the line
where the stale decision lands.

A grant asserts one of the safe shapes, with the reason written down:
the method re-validates after the await before writing; it runs only
before the node serves (start-up) or after it stops; or it is the
SOLE writer and readers tolerate the swap.
"""

from __future__ import annotations

import ast
from typing import Iterator

from p1_tpu.analysis.base import Rule, register, sort_key, walk_no_nested_defs
from p1_tpu.analysis.findings import Finding

#: Consensus-state attributes (on self) whose cross-await read/write
#: interleavings the chaos sweeps hunt dynamically.
WATCHED = frozenset({"chain", "ledger", "store", "mempool"})


@register
class AwaitStateRule(Rule):
    name = "await-state"
    title = "consensus attribute read, awaited past, then written"
    scope = ("node/",)  # where the consensus loop and its state live

    def check(self, tree: ast.Module, rel: str) -> Iterator[Finding]:
        for fn in ast.walk(tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            loads: dict[str, tuple[int, int]] = {}  # attr -> first load pos
            awaits: list[tuple[int, int]] = []
            flagged: set[str] = set()
            for node in sorted(walk_no_nested_defs(fn), key=sort_key):
                if isinstance(node, ast.Await):
                    awaits.append(sort_key(node))
                elif (
                    isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and node.attr in WATCHED
                ):
                    pos = sort_key(node)
                    if isinstance(node.ctx, ast.Load):
                        loads.setdefault(node.attr, pos)
                    elif isinstance(node.ctx, ast.Store):
                        first = loads.get(node.attr)
                        if (
                            node.attr not in flagged
                            and first is not None
                            and any(first < a < pos for a in awaits)
                        ):
                            flagged.add(node.attr)
                            yield self.finding(
                                rel,
                                node,
                                f"self.{node.attr} read before an await "
                                f"and written after it in {fn.name}() — "
                                "the world may have moved at the "
                                "scheduling point; re-validate before "
                                "writing or grant with the safety "
                                "argument",
                                node.attr,
                            )
                        loads.pop(node.attr, None)
