"""The rule set.  Importing this package registers every rule; the
canonical list is what lives here — docs/LINT.md catalogs each rule's
definition, the historical bug or ROADMAP item that motivates it, and
how to grant an exception."""

from p1_tpu.analysis.rules import (  # noqa: F401  (registration side effect)
    awaitstate,
    blocking,
    escstate,
    losttask,
    rng,
    setiter,
    transblock,
    wallclock,
    wirecontract,
)
