"""lost-task: every spawned task handle must be held by SOMETHING.

``asyncio.create_task`` detaches a coroutine from the spawning control
flow; if the handle is neither stored, awaited, nor given a done
callback, an exception inside it is observed by NOBODY until the
garbage collector happens to log "Task exception was never retrieved"
— or never, if the loop dies first.  Round 3's review found exactly
this shape killing the store-recovery loop: the task died silently and
the node sat degraded forever, because ``_store_fail`` early-returns
once degraded and nothing else respawns the loop.  The fix
(``_spawn_store_recovery`` + ``_store_recovery_done``: log, then
respawn while still degraded) is the house pattern this rule points
grants and fixes at.

Flagged:

- a ``create_task``/``ensure_future`` call whose value is discarded
  (a bare expression statement);
- a handle assigned to a local name that the enclosing function never
  mentions again — morally identical to discarding it, one rename away
  from looking supervised.

Not flagged (the handle IS held): awaited; stored into an attribute,
subscript, or container; passed as an argument; assigned to a name the
function later uses (cancel/await/add_done_callback/bookkeeping).
Whether the holder then OBSERVES a failure is beyond the AST — the
audit that accompanies each grant, and the regression tests in
tests/test_node.py / tests/test_queryplane.py, carry that half.
"""

from __future__ import annotations

import ast
from typing import Iterator

from p1_tpu.analysis.base import (
    Rule,
    dotted_name,
    enclosing_scope,
    parent_map,
    register,
    scope_name,
)
from p1_tpu.analysis.findings import Finding

_SPAWNERS = ("create_task", "ensure_future")


@register
class LostTaskRule(Rule):
    name = "lost-task"
    title = "spawned task handle neither stored, awaited, nor callback'd"
    scope = ()  # the whole package: a lost task is a bug anywhere

    def check(self, tree: ast.Module, rel: str) -> Iterator[Finding]:
        parents = parent_map(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = dotted_name(node.func)
            if dotted is None or dotted.rsplit(".", 1)[-1] not in _SPAWNERS:
                continue
            parent = parents.get(node)
            scope = enclosing_scope(node, parents)
            key = scope_name(scope)
            if isinstance(parent, ast.Expr):
                yield self.finding(
                    rel,
                    node,
                    f"{dotted}(...) handle discarded in {key}() — store "
                    "it, await it, or attach a done callback that logs "
                    "and recovers",
                    key,
                )
            elif (
                isinstance(parent, ast.Assign)
                and len(parent.targets) == 1
                and isinstance(parent.targets[0], ast.Name)
                and not _name_used_elsewhere(
                    scope, parent.targets[0].id, parent.targets[0]
                )
            ):
                yield self.finding(
                    rel,
                    node,
                    f"{dotted}(...) handle bound to "
                    f"{parent.targets[0].id!r} in {key}() but never used "
                    "— the task can die unobserved",
                    key,
                )


def _name_used_elsewhere(scope: ast.AST, name: str, binding: ast.Name) -> bool:
    for node in ast.walk(scope):
        if isinstance(node, ast.Name) and node.id == name and node is not binding:
            return True
    return False
