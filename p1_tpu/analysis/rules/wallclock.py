"""wall-clock: no host-clock reads or sleeps off the transport seam.

The transport seam (node/transport.py) exists so every clock read in
the node goes through an injectable ``Clock`` and every sleep/deadline
through the event loop — which is what lets node/netsim.py virtualize a
thousand nodes deterministically.  One ``time.time()`` in a consensus
or session path silently re-couples the node to the host clock: the
sim still RUNS, but deadlines stop scaling with virtual time and
same-seed traces drift.  Round 11 hit the worst version — a codec-side
``time.time()`` default INSIDE frame bytes made simulated flood traces
nondeterministic — and the tokenizer lint this rule replaces caught it.

``asyncio.sleep`` / ``asyncio.wait_for`` are loop-relative — the
simulator virtualizes the loop itself, so they are sim-compatible BY
CONSTRUCTION and allowed wherever async code runs under the node's
loop.  They are still matched and granted per file: a *new* module
acquiring sleeps is worth a deliberate allowlist edit (is this file
really always run under the virtual loop?), not a silent pass.

Structural, not textual: only ``ast.Call`` nodes count, so an
injectable-clock DEFAULT argument (``clock=time.monotonic``) or a
callable passed through (``clock=self.clock.monotonic``) is clean
without a grant — the old scanner got this by re-joining tokens and
substring-matching, which also mis-hit names merely *ending* in a
pattern.  Grant keys are the dotted callable without parentheses.
"""

from __future__ import annotations

import ast
from typing import Iterator

from p1_tpu.analysis.base import Rule, call_matches, dotted_name, register
from p1_tpu.analysis.findings import Finding

#: Dotted callables that read the HOST clock (or sleep).
#: ``datetime.now`` matches both ``datetime.now(...)`` and
#: ``datetime.datetime.now(...)`` via dot-boundary suffix matching.
PATTERNS = (
    "time.time",
    "time.monotonic",
    "time.perf_counter",
    "datetime.now",
    "asyncio.sleep",
)


@register
class WallClockRule(Rule):
    name = "wall-clock"
    title = "host clock reads/sleeps outside the transport seam"
    #: The simulator-covered product tree — same coverage the tokenizer
    #: lint enforced (mempool/ joined in round 11: pool stamps and TTL
    #: ages ride the node's injected clock so chaos schedules see
    #: deterministic checkpoint ages).
    scope = ("node/", "chain/", "mempool/")

    def check(self, tree: ast.Module, rel: str) -> Iterator[Finding]:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = dotted_name(node.func)
            for pat in PATTERNS:
                if call_matches(dotted, pat):
                    yield self.finding(
                        rel,
                        node,
                        f"{dotted}() reads the host clock off the seam — "
                        "route it through the injected Clock (or grant "
                        "with a reason)",
                        pat,
                    )
                    break
