"""wire-contract: the frame registry is exhaustively classified.

The protocol surface is 34 frame types across wire v15, and every one
must thread SEVEN independent tables/switches, written in three files:
an encoder (node/protocol.py ``encode_*``), a decoder arm
(``_decode``), a ``_dispatch`` arm (node/node.py), an admission
classification (``_MSG_CLASS`` charge class or the explicit
``_ADMISSION_EXEMPT`` free list — node/governor.py's token buckets
only see what the table names), a SHED keep/drop decision
(``_SHED_DROPS`` / ``_SHED_KEEPS``), a version gate
(``MSG_SINCE``: the wire version that introduced it, ≤
``PROTOCOL_VERSION``), and — round 23 — a relay-byte accounting
family (``_RELAY_ACCOUNTING``: which ``relay.bytes.*`` counter the
frame's egress lands in; an unaccounted frame is bandwidth the
propagation budget can't see, which is exactly the blind spot a
bandwidth-scale relay exists to close).  The historical failure class
is real: rounds
9–12 each added frame pairs, and "the new frame forgot its
shed/admission classification" survives review precisely because the
omission is INVISIBLE — an unclassified frame silently rides the
default (uncharged, never shed), which is the most permissive
possible reading of a hostile peer's bytes.

This package rule cross-checks the whole surface structurally: it
finds the ``MsgType`` enum, then collects every ``MsgType.X``
reference inside each registry — no imports, no execution — and emits
one finding per hole or contradiction, keyed ``"MEMBER:aspect"``
(``"SNAPSHOT:shed"``), anchored at the member's line in the enum so
the fix starts from the declaration.  Aspects: ``encoder``,
``decoder``, ``dispatch``, ``admission`` (missing from both tables,
or — ``admission-both`` — named in both), ``shed`` /``shed-both``,
``version`` / ``version-future`` (``MSG_SINCE`` entry missing, or
claiming a version newer than ``PROTOCOL_VERSION``), and ``relay``
(no ``_RELAY_ACCOUNTING`` family).

Grants here should be RARE and temporary (a frame mid-introduction
across a stacked PR); the steady state is zero.  The import-time
asserts beside ``_MSG_CLASS``/``_SHED_DROPS`` enforce the
admission/shed halves at runtime too — the rule's extra value is the
encoder/decoder/dispatch/version coverage asserts can't see, and
failing BEFORE the code ever runs.
"""

from __future__ import annotations

import ast
from typing import Iterator

from p1_tpu.analysis.base import Rule, dotted_name, register
from p1_tpu.analysis.findings import Finding

_ENUM_BASES = {"IntEnum", "Enum", "enum.IntEnum", "enum.Enum"}


def _msgtype_refs(node: ast.AST) -> set[str]:
    """Every ``MsgType.X`` attribute reference under ``node``."""
    out = set()
    for sub in ast.walk(node):
        if (
            isinstance(sub, ast.Attribute)
            and isinstance(sub.value, ast.Name)
            and sub.value.id == "MsgType"
        ):
            out.add(sub.attr)
    return out


@register
class WireContractRule(Rule):
    name = "wire-contract"
    title = "frame type missing an encoder/decoder/dispatch/admission/shed/version entry"
    scope = ()  # cross-file by nature; anchors land in node/
    package_rule = True

    def check_package(self, pkg) -> Iterator[Finding]:
        members: dict[str, int] = {}  # name -> lineno
        enum_rel = None
        protocol_version: int | None = None
        encoders: set[str] = set()
        decoders: set[str] = set()
        dispatch: set[str] = set()
        msg_class: set[str] = set()
        exempt: set[str] = set()
        shed_drops: set[str] = set()
        shed_keeps: set[str] = set()
        relay_acct: set[str] = set()
        msg_since: dict[str, tuple[int | None, int]] = {}  # name -> (ver, line)
        have = {
            "_MSG_CLASS": False,
            "_ADMISSION_EXEMPT": False,
            "_SHED_DROPS": False,
            "_SHED_KEEPS": False,
            "_RELAY_ACCOUNTING": False,
            "MSG_SINCE": False,
            "_decode": False,
            "_dispatch": False,
        }

        for rel in sorted(pkg.trees):
            tree = pkg.trees[rel]
            for node in ast.walk(tree):
                if isinstance(node, ast.ClassDef) and node.name == "MsgType":
                    if any(
                        dotted_name(b) in _ENUM_BASES for b in node.bases
                    ):
                        enum_rel = rel
                        for stmt in node.body:
                            if (
                                isinstance(stmt, ast.Assign)
                                and len(stmt.targets) == 1
                                and isinstance(stmt.targets[0], ast.Name)
                            ):
                                members[stmt.targets[0].id] = stmt.lineno
                elif isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    if node.name.startswith("encode_"):
                        encoders |= _msgtype_refs(node)
                    elif node.name in ("_decode", "decode"):
                        have["_decode"] = True
                        decoders |= _msgtype_refs(node)
                    elif node.name == "_dispatch":
                        have["_dispatch"] = True
                        dispatch |= _msgtype_refs(node)
                elif (
                    isinstance(node, ast.Assign) and len(node.targets) == 1
                ) or (
                    isinstance(node, ast.AnnAssign) and node.value is not None
                ):
                    # Annotated module-level tables (``X: dict = {...}``)
                    # register the same as bare assignments — the relay
                    # table ships annotated, and a rule that only read
                    # ast.Assign would silently go inert on it.
                    tgt = (
                        node.target
                        if isinstance(node, ast.AnnAssign)
                        else node.targets[0]
                    )
                    if not isinstance(tgt, ast.Name):
                        continue
                    if tgt.id == "_MSG_CLASS":
                        have["_MSG_CLASS"] = True
                        msg_class |= _msgtype_refs(node.value)
                    elif tgt.id == "_ADMISSION_EXEMPT":
                        have["_ADMISSION_EXEMPT"] = True
                        exempt |= _msgtype_refs(node.value)
                    elif tgt.id == "_SHED_DROPS":
                        have["_SHED_DROPS"] = True
                        shed_drops |= _msgtype_refs(node.value)
                    elif tgt.id == "_SHED_KEEPS":
                        have["_SHED_KEEPS"] = True
                        shed_keeps |= _msgtype_refs(node.value)
                    elif tgt.id == "_RELAY_ACCOUNTING":
                        have["_RELAY_ACCOUNTING"] = True
                        relay_acct |= _msgtype_refs(node.value)
                    elif tgt.id == "MSG_SINCE":
                        have["MSG_SINCE"] = True
                        self._read_since(node.value, msg_since)
                    elif tgt.id == "PROTOCOL_VERSION" and isinstance(
                        node.value, ast.Constant
                    ):
                        if isinstance(node.value.value, int):
                            protocol_version = node.value.value

        if enum_rel is None or not members:
            return  # no wire surface in this index (fixture mini-packages)

        def finding(member: str, aspect: str, detail: str) -> Finding:
            return Finding(
                file=enum_rel,
                line=members.get(member, 0),
                rule=self.name,
                detail=detail,
                key=f"{member}:{aspect}",
            )

        for m in members:
            if m not in encoders:
                yield finding(
                    m,
                    "encoder",
                    f"MsgType.{m} has no encode_* function — every frame "
                    "type needs a canonical byte producer",
                )
            if have["_decode"] and m not in decoders:
                yield finding(
                    m,
                    "decoder",
                    f"MsgType.{m} has no _decode arm — peers sending it "
                    "get an 'unknown message' protocol error",
                )
            if have["_dispatch"] and m not in dispatch:
                yield finding(
                    m,
                    "dispatch",
                    f"MsgType.{m} has no _dispatch arm — a decoded frame "
                    "with nowhere to go",
                )
            if have["_MSG_CLASS"] and have["_ADMISSION_EXEMPT"]:
                if m not in msg_class and m not in exempt:
                    yield finding(
                        m,
                        "admission",
                        f"MsgType.{m} is in neither _MSG_CLASS nor "
                        "_ADMISSION_EXEMPT — unclassified traffic rides "
                        "free past the governor's budgets",
                    )
                elif m in msg_class and m in exempt:
                    yield finding(
                        m,
                        "admission-both",
                        f"MsgType.{m} is charged by _MSG_CLASS AND "
                        "exempted by _ADMISSION_EXEMPT — pick one",
                    )
            if have["_SHED_DROPS"] and have["_SHED_KEEPS"]:
                if m not in shed_drops and m not in shed_keeps:
                    yield finding(
                        m,
                        "shed",
                        f"MsgType.{m} has no SHED classification — say "
                        "explicitly whether an overloaded node drops or "
                        "serves it (_SHED_DROPS / _SHED_KEEPS)",
                    )
                elif m in shed_drops and m in shed_keeps:
                    yield finding(
                        m,
                        "shed-both",
                        f"MsgType.{m} is in _SHED_DROPS AND _SHED_KEEPS "
                        "— pick one",
                    )
            if have["_RELAY_ACCOUNTING"] and m not in relay_acct:
                yield finding(
                    m,
                    "relay",
                    f"MsgType.{m} has no _RELAY_ACCOUNTING family — "
                    "its egress is invisible to the relay.bytes.* "
                    "propagation budget",
                )
            if have["MSG_SINCE"]:
                since = msg_since.get(m)
                if since is None:
                    yield finding(
                        m,
                        "version",
                        f"MsgType.{m} has no MSG_SINCE entry — record "
                        "the wire version that introduced it",
                    )
                elif (
                    protocol_version is not None
                    and since[0] is not None
                    and since[0] > protocol_version
                ):
                    yield finding(
                        m,
                        "version-future",
                        f"MsgType.{m} claims wire v{since[0]} but "
                        f"PROTOCOL_VERSION is {protocol_version} — "
                        "bump the version with the frame",
                    )
        # dangling entries: registry rows for members the enum lost
        for name, (_, line) in sorted(msg_since.items()):
            if name not in members:
                yield Finding(
                    file=enum_rel,
                    line=line,
                    rule=self.name,
                    detail=(
                        f"MSG_SINCE names MsgType.{name} but the enum "
                        "has no such member — stale registry row"
                    ),
                    key=f"{name}:version-dangling",
                )

    @staticmethod
    def _read_since(
        value: ast.AST, out: dict[str, tuple[int | None, int]]
    ) -> None:
        if not isinstance(value, ast.Dict):
            return
        for k, v in zip(value.keys, value.values):
            if (
                isinstance(k, ast.Attribute)
                and isinstance(k.value, ast.Name)
                and k.value.id == "MsgType"
            ):
                ver = (
                    v.value
                    if isinstance(v, ast.Constant)
                    and isinstance(v.value, int)
                    else None
                )
                out[k.attr] = (ver, k.lineno)
