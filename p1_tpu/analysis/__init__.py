"""Determinism & async-safety static analysis (tier-1 enforced).

The last several rounds each found a protocol/recovery bug *dynamically*
— a dead store-recovery loop whose task died silently (round 3 review),
set→dict ordering fixes required for byte-identical sim traces (round
7), a host-clock read inside frame bytes that made simulated flood
traces nondeterministic (round 11) — and the one static check the tree
had (the tokenizer wall-clock lint in tests/test_simlint.py) had
already caught one of those classes at commit time.  This package
promotes that from one grep into a multi-pass AST analyzer in the
sanitizer/race-detector lineage: find the bug CLASS, not the bug
instance, and pin it so refactors can't silently reintroduce it.

Two properties are load-bearing and generalized from the original lint:

- **anything not granted fails** — a new file acquiring a flagged
  construct is a deliberate allowlist edit with a written reason, not a
  silent pass;
- **any grant nothing uses fails** — stale grants rot into blanket
  permissions, so the engine reports them as violations too.

Entry points: ``run_analysis()`` (the whole package, every rule),
``p1 lint`` (CLI wrapper, exit 0 clean / 1 findings / 2 usage), and the
tier-1 test ``tests/test_analysis.py`` that keeps the tree clean.
"""

from __future__ import annotations

from p1_tpu.analysis.base import RULES, Rule, register
from p1_tpu.analysis.engine import PKG_ROOT, Report, package_files, run_analysis
from p1_tpu.analysis.findings import Finding

# Importing the rules package populates the registry as a side effect —
# the canonical rule set IS "whatever p1_tpu.analysis.rules defines".
from p1_tpu.analysis import rules as _rules  # noqa: F401  (registry load)

__all__ = [
    "Finding",
    "Report",
    "Rule",
    "RULES",
    "PKG_ROOT",
    "package_files",
    "register",
    "run_analysis",
]
