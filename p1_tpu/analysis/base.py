"""Rule protocol, registry, and the shared AST helpers every rule uses.

A rule is one *bug class* with one structural definition: it walks a
parsed module and yields ``Finding`` objects.  Rules declare a
``scope`` — path prefixes (relative to the package root) they apply to
— because the invariants are domain invariants, not universal style:
wall-clock reads are fine in ``hashx/`` benchmark code and fatal in
``node/`` consensus code.  The scope is part of the rule's definition
and documented per rule in docs/LINT.md.
"""

from __future__ import annotations

import ast
from typing import Iterator

from p1_tpu.analysis.findings import Finding

#: name -> rule instance.  Populated by @register at import time
#: (p1_tpu/analysis/rules/__init__.py imports every rule module).
RULES: dict[str, "Rule"] = {}


def register(cls: type["Rule"]) -> type["Rule"]:
    rule = cls()
    if rule.name in RULES:  # duplicate registration = a packaging bug
        raise ValueError(f"duplicate rule name {rule.name!r}")
    RULES[rule.name] = rule
    return cls


class Rule:
    """One structural invariant.  Subclasses set the class attributes
    and implement ``check``; the engine handles scoping, allowlists,
    and stale-grant accounting uniformly.

    Two kinds of rule share this protocol (round 16): a *per-file*
    rule implements ``check`` and sees one parsed module at a time; a
    *package* rule sets ``package_rule = True``, implements
    ``check_package``, and sees the whole-package index (every tree
    plus the call graph) ONCE per run — the interprocedural rules
    (transitive-blocking, escaped-state, wire-contract) live there.
    Findings from both settle against the allowlist identically."""

    #: Registry/allowlist/CLI name, kebab-case ("wall-clock").
    name: str = ""
    #: One-line summary for `p1 lint --json` and docs.
    title: str = ""
    #: Path prefixes (POSIX, relative to p1_tpu/) the rule covers.
    #: Empty tuple = the whole package.
    scope: tuple[str, ...] = ()
    #: True = the rule runs once over the PackageIndex, not per file.
    package_rule: bool = False

    def applies_to(self, rel: str) -> bool:
        return not self.scope or rel.startswith(self.scope)

    def check(self, tree: ast.Module, rel: str) -> Iterator[Finding]:
        raise NotImplementedError

    def check_package(self, pkg) -> Iterator[Finding]:
        """Package rules override this; ``pkg`` is the engine's
        PackageIndex (``.trees``, ``.graph``)."""
        raise NotImplementedError

    def finding(self, rel: str, node: ast.AST, detail: str, key: str) -> Finding:
        return Finding(
            file=rel,
            line=getattr(node, "lineno", 0),
            rule=self.name,
            detail=detail,
            key=key,
        )


# -- AST helpers ---------------------------------------------------------


def dotted_name(node: ast.AST) -> str | None:
    """The dotted spelling of a call target, or None when any link is
    not a plain name/attribute chain.  A call in the chain contributes
    ``()``: ``asyncio.get_running_loop().create_task`` — so suffix
    matching still sees the module and the method."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return None if base is None else f"{base}.{node.attr}"
    if isinstance(node, ast.Call):
        base = dotted_name(node.func)
        return None if base is None else f"{base}()"
    return None


def call_matches(dotted: str | None, pattern: str) -> bool:
    """True when ``dotted`` IS ``pattern`` or ends with ``.pattern`` on
    a dot boundary — so ``datetime.datetime.now`` matches the pattern
    ``datetime.now`` while ``self.clock.time`` does not match
    ``time.time`` (the token-join scanner this replaces got that right
    only by accident of spelling)."""
    return dotted is not None and (
        dotted == pattern or dotted.endswith("." + pattern)
    )


def parent_map(tree: ast.AST) -> dict[ast.AST, ast.AST]:
    return {
        child: parent
        for parent in ast.walk(tree)
        for child in ast.iter_child_nodes(parent)
    }


def enclosing_scope(
    node: ast.AST, parents: dict[ast.AST, ast.AST]
) -> ast.AST:
    """Nearest enclosing function (or the module): the region in which
    a local name binding is visible."""
    cur: ast.AST | None = parents.get(node)
    while cur is not None:
        if isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.Module)
        ):
            return cur
        cur = parents.get(cur)
    return node


def scope_name(node: ast.AST) -> str:
    return getattr(node, "name", "<module>")


def walk_no_nested_defs(node: ast.AST) -> Iterator[ast.AST]:
    """Yield descendants of ``node`` WITHOUT descending into nested
    function/class bodies: the statements that execute as part of this
    function's own control flow.  (A task spawned here but awaited in a
    nested closure runs on a different schedule entirely — rules about
    sequential read/await/write hazards must not conflate the two.)"""
    for child in ast.iter_child_nodes(node):
        yield child
        if isinstance(
            child,
            (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef),
        ):
            continue
        yield from walk_no_nested_defs(child)


def sort_key(node: ast.AST) -> tuple[int, int]:
    return (getattr(node, "lineno", 0), getattr(node, "col_offset", 0))


_SET_METHODS = frozenset(
    {"difference", "union", "intersection", "symmetric_difference"}
)
_SET_OPS = (ast.Sub, ast.BitOr, ast.BitAnd, ast.BitXor)


def is_set_expr(node: ast.AST) -> bool:
    """True when ``node`` is structurally a set: a literal/comprehension,
    a ``set()``/``frozenset()`` call, a set-method call, or a set
    operator over such operands (or ``.keys()`` views — the "dict-keys
    difference" shape).  Shared by the set-iteration rule and the call
    graph's local-binding summaries so the direct and one-hop layers
    agree on what a set is."""
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        dotted = dotted_name(node.func)
        if dotted in ("set", "frozenset"):
            return True
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _SET_METHODS
        ):
            return True
    if isinstance(node, ast.BinOp) and isinstance(node.op, _SET_OPS):
        return (
            is_set_expr(node.left)
            or is_set_expr(node.right)
            or is_keys_view(node.left)
            or is_keys_view(node.right)
        )
    return False


def is_keys_view(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "keys"
        and not node.args
    )
