"""Drive the rules over the package and settle findings against grants.

One parse per file, every in-scope rule over the shared tree (the
"multi-pass" is rule passes, not re-parses — the whole-package run
stays well under the ~5 s tier-1 budget on a 1-vCPU host).  Since
round 16 the parsed trees are kept for the run as a ``PackageIndex``
and a whole-package ``CallGraph`` is built over them once: *package
rules* (``Rule.package_rule``) see that index and check
interprocedural invariants no single tree can express.

Settlement semantics (both directions enforced, both inherited from the
original wall-clock lint):

- a finding whose ``(rule, file, key)`` appears in the allowlist is
  *granted* — suppressed from ``violations`` but recorded as having
  consumed its grant;
- a grant no finding consumed is *stale* and reported as a violation in
  its own right: an allowlist entry that outlives its construct is a
  blanket permission waiting for the next regression to hide under.

Scoped runs (``p1 lint --path``): ``paths`` narrows which files'
findings are REPORTED, but the analysis itself always covers the
whole package — the call graph is interprocedural, so a partial parse
would silently weaken every package rule — and settlement stays
global: grant consumption is computed from ALL findings and stale
grants anywhere still fail, so a scoped run can narrow what you look
at without hiding a rotting grant.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from functools import cached_property
from pathlib import Path
from typing import Iterable, Iterator

from p1_tpu.analysis.base import RULES, Rule
from p1_tpu.analysis.callgraph import CallGraph
from p1_tpu.analysis.findings import Finding

#: The analyzed package root (p1_tpu/).
PKG_ROOT = Path(__file__).resolve().parent.parent


def package_files(root: Path = PKG_ROOT) -> Iterator[tuple[str, Path]]:
    """Every Python source in the package as (rel, path), sorted so
    reports and grant settlement are order-stable."""
    for path in sorted(root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        yield path.relative_to(root).as_posix(), path


@dataclass
class PackageIndex:
    """The parsed package a run shares across rules: rel -> tree, plus
    the call graph built lazily on first package-rule access (a
    per-file-only run never pays for it)."""

    trees: dict[str, ast.Module]

    @cached_property
    def graph(self) -> CallGraph:
        return CallGraph(self.trees)


@dataclass
class Report:
    """One analysis run.  ``clean`` is the tier-1 gate: no unallowlisted
    findings AND no stale grants."""

    findings: list[Finding] = field(default_factory=list)  # everything emitted
    violations: list[Finding] = field(default_factory=list)  # not granted
    granted: list[Finding] = field(default_factory=list)  # grant-suppressed
    stale: list[str] = field(default_factory=list)  # grants nothing used
    parse_errors: list[str] = field(default_factory=list)
    files: int = 0
    rules: list[str] = field(default_factory=list)
    #: call-graph size when a package rule ran (bench.py emits these so
    #: analysis-cost creep is visible round over round); 0 = not built.
    callgraph_nodes: int = 0
    callgraph_edges: int = 0
    #: the --path scope of this run, empty = whole package.
    scoped_to: list[str] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.violations and not self.stale and not self.parse_errors

    def to_json(self) -> dict:
        return {
            "files": self.files,
            "rules": self.rules,
            "clean": self.clean,
            "violations": [vars(f) for f in self.violations],
            "granted": [vars(f) for f in self.granted],
            "stale": self.stale,
            "parse_errors": self.parse_errors,
            "callgraph_nodes": self.callgraph_nodes,
            "callgraph_edges": self.callgraph_edges,
            "scoped_to": self.scoped_to,
        }


def _in_scope(rel: str, paths: list[str] | None) -> bool:
    if not paths:
        return True
    return any(
        rel == p or (p.endswith("/") and rel.startswith(p)) for p in paths
    )


def run_analysis(
    root: Path = PKG_ROOT,
    rules: Iterable[Rule] | None = None,
    grants: dict[str, dict[str, dict[str, str]]] | None = None,
    paths: list[str] | None = None,
) -> Report:
    """Run ``rules`` (default: the full registry) over every module
    under ``root`` and settle against ``grants`` (default: the audited
    allowlist in p1_tpu/analysis/allowlist.py).  ``paths`` (package-
    relative files like "node/node.py" or dir prefixes like "node/")
    scopes which files' findings are reported — see the module
    docstring for what stays global."""
    if grants is None:
        from p1_tpu.analysis.allowlist import GRANTS

        grants = GRANTS
    active = list(RULES.values()) if rules is None else list(rules)
    report = Report(
        rules=[r.name for r in active], scoped_to=sorted(paths or [])
    )
    used: set[tuple[str, str, str]] = set()

    trees: dict[str, ast.Module] = {}
    for rel, path in package_files(root):
        report.files += 1
        try:
            trees[rel] = ast.parse(path.read_bytes(), filename=rel)
        except SyntaxError as e:  # a file ast can't read is a finding, not a skip
            report.parse_errors.append(f"{rel}: {e.msg} (line {e.lineno})")
    pkg = PackageIndex(trees=trees)

    def settle(f: Finding) -> None:
        # Grant consumption is GLOBAL: every finding — in scope or not —
        # marks its grant used, so a scoped run settles the stale-grant
        # direction exactly like a full run.  Only the REPORTED lists
        # (findings/violations/granted) honor the scope.
        granted = f.key in grants.get(f.rule, {}).get(f.file, {})
        if granted:
            used.add((f.rule, f.file, f.key))
        if not _in_scope(f.file, paths):
            return
        report.findings.append(f)
        (report.granted if granted else report.violations).append(f)

    for rule in active:
        if rule.package_rule:
            for f in rule.check_package(pkg):
                settle(f)
            continue
        for rel, tree in trees.items():
            if not rule.applies_to(rel):
                continue
            for f in rule.check(tree, rel):
                settle(f)

    if any(r.package_rule for r in active):
        report.callgraph_nodes = len(pkg.graph.nodes)
        report.callgraph_edges = pkg.graph.edges

    active_names = {r.name for r in active}
    known = {rel for rel, _ in package_files(root)}
    for rule_name, by_file in sorted(grants.items()):
        if rule_name not in RULES:
            # A grant under a name the registry doesn't know is stale by
            # definition — reported even on partial runs, or a renamed
            # rule would orphan its whole grant table silently.
            if by_file:
                report.stale.append(f"{rule_name}: no such rule")
            continue
        if rule_name not in active_names:
            continue  # a partial run must not misreport other rules' grants
        for rel, keys in sorted(by_file.items()):
            if rel not in known:
                report.stale.append(f"{rule_name}: {rel}: file no longer exists")
                continue
            for key in sorted(keys):
                if (rule_name, rel, key) not in used:
                    report.stale.append(
                        f"{rule_name}: {rel}: grant {key!r} never used"
                    )

    report.findings.sort()
    report.violations.sort()
    report.granted.sort()
    return report
